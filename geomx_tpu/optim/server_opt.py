"""Server-side optimizers.

The reference runs the optimizer inside the *global server* as a pickled
python updater distributed by the master worker (ref:
python/mxnet/kvstore.py:452-499 set_optimizer → kController command;
kvstore_dist_server.h:542-545 exec_.Exec(updater_)).  We keep the same
architecture: optimizers are small host-side state machines applied per
ps-key slab, constructed from a plain config dict so the master worker can
ship them over the command channel.

Includes DCASGD (delay-compensated async SGD) which the reference pairs
with MixedSync (ref: python/mxnet/optimizer/optimizer.py class DCASGD;
README.md:38).

Numerics run through numpy on the host: these slabs live on the server
processes, not on TPU — the TPU path is the worker's jit-compiled train
step.  (Server-side slab math is memory-bandwidth-bound elementwise work;
numpy is the right tool on a host CPU.)
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ServerOptimizer:
    """Base: per-key state, elementwise update of a flat slab."""

    def __init__(self, lr: float = 0.01, wd: float = 0.0):
        self.lr = lr
        self.wd = wd
        self.state: Dict[int, dict] = {}

    def update(self, key: int, weight: np.ndarray, grad: np.ndarray) -> np.ndarray:
        """Return the NEW weight array.  Contract: ``weight`` may be a
        frozen (``writeable=False``) array aliased by in-flight pull
        responses — implementations must never write it in place (numpy
        would raise); build the result functionally or in ``grad``."""
        raise NotImplementedError

    def update_scaled(self, key: int, weight: np.ndarray,
                      grad_accum: np.ndarray, scale: float) -> np.ndarray:
        """Update with a pre-scale folded in: semantically
        ``update(key, weight, grad_accum * scale)``, but ``grad_accum``
        is CALLER-DONATED — the optimizer may mutate or adopt it.  The
        server's round-completion path passes its own aggregation buffer
        here (it is discarded right after), which lets the big-tensor
        regime skip the ``accum / num_contributors`` temporary plus the
        result allocation: for plain SGD the whole update is two in-place
        passes over HBM instead of ~6 passes + 3 × tensor-size allocs
        (measured 3.7 s → 0.25 s on a 200 MB slab)."""
        if scale != 1.0:
            np.multiply(grad_accum, scale, out=grad_accum)
        return self.update(key, weight, grad_accum)

    def _st(self, key: int, init) -> dict:
        st = self.state.get(key)
        if st is None:
            st = init()
            self.state[key] = st
        return st


class Sgd(ServerOptimizer):
    def __init__(self, lr: float = 0.01, momentum: float = 0.0, wd: float = 0.0):
        super().__init__(lr, wd)
        self.momentum = momentum

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        if self.momentum > 0.0:
            st = self._st(key, lambda: {"mom": np.zeros_like(weight)})
            st["mom"] = self.momentum * st["mom"] - self.lr * g
            return weight + st["mom"]
        return weight - self.lr * g

    def update_scaled(self, key, weight, grad_accum, scale):
        if self.momentum == 0.0 and self.wd == 0.0:
            # new_w = weight - lr*scale*accum, built in the donated
            # buffer: two in-place passes, zero allocations
            np.multiply(grad_accum, -self.lr * scale, out=grad_accum)
            grad_accum += weight
            return grad_accum
        return super().update_scaled(key, weight, grad_accum, scale)


class Adam(ServerOptimizer):
    def __init__(self, lr: float = 0.01, beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, wd: float = 0.0):
        super().__init__(lr, wd)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {
            "m": np.zeros_like(weight), "v": np.zeros_like(weight), "t": 0,
        })
        st["t"] += 1
        st["m"] = self.beta1 * st["m"] + (1 - self.beta1) * g
        st["v"] = self.beta2 * st["v"] + (1 - self.beta2) * g * g
        mhat = st["m"] / (1 - self.beta1 ** st["t"])
        vhat = st["v"] / (1 - self.beta2 ** st["t"])
        return weight - self.lr * mhat / (np.sqrt(vhat) + self.eps)


class DCASGD(ServerOptimizer):
    """Delay-Compensated ASGD for the async global tier (MixedSync).

    w ← w − lr·(g + λ·g⊙g⊙(w − w_prev_for_this_sender)) where w_prev is the
    weight snapshot this sender last pulled (per-sender backup, mirroring
    the reference's per-worker previous-weight bookkeeping).
    """

    def __init__(self, lr: float = 0.01, lamda: float = 0.04, wd: float = 0.0):
        super().__init__(lr, wd)
        self.lamda = lamda

    def update(self, key, weight, grad, sender: Optional[str] = None):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {"prev": {}})
        prev = st["prev"].get(sender)
        if prev is None:
            prev = weight.copy()
        comp = g + self.lamda * g * g * (weight - prev)
        new_w = weight - self.lr * comp
        st["prev"][sender] = new_w.copy()
        return new_w


class Nag(ServerOptimizer):
    """Nesterov accelerated SGD (ref: python/mxnet/optimizer/optimizer.py
    class NAG)."""

    def __init__(self, lr: float = 0.01, momentum: float = 0.9,
                 wd: float = 0.0):
        super().__init__(lr, wd)
        self.momentum = momentum

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {"mom": np.zeros_like(weight)})
        st["mom"] = self.momentum * st["mom"] + g
        return weight - self.lr * (g + self.momentum * st["mom"])


class RmsProp(ServerOptimizer):
    """RMSProp (ref: optimizer.py class RMSProp, non-centered)."""

    def __init__(self, lr: float = 0.01, rho: float = 0.9, eps: float = 1e-8,
                 wd: float = 0.0):
        super().__init__(lr, wd)
        self.rho, self.eps = rho, eps

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {"v": np.zeros_like(weight)})
        st["v"] = self.rho * st["v"] + (1 - self.rho) * g * g
        return weight - self.lr * g / (np.sqrt(st["v"]) + self.eps)


class AdaGrad(ServerOptimizer):
    """AdaGrad (ref: optimizer.py class AdaGrad)."""

    def __init__(self, lr: float = 0.01, eps: float = 1e-7, wd: float = 0.0):
        super().__init__(lr, wd)
        self.eps = eps

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {"h": np.zeros_like(weight)})
        st["h"] += g * g
        return weight - self.lr * g / (np.sqrt(st["h"]) + self.eps)


class AdaDelta(ServerOptimizer):
    """AdaDelta (ref: optimizer.py class AdaDelta) — no base lr."""

    def __init__(self, lr: float = 1.0, rho: float = 0.9, eps: float = 1e-5,
                 wd: float = 0.0):
        super().__init__(lr, wd)
        self.rho, self.eps = rho, eps

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        st = self._st(key, lambda: {"acc_g": np.zeros_like(weight),
                                    "acc_d": np.zeros_like(weight)})
        st["acc_g"] = self.rho * st["acc_g"] + (1 - self.rho) * g * g
        d = (np.sqrt(st["acc_d"] + self.eps)
             / np.sqrt(st["acc_g"] + self.eps)) * g
        st["acc_d"] = self.rho * st["acc_d"] + (1 - self.rho) * d * d
        return weight - self.lr * d


class Signum(ServerOptimizer):
    """Momentum-sign SGD (ref: optimizer.py class Signum) — a natural fit
    for WAN tiers: the update magnitude is bounded by lr regardless of
    gradient scale."""

    def __init__(self, lr: float = 0.01, momentum: float = 0.9,
                 wd: float = 0.0):
        super().__init__(lr, wd)
        self.momentum = momentum

    def update(self, key, weight, grad):
        g = grad + self.wd * weight
        if self.momentum > 0.0:
            st = self._st(key, lambda: {"mom": np.zeros_like(weight)})
            st["mom"] = self.momentum * st["mom"] + (1 - self.momentum) * g
            g = st["mom"]
        return weight - self.lr * np.sign(g)


_REGISTRY = {"sgd": Sgd, "adam": Adam, "dcasgd": DCASGD, "nag": Nag,
             "rmsprop": RmsProp, "adagrad": AdaGrad, "adadelta": AdaDelta,
             "signum": Signum}


def spec_of(opt: ServerOptimizer) -> Optional[dict]:
    """The plain config dict that would reconstruct ``opt`` (inverse of
    :func:`make_optimizer`, hyper-parameters only — per-key ``state``
    travels separately).  Used by the device-resident optimizer stage
    (kvstore/jax_backend.py) to rebuild the equivalent host optimizer
    for checkpoint/replication/handoff snapshots and to re-activate a
    device optimizer from a restored host one.  Returns None for types
    outside the registry (a custom subclass shipped over the command
    channel keeps its own pickle path)."""
    for name, cls in _REGISTRY.items():
        if type(opt) is cls:
            break
    else:
        return None
    spec = {"type": name, "lr": opt.lr, "wd": opt.wd}
    for attr in ("momentum", "beta1", "beta2", "eps", "lamda", "rho"):
        if hasattr(opt, attr):
            spec[attr] = getattr(opt, attr)
    return spec


def make_optimizer(config: dict) -> ServerOptimizer:
    """Build from a plain dict (shipped over the command channel), e.g.
    ``{"type": "adam", "lr": 0.01}``."""
    cfg = dict(config)
    typ = cfg.pop("type")
    try:
        cls = _REGISTRY[typ]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {typ!r}; choose from {sorted(_REGISTRY)}"
        ) from None
    return cls(**cfg)
