"""Self-healing serving plane (ISSUE 15): liveness-aware load
balancing, admission control with explicit load shedding, and replica
autoscaling.

Covers: p2c spread across replicas; fast failover off a dead target
(the per-attempt timeout regression — a SIGKILLed replica costs one
bounded failed read, not the caller's whole deadline); ejection after
consecutive errors + half-open probe recovery; the cluster-state view
skipping retired replicas; replica-side admission control (bounded
inflight budget, shed errors carrying RETRY_AFTER + depth, the
disabled path bit-for-bit legacy); shed → retry-elsewhere → success
through the balancer; batched PREDICT aggregation; the autoscaler's
hysteresis (scale-up under shedding, cooldown-suppressed reversals
counted as flaps, scale-down needing double patience) and its wire
retire/reactivate actuation; the serve_overload / replica_flap health
rules; and the churn orchestrator's replica kill/restart events.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.serve.client import ReplicaError


def _cfg(replicas=2, parties=1, **kw):
    kw.setdefault("serve_refresh_interval_s", 0.0)  # manual refresh()
    kw.setdefault("serve_staleness_s", 5.0)
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=1,
                                    num_replicas=replicas), **kw)


def _wait_for(pred, timeout=20.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _seed_model(sim, n=1000):
    w = sim.worker(0, 0)
    w.init(0, np.arange(n, dtype=np.float32))
    for rep in sim.replicas:
        assert rep.refresh()
    return w


def _pin_rng(lb):
    """Make the balancer's p2c pick deterministic: candidates in rank
    order, no jitter — the shed/failover tests need to know which
    replica the first attempt lands on."""
    lb._rng = SimpleNamespace(sample=lambda c, k: sorted(c)[:k],
                              uniform=lambda a, b: 0.0,
                              random=lambda: 0.0)


# ---------------------------------------------------------------------------
def test_balancer_p2c_spreads_load_across_replicas():
    sim = Simulation(_cfg(replicas=2))
    try:
        _seed_model(sim)
        lb = sim.serve_balancer(seed=7)
        for _ in range(30):
            arr, meta = lb.pull_tensor(0, 1000)
            assert np.array_equal(arr, np.arange(1000, dtype=np.float32))
            assert meta["replica"] in (0, 1)
        st = lb.stats()
        assert st["picks"] == 30 and st["failovers"] == 0
        # p2c with equal scores still lands on both replicas
        assert st["replicas"][0]["picks"] > 0
        assert st["replicas"][1]["picks"] > 0
    finally:
        sim.shutdown()


def test_balancer_fails_over_dead_replica_fast():
    """The PR 8 regression: a read whose chosen replica is dead must
    re-pick after ONE bounded attempt (serve_attempt_timeout_s), not
    burn the caller's whole timeout on the corpse."""
    sim = Simulation(_cfg(replicas=2, serve_attempt_timeout_s=0.5))
    try:
        _seed_model(sim)
        lb = sim.serve_balancer(seed=3)
        _pin_rng(lb)  # first pick = replica 0, deterministically
        lb.pull_tensor(0, 1000)
        sim.kill_replica(0)
        t0 = time.monotonic()
        arr, meta = lb.pull_tensor(0, 1000, timeout=10.0)
        dt = time.monotonic() - t0
        assert np.array_equal(arr, np.arange(1000, dtype=np.float32))
        assert meta["replica"] == 1
        # one failed 0.5s attempt + the live read — far under the 10s
        # deadline the old single-target client would have burned
        assert dt < 3.0, dt
        assert lb.stats()["failovers"] >= 1
    finally:
        sim.shutdown()


def test_balancer_ejects_dead_replica_and_half_open_recovers():
    sim = Simulation(_cfg(replicas=2, serve_attempt_timeout_s=0.3,
                          serve_eject_errors=2, serve_probe_s=0.4,
                          serve_lb_refresh_s=3600.0))
    try:
        _seed_model(sim)
        lb = sim.serve_balancer(seed=5)
        _pin_rng(lb)
        sim.kill_replica(0)
        # reads keep succeeding; replica 0 accumulates failures until
        # it is ejected from the candidate set
        for _ in range(3):
            _, meta = lb.pull_tensor(0, 1000, timeout=10.0)
            assert meta["replica"] == 1
        assert _wait_for(lambda: lb.stats()["replicas"][0]["ejected"],
                         timeout=1.0)
        assert lb.stats()["ejections"] >= 1
        # while ejected (probe not due), picks never land on 0
        picks0 = lb.stats()["replicas"][0]["picks"]
        for _ in range(5):
            lb.pull_tensor(0, 1000)
        assert lb.stats()["replicas"][0]["picks"] == picks0
        # revive replica 0; after serve_probe_s one half-open trial
        # runs and restores it
        rep2 = sim.restart_replica(0)
        assert _wait_for(lambda: rep2.refresh(), timeout=10.0)
        time.sleep(0.5)  # probe due
        for _ in range(10):
            lb.pull_tensor(0, 1000)
        st = lb.stats()
        assert st["probes"] >= 1 and st["recoveries"] >= 1
        assert not st["replicas"][0]["ejected"]
        assert st["replicas"][0]["picks"] > picks0
    finally:
        sim.shutdown()


def test_balancer_view_skips_retired_replica():
    """The cluster-state view (Ctrl.CLUSTER_STATE replica table) feeds
    the candidate set: a RETIRED replica is skipped without burning a
    probe on it."""
    sim = Simulation(_cfg(replicas=2, enable_obs=True,
                          obs_interval_s=0.0))
    try:
        _seed_model(sim)
        sim.replicas[0].set_active(False)
        sim.pump_metrics()
        lb = sim.serve_balancer(seed=1)
        assert lb.refresh_view()
        assert lb.candidates() == [1]
        for _ in range(5):
            _, meta = lb.pull_tensor(0, 1000)
            assert meta["replica"] == 1
        assert lb.stats()["sheds"] == 0  # never even asked replica 0
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
def test_admission_control_sheds_with_retry_after():
    """Past the inflight budget, a read is refused with an explicit
    RETRY_AFTER error carrying the suggested backoff and the current
    depth — never queued unboundedly."""
    sim = Simulation(_cfg(replicas=1, serve_max_inflight=2,
                          serve_staleness_s=0.3,
                          serve_retry_after_s=0.2))
    try:
        w = sim.worker(0, 0)
        w.init(0, np.arange(256, dtype=np.float32))
        rep = sim.replicas[0]
        assert rep.refresh()
        time.sleep(0.4)  # the copy ages past the bound: reads park
        clients = [sim.serve_client(0) for _ in range(3)]
        results = {}

        def read(i):
            try:
                results[i] = clients[i].pull_tensor(0, 256, timeout=20.0)
            except ReplicaError as e:
                results[i] = e

        threads = [threading.Thread(target=read, args=(i,), daemon=True)
                   for i in range(2)]
        for t in threads:
            t.start()
        assert _wait_for(lambda: len(rep._parked) == 2, timeout=5.0)
        # budget full (2 parked reads admitted): the third is shed NOW
        with pytest.raises(ReplicaError, match="RETRY_AFTER") as ei:
            clients[2].pull_tensor(0, 256, timeout=20.0)
        assert ei.value.shed
        assert ei.value.retry_after_s == pytest.approx(0.2)
        assert ei.value.body["inflight"] >= 2
        assert rep.serve_sheds == 1
        # the parked reads serve the moment a refresh lands
        assert rep.refresh()
        for t in threads:
            t.join(timeout=10.0)
        assert all(not isinstance(results[i], Exception)
                   for i in range(2))
        assert rep.stats()["inflight"] == 0
    finally:
        sim.shutdown()


def test_admission_disabled_path_is_legacy():
    """serve_max_inflight == 0 (the default): no shed path, no batch
    thread — overload behaves exactly like PR 8 (reads park)."""
    sim = Simulation(_cfg(replicas=1, serve_staleness_s=0.3))
    try:
        w = sim.worker(0, 0)
        w.init(0, np.arange(128, dtype=np.float32))
        rep = sim.replicas[0]
        assert rep.max_inflight == 0 and rep._batch_thread is None
        assert rep.refresh()
        time.sleep(0.4)
        clients = [sim.serve_client(0) for _ in range(3)]
        done = []

        def read(i):
            clients[i].pull_tensor(0, 128, timeout=20.0)
            done.append(i)

        threads = [threading.Thread(target=read, args=(i,), daemon=True)
                   for i in range(3)]
        for t in threads:
            t.start()
        assert _wait_for(lambda: len(rep._parked) == 3, timeout=5.0)
        assert rep.serve_sheds == 0  # all three parked, none shed
        assert rep.refresh()
        for t in threads:
            t.join(timeout=10.0)
        assert len(done) == 3
    finally:
        sim.shutdown()


def test_shed_retries_elsewhere_and_succeeds():
    """The client half of explicit load shedding: a shed answer
    deprioritizes the replica for the suggested backoff and the read
    lands elsewhere immediately."""
    sim = Simulation(_cfg(replicas=2, serve_retry_after_s=0.3))
    try:
        _seed_model(sim)
        rep0 = sim.replicas[0]
        # force replica 0 over budget (white-box: budget 1, one
        # admitted slot pinned) so every read it sees is shed
        rep0.max_inflight = 1
        with rep0._mu:
            rep0._admitted = 1
        lb = sim.serve_balancer(seed=2)
        _pin_rng(lb)  # first attempt lands on replica 0
        t0 = time.monotonic()
        arr, meta = lb.pull_tensor(0, 1000, timeout=10.0)
        dt = time.monotonic() - t0
        assert meta["replica"] == 1
        assert np.array_equal(arr, np.arange(1000, dtype=np.float32))
        assert dt < 2.0, dt  # immediate retry elsewhere, no timeout
        assert rep0.serve_sheds == 1
        st = lb.stats()
        assert st["sheds"] == 1
        assert st["replicas"][0]["deprioritized"]
        # within the backoff window the balancer avoids replica 0
        _, meta = lb.pull_tensor(0, 1000)
        assert meta["replica"] == 1
        assert lb.stats()["sheds"] == 1  # no second shed burned
    finally:
        sim.shutdown()


def test_batched_predict_aggregates_compatible_requests():
    """Goodput before shedding: N compatible queued PREDICTs execute
    as ONE forward pass and split back per request."""
    sim = Simulation(_cfg(replicas=1, serve_batch_max=4,
                          serve_batch_wait_ms=120.0))
    try:
        w = sim.worker(0, 0)
        w.init(1, np.arange(32, dtype=np.float32) / 32.0)  # 8x4 layer
        rep = sim.replicas[0]
        assert rep._batch_thread is not None
        assert rep.refresh()
        W = np.arange(32, dtype=np.float32).reshape(8, 4) / 32.0
        rng = np.random.default_rng(0)
        xs = [rng.standard_normal((2, 8)).astype(np.float32)
              for _ in range(4)]
        clients = [sim.serve_client(0) for _ in range(4)]
        out = {}

        def ask(i):
            out[i] = clients[i].predict(xs[i], [(1, (8, 4))],
                                        timeout=15.0)

        threads = [threading.Thread(target=ask, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20.0)
        assert len(out) == 4
        for i in range(4):
            y, meta = out[i]
            assert y.shape == (2, 4)
            assert np.allclose(y, xs[i] @ W, atol=1e-5)
        # at least one aggregated execution happened (the 120ms window
        # is far wider than the thread-start skew)
        assert rep.predict_batches >= 1
        assert rep.batched_predicts >= 2
        assert rep.serve_predicts == 4
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
def _ingest(mc, node, t, **stats):
    mc.ingest({"node": node, "boot": 1, "t_mono": float(t),
               "metrics": {}, "stats": stats})


def test_autoscaler_hysteresis_up_flap_and_down():
    """Scale-up after `patience` overloaded sweeps; a reversal inside
    cooldown is counted as a flap but never executed; scale-down needs
    2x patience.  Actuation is the wire retire/reactivate path."""
    sim = Simulation(_cfg(replicas=3, enable_obs=True,
                          obs_interval_s=0.0, serve_autoscale=True,
                          serve_scale_cooldown_s=30.0,
                          serve_scale_patience=1,
                          serve_target_qps=100.0))
    try:
        _seed_model(sim)
        asc = sim.replica_autoscaler
        mc = sim.metrics_collector
        assert asc is not None and asc.max_replicas == 3
        # all three replicas visible to the liveness view
        for r in range(3):
            _ingest(mc, f"replica:{r}", 1.0, serve_pulls=0,
                    serve_sheds=0)
        # start from 2 active: retire rank 2 through the autoscaler's
        # own actuator (wire SERVE_SCALE + subscriber prune)
        rank, how = asc._scale_down([0, 1, 2])
        assert (rank, how) == (2, "retire")
        assert sim.replicas[2]._retired
        with pytest.raises(ReplicaError, match="RETRY_AFTER"):
            sim.serve_client(2).pull_tensor(0, 1000)
        # overload signal: sheds climbing on the active replicas
        for r in range(3):
            _ingest(mc, f"replica:{r}", 2.0, serve_pulls=100,
                    serve_sheds=0)
            _ingest(mc, f"replica:{r}", 4.0,
                    serve_pulls=250, serve_sheds=30 if r < 2 else 0)
        rec = asc.tick(now=100.0)
        assert rec is not None and rec["action"] == "scale_up"
        assert rec["how"] == "reactivate" and rec["replica"] == 2
        assert not sim.replicas[2]._retired
        assert _wait_for(lambda: sim.replicas[2].refresh_rounds >= 2
                         or sim.replicas[2].refresh(), timeout=10.0)
        _, meta = sim.serve_client(2).pull_tensor(0, 1000)
        assert meta["staleness_s"] <= 5.0
        # idle signal now: the desired direction REVERSES inside the
        # cooldown — counted as a flap, never executed.  The samples
        # sit past the autoscaler's rate lookback, so the old shed
        # burst no longer reads as current overload
        for r in range(3):
            _ingest(mc, f"replica:{r}", 20.0, serve_pulls=251,
                    serve_sheds=30 if r < 2 else 0)
            _ingest(mc, f"replica:{r}", 22.0, serve_pulls=251,
                    serve_sheds=30 if r < 2 else 0)
        assert asc.tick(now=110.0) is None  # cooling down
        assert asc.flaps == 1
        assert asc.tick(now=112.0) is None  # still cooling: one flap
        assert asc.flaps == 1               # per window, not per tick
        # cooldown over: scale-down still needs 2x patience
        rec = asc.tick(now=140.0)
        assert rec is not None and rec["action"] == "scale_down"
        assert rec["replica"] == 2 and sim.replicas[2]._retired
        # executed decisions never reversed inside a cooldown
        ts = [d["t_mono"] for d in asc.decisions]
        dirs = [d["action"] for d in asc.decisions]
        for i in range(1, len(ts)):
            if dirs[i] != dirs[i - 1]:
                assert ts[i] - ts[i - 1] >= asc.cooldown_s
    finally:
        sim.shutdown()


def test_autoscaler_floor_and_ceiling():
    sim = Simulation(_cfg(replicas=2, enable_obs=True,
                          obs_interval_s=0.0, serve_autoscale=True,
                          serve_scale_patience=1, serve_min_replicas=2,
                          serve_target_qps=10.0))
    try:
        _seed_model(sim)
        asc = sim.replica_autoscaler
        mc = sim.metrics_collector
        for r in range(2):
            _ingest(mc, f"replica:{r}", 1.0, serve_pulls=0,
                    serve_sheds=0)
            _ingest(mc, f"replica:{r}", 3.0, serve_pulls=0,
                    serve_sheds=0)
        # idle forever, but min_replicas == num_replicas: never shrinks
        for i in range(6):
            assert asc.tick(now=100.0 + 40 * i) is None
        assert asc.stats()["scale_downs"] == 0
        # overloaded, but already at the ceiling: never grows
        for r in range(2):
            _ingest(mc, f"replica:{r}", 5.0, serve_pulls=500,
                    serve_sheds=50)
        for i in range(3):
            assert asc.tick(now=500.0 + 40 * i) is None
        assert asc.stats()["scale_ups"] == 0
    finally:
        sim.shutdown()


def test_health_rules_serve_overload_and_replica_flap():
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=1),
                            enable_obs=True, obs_interval_s=0.0,
                            obs_shed_rate=2.0, obs_replica_flap=2))
    try:
        mc, eng = sim.metrics_collector, sim.health
        # serve_overload: 40 sheds over 4s = 10/s > 2/s
        _ingest(mc, "replica:3", 1.0, serve_sheds=0)
        _ingest(mc, "replica:3", 5.0, serve_sheds=40)
        recs = eng.tick(now=10.0)
        got = {(r["rule"], r["subject"], r["state"]) for r in recs}
        assert ("serve_overload", "replica:3", "firing") in got
        assert not [r for r in eng.tick(now=11.0)
                    if r["rule"] == "serve_overload"]  # no duplicate
        # recovery: rate back under the threshold
        _ingest(mc, "replica:3", 6.0, serve_sheds=40)
        _ingest(mc, "replica:3", 60.0, serve_sheds=41)
        got = {(r["rule"], r["subject"], r["state"])
               for r in eng.tick(now=20.0)}
        assert ("serve_overload", "replica:3", "recovered") in got
        # replica_flap: the scheduler's autoscale_flaps counter grew
        gs = "global_scheduler:0"
        mc.ingest({"node": gs, "boot": 1, "t_mono": 1.0,
                   "metrics": {f"{gs}.autoscale_flaps": 0}, "stats": {}})
        mc.ingest({"node": gs, "boot": 1, "t_mono": 5.0,
                   "metrics": {f"{gs}.autoscale_flaps": 3}, "stats": {}})
        got = {(r["rule"], r["subject"], r["state"])
               for r in eng.tick(now=30.0)}
        assert ("replica_flap", "autoscaler", "firing") in got
    finally:
        sim.shutdown()


def test_status_console_shows_shed_and_inflight_columns():
    sim = Simulation(_cfg(replicas=1, enable_obs=True,
                          obs_interval_s=0.0, serve_max_inflight=8))
    try:
        _seed_model(sim)
        c = sim.serve_client(0)
        c.pull_tensor(0, 1000)
        sim.pump_metrics()
        state = sim.cluster_state()
        ent = state["replicas"][0]
        assert ent["serve_sheds"] == 0
        assert ent["inflight"] == 0 and ent["max_inflight"] == 8
        assert ent["retired"] is False
        from geomx_tpu.obs.state import render_text

        sim.replicas[0].set_active(False)
        sim.pump_metrics()
        txt = render_text(sim.cluster_state())
        assert "inflight=0/8" in txt and "RETIRED" in txt
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
def test_churn_orchestrator_replica_kill_and_restart():
    """The serve soak rides the same seeded-tape machinery as the
    worker/server churn: replica kills are attributed (flight ring +
    churn_replica_kills), floored, and followed by scheduled
    restarts."""
    from geomx_tpu.chaos.churn import (ChurnOrchestrator, ChurnPhase,
                                       ChurnPlan)

    sim = Simulation(_cfg(replicas=2, heartbeat_interval_s=0.2,
                          heartbeat_timeout_s=1.0, request_retry_s=1.0,
                          serve_refresh_interval_s=0.1,
                          serve_staleness_s=3.0))
    try:
        w = sim.worker(0, 0)
        w.init(0, np.arange(500, dtype=np.float32))
        assert _wait_for(lambda: all(r.refresh_rounds > 0
                                     for r in sim.replicas), timeout=10)
        plan = ChurnPlan(
            phases=(ChurnPhase(duration_s=1.5, notice_fraction=0.0,
                               replica_kill_rate=2.0,
                               replica_restart_s=0.5),),
            seed=11, min_replicas_live=1)
        orch = ChurnOrchestrator(sim, plan)
        orch.run()  # inline: tape + scheduled restarts to completion
        st = orch.stats()
        assert st["replica_kills"] >= 1
        kinds = [e["kind"] for e in orch.events]
        assert "churn_replica_kill" in kinds
        assert "churn_replica_restart" in kinds
        # every killed replica was restarted and serves again
        assert all(orch._replica_live.values())
        assert _wait_for(lambda: all(len(r.store) > 0
                                     and r.refresh_rounds > 0
                                     for r in sim.replicas),
                         timeout=15.0)
        c = sim.serve_client(0)
        arr, meta = c.pull_tensor(0, 500)
        assert np.array_equal(arr, np.arange(500, dtype=np.float32))
        assert meta["staleness_s"] <= 3.0
    finally:
        sim.shutdown()
