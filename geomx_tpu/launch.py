"""Single-role process entry point for real (multi-process) deployments.

The reference launches one OS process per role with env-var role
injection (ref: 3rdparty/ps-lite/tracker/dmlc_local.py,
scripts/cpu/run_vanilla_hips.sh — 12 processes for 2 parties + central).
This module is the equivalent:

    python -m geomx_tpu.launch --role scheduler:0@p0 --parties 2 --workers 2
    python -m geomx_tpu.launch --role server:0@p0    ...
    python -m geomx_tpu.launch --role worker:0@p0    ...
    python -m geomx_tpu.launch --role global_scheduler:0 ...
    python -m geomx_tpu.launch --role global_server:0 ...

Role/topology can also come from env (GEOMX_ROLE, GEOMX_NUM_PARTIES,
GEOMX_WORKERS_PER_PARTY, GEOMX_NUM_GLOBAL_SERVERS, GEOMX_BASE_PORT,
GEOMX_NODE_HOSTS), mirroring the reference's DMLC_* env surface.
Workers run the demo CNN training; non-worker roles serve until a
TERMINATE control message arrives (sent by worker rank-0 of party 0 when
training finishes), like the reference's kStopServer flow.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.ps import Postoffice
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.transport.tcp import TcpFabric, default_address_plan


def build_runtime(node: NodeId, config: Config, base_port: int = 9200,
                  hosts=None, advertise=None):
    """Construct the postoffice + role object for one node.

    ``advertise`` = (host, port) overrides this node's planned address —
    a *replacement* node coming up somewhere new (the static plan's slot
    is stale).  The new address is broadcast to every peer after start
    (ref: the scheduler's re-registration broadcast van.cc:176-193;
    plan-based here, so the node announces directly)."""
    if hosts is None:
        import json

        hosts = json.loads(os.environ.get("GEOMX_NODE_HOSTS", "{}"))
    plan = default_address_plan(config.topology, base_port, hosts)
    if advertise is not None:
        plan[str(node)] = advertise
    fabric = TcpFabric(plan, config=config)
    po = Postoffice(node, config.topology, fabric, config)
    stop_ev = threading.Event()
    # distributed tracing: the global scheduler hosts the collector
    # (registered BEFORE po.start so no TRACE_REPORT beats it); every
    # node gets a reporter bound to its postoffice
    po.trace_collector = None
    if config.trace_sample_every > 0:
        from geomx_tpu.trace import get_collector, get_tracer

        if node.role is Role.GLOBAL_SCHEDULER:
            po.trace_collector = get_collector(po)
        tracer = get_tracer(str(node))
        tracer.batch_events = config.trace_batch_events
        tracer.attach(po)

    def on_control(msg: Message) -> bool:
        if msg.control is Control.TERMINATE:
            stop_ev.set()
            return True
        return False

    po.add_control_hook(on_control)
    # NOTE: po.start() happens AFTER role construction (and after a
    # restarted global server loads its checkpoint): starting the van
    # first opens a window where replayed pushes reach a server whose
    # store is still empty (observed as KeyError in the stress test's
    # mid-run recovery)

    role_obj = None
    if node.role is Role.SERVER:
        from geomx_tpu.kvstore.server import LocalServer

        role_obj = LocalServer(po, config)
    elif node.role is Role.GLOBAL_SERVER:
        from geomx_tpu.kvstore.server import GlobalServer

        role_obj = GlobalServer(po, config)
        # crash recovery: a restarted global server resumes from its last
        # checkpoint (weights + optimizer + config); load_checkpoint also
        # drains pulls that parked during the restart window
        ckpt_dir = config.checkpoint_dir or os.environ.get(
            "GEOMX_CHECKPOINT_DIR")
        if ckpt_dir:
            path = f"{ckpt_dir}/global_server_{node.rank}.npz"
            if os.path.exists(path):
                role_obj.load_checkpoint(path)
                print(f"{node}: resumed from {path} "
                      f"({len(role_obj.store)} keys)", flush=True)
    elif node.role is Role.STANDBY_GLOBAL:
        from geomx_tpu.kvstore.server import GlobalServer

        # hot standby (--role standby_global:K): a full GlobalServer that
        # applies the primary's replication stream and serves nothing
        # until the global scheduler promotes it (kvstore/replication.py)
        role_obj = GlobalServer(po, config, standby=True)
    elif node.role is Role.REPLICA:
        from geomx_tpu.serve import ModelReplica

        # read-serving replica (--role replica:K): subscribes to every
        # global shard with staleness-bounded pulls and answers
        # SERVE_PULL/PREDICT read traffic from its local copy
        # (geomx_tpu/serve; docs/serving.md)
        role_obj = ModelReplica(po, config)
    elif node.role is Role.SCHEDULER and config.enable_intra_ts:
        from geomx_tpu.sched.ts_push import TsPushScheduler
        from geomx_tpu.sched.tsengine import TsScheduler

        role_obj = TsScheduler(po, config.topology.workers(node.party),
                               greed_rate=config.ts_max_greed_rate)
        TsPushScheduler(po, num_workers=config.topology.workers_per_party)
    elif node.role is Role.GLOBAL_SCHEDULER and config.enable_inter_ts:
        from geomx_tpu.sched.tsengine import TsScheduler

        role_obj = TsScheduler(po, config.topology.servers(),
                               greed_rate=config.ts_max_greed_rate)
        if config.enable_inter_ts_push:
            from geomx_tpu.sched.ts_push import TsPushScheduler

            TsPushScheduler(
                po, num_workers=config.topology.num_global_workers)
    if (node.role is Role.SCHEDULER and config.heartbeat_interval_s > 0
            and config.enable_eviction):
        # crash-tolerant membership: this party scheduler turns expired
        # worker heartbeats into forced leaves + barrier releases
        from geomx_tpu.kvstore.eviction import WorkerEvictionMonitor

        role_obj = role_obj or WorkerEvictionMonitor(po)
    po.recovery_monitor = None
    po.failover_monitor = None
    if (node.role is Role.GLOBAL_SCHEDULER
            and config.heartbeat_interval_s > 0
            and config.enable_eviction):
        # dead local servers fold their party out of global rounds; a
        # warm-booted replacement folds back in (kvstore/eviction.py)
        from geomx_tpu.kvstore.eviction import LocalServerRecoveryMonitor

        po.recovery_monitor = LocalServerRecoveryMonitor(po)
        role_obj = role_obj or po.recovery_monitor
    po.replica_monitor = None
    if (node.role is Role.GLOBAL_SCHEDULER
            and config.topology.num_replicas
            and config.heartbeat_interval_s > 0
            and config.enable_eviction):
        # serve replicas are evictable members: expired heartbeats prune
        # their tracked pull views at every shard; resumed ones rejoin
        from geomx_tpu.serve import ReplicaMonitor

        po.replica_monitor = ReplicaMonitor(po)
        role_obj = role_obj or po.replica_monitor
    po.replica_autoscaler = None
    if node.role is Role.GLOBAL_SCHEDULER and config.enable_obs:
        # cluster telemetry plane (geomx_tpu/obs): the metrics collector
        # + SLO health engine live here, registered BEFORE po.start so
        # no METRICS_REPORT frame beats the endpoint
        from geomx_tpu.obs import HealthEngine, MetricsCollector

        po.metrics_collector = MetricsCollector(
            po, config, trace_collector=po.trace_collector)
        po.health = HealthEngine(po.metrics_collector, config,
                                 trace_collector=po.trace_collector)
    else:
        po.metrics_collector = None
        po.health = None
    if (node.role is Role.GLOBAL_SCHEDULER and config.serve_autoscale
            and config.topology.num_replicas):
        # elastic serve capacity (geomx_tpu/serve/autoscaler): reads
        # the telemetry collector's per-replica series, retires /
        # reactivates replicas over the wire with hysteresis.  No
        # spawn hook here — an OS deployment's process manager starts
        # cold replicas; reactivation covers the retired-but-live ones
        from geomx_tpu.serve import ReplicaAutoscaler

        po.replica_autoscaler = ReplicaAutoscaler(
            po, config, collector=po.metrics_collector)
        role_obj = role_obj or po.replica_autoscaler
    if node.role is Role.GLOBAL_SCHEDULER and config.adaptive_wan:
        # closed-loop WAN codec autotuning (geomx_tpu/control): the
        # controller samples server stats + the trace report and
        # broadcasts epoch-fenced SET_WAN_POLICY down both tiers
        from geomx_tpu.control import AdaptiveWanController

        po.wan_controller = AdaptiveWanController(
            po, config, collector=po.trace_collector,
            metrics=po.metrics_collector)
        role_obj = role_obj or po.wan_controller
    if (node.role is Role.GLOBAL_SCHEDULER
            and config.topology.num_standby_globals
            and config.heartbeat_interval_s > 0):
        # automatic global-tier failover: the heartbeat-driven failure
        # detector + promotion coordinator lives on this scheduler
        from geomx_tpu.kvstore.replication import GlobalFailoverMonitor

        po.failover_monitor = GlobalFailoverMonitor(po)
        role_obj = role_obj or po.failover_monitor
    if node.role is Role.GLOBAL_SCHEDULER:
        # live cluster-state console (always on — costs nothing until
        # queried): Ctrl.CLUSTER_STATE merges shard holders/terms, party
        # folds, heartbeat freshness, policy epoch and health alerts
        from geomx_tpu.obs import ClusterStateService

        po.state_service = ClusterStateService(
            po, config,
            failover_monitor=po.failover_monitor,
            recovery_monitor=po.recovery_monitor,
            wan_controller=getattr(po, "wan_controller", None),
            collector=po.metrics_collector,
            health=po.health)
        role_obj = role_obj or po.state_service
    if node.role is Role.WORKER:
        from geomx_tpu.kvstore.client import WorkerKVStore

        role_obj = WorkerKVStore(po, config)
    elif node.role is Role.MASTER_WORKER:
        from geomx_tpu.kvstore.client import MasterWorker

        role_obj = MasterWorker(po, config)
    po.start()
    po.metrics_pump = None
    if config.enable_obs:
        # every role ships time-series samples; server roles attach
        # their QUERY_STATS-equivalent stats dict
        from geomx_tpu.kvstore.server import GlobalServer, LocalServer
        from geomx_tpu.obs import MetricsPump
        from geomx_tpu.serve import ModelReplica

        stats_fn = (role_obj.stats
                    if isinstance(role_obj, (LocalServer, GlobalServer,
                                             ModelReplica))
                    else None)
        po.metrics_pump = MetricsPump(
            po, config, stats_fn=stats_fn,
            collector=getattr(po, "metrics_collector", None))
    # scripted link faults (GEOMX_NETFAULT_PLAN): a JSON tape of WAN
    # cuts/heals applied to THIS process's fabric fault policy — the
    # partition demo's in-fabric blackhole (no iptables, no root)
    from geomx_tpu.chaos import install_env_netfaults

    install_env_netfaults(po)
    if advertise is not None:
        announce_address(po, *advertise)
    return po, role_obj, stop_ev


def announce_address(po: Postoffice, host: str, port: int,
                     repeat_s: float = 5.0):
    """Broadcast this node's replacement address to every peer, then
    keep re-broadcasting every ``repeat_s`` from a background thread.

    The repeat is what makes the announcement survive compound
    failures: a peer that was down during (or restarted after) the
    first broadcast rebuilds its plan from the STATIC addresses and
    would otherwise dial the stale slot forever.  Receivers apply
    updates idempotently, so the steady-state cost is a few 64-byte
    messages per period.  Runs off the startup path — a down peer's
    dial retry must not stall role construction."""
    body = {"node": str(po.node), "host": host, "port": port}
    peers = [n for n in po.topology.all_nodes() if str(n) != str(po.node)]

    def broadcast_loop():
        while True:
            for n in peers:
                domain = (Domain.LOCAL
                          if n.party is not None and n.party == po.node.party
                          else Domain.GLOBAL)
                # van swallows delivery errors (down peers get the next
                # round); sends to live peers are no-ops after the first
                po.van.send(Message(recipient=n,
                                    control=Control.ADDR_UPDATE,
                                    domain=domain, body=body))
            time.sleep(repeat_s)

    threading.Thread(target=broadcast_loop, daemon=True,
                     name=f"addr-announce-{po.node}").start()


def shutdown_cluster(po: Postoffice):
    """Broadcast TERMINATE to every non-worker node (worker rank-0 of
    party 0 calls this after training, ref: kStopServer).

    The broadcast is sent twice with a gap: a peer that crashed and
    restarted leaves this node holding a half-closed connection whose
    first send is silently buffered into the void (no error until the
    RST arrives).  By the second round the RST has landed, the send
    raises, and the fabric redials the live incarnation.  TERMINATE is
    idempotent, so the duplicate is harmless."""
    topo = po.topology
    targets = []
    for p in range(topo.num_parties):
        targets.append((topo.server(p), Domain.LOCAL))
        targets.append((topo.scheduler(p), Domain.LOCAL))
    for gs in topo.global_servers():
        targets.append((gs, Domain.GLOBAL))
    for sb in topo.standby_globals():
        targets.append((sb, Domain.GLOBAL))
    for rp in topo.replicas():
        targets.append((rp, Domain.GLOBAL))
    targets.append((topo.global_scheduler(), Domain.GLOBAL))
    for attempt in range(2):
        if attempt:
            time.sleep(0.5)
        for node, domain in targets:
            try:
                po.van.send(Message(recipient=node, control=Control.TERMINATE,
                                    domain=domain))
            except (KeyError, OSError):
                pass


def _wait_servers_up(kv, timeout: float = 90.0):
    """Ping the party server and every global shard until each answers
    a QUERY_STATS round trip.  Control commands are fire-once (the
    replay layer covers only data traffic), so configuration must not
    race a still-binding server process — with a sharded global tier
    the last shard to bind loses that race reliably."""
    from geomx_tpu.kvstore.common import Ctrl
    from geomx_tpu.transport.message import Domain as _Domain

    deadline = time.monotonic() + timeout
    for i in range(-1, len(kv.po.topology.global_servers())):
        while True:
            # re-resolve the shard's CURRENT holder on every retry: a
            # shard that dies during bring-up answers through its
            # promoted standby once the NEW_PRIMARY broadcast lands
            if i < 0:
                node, domain = kv.po.topology.server(kv.party), _Domain.LOCAL
            else:
                gts = kv.global_targets()
                if i >= len(gts):  # shards merged by a reassignment
                    break
                node, domain = gts[i], _Domain.GLOBAL
            ts = kv.worker.send_cmd(node, Ctrl.QUERY_STATS,
                                    domain=domain, wait=False)
            try:
                kv.worker.customer.wait(ts, timeout=2.0)
                kv.worker.cmd_response(ts)  # drop the stats body
                break
            except TimeoutError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{kv.po.node}: {node} never answered a "
                        "configuration ping")


def _configure_worker(po, kv, args):
    """Shared worker-side setup for every demo workload: either gate on
    the central master worker's configuration or (rank 0) push optimizer
    + compression ourselves, then barrier.  Every workload variant MUST
    route through here — a path that skips it silently trains without
    the requested compression and reintroduces the first-round race
    against the default optimizer."""
    topo = po.topology
    if kv.rank == 0:
        _wait_servers_up(kv)
    if topo.central_worker:
        # central-worker deployment: the MASTER drives configuration
        # (ref: DMLC_ENABLE_CENTRAL_WORKER); workers only gate training
        # on it having landed, so the first round can't race the default
        # optimizer
        from geomx_tpu.kvstore.common import Ctrl
        from geomx_tpu.transport.message import Domain

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            # EVERY shard must be configured — with MultiGPS a partially
            # configured tier would silently mix optimizers across keys
            ok = all((kv.worker.send_cmd(gs, Ctrl.QUERY_STATS,
                                         domain=Domain.GLOBAL) or {}
                      ).get("optimizer_configured")
                     for gs in kv.global_targets())
            if ok:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("master worker never configured the "
                               "optimizer")
    else:
        if kv.party == 0 and kv.rank == 0:
            kv.set_optimizer({"type": args.optimizer, "lr": 0.01})
        if kv.rank == 0 and args.compression != "none":
            kv.set_gradient_compression({"type": args.compression})
    kv.barrier()


def install_preempt_handler(po, role_obj, stop_ev):
    """Map SIGTERM onto the graceful preemption drain (spot preemptions
    arrive as SIGTERM-with-notice on every major cloud; SIGKILL stays
    the ungraceful path — heartbeat eviction covers it).  A noticed
    WORKER finishes its in-flight step (the training loops poll the
    notice), flushes un-ACKed pushes and leaves the party; a noticed
    LOCAL SERVER drains its WAN round and hands its party fold to the
    global tier; every other role just exits in order.  Installed only
    under ``Config.enable_preempt`` — default-off keeps the legacy
    SIGTERM semantics (flight dump + immediate death)."""
    import signal

    from geomx_tpu.kvstore.client import WorkerKVStore
    from geomx_tpu.kvstore.server import LocalServer

    def handler(signum, frame):
        print(f"{po.node}: SIGTERM → preempt notice (graceful drain; "
              "SIGKILL would take the eviction path)", flush=True)
        if isinstance(role_obj, WorkerKVStore):
            # the demo loop breaks at its next step boundary and the
            # drain thread flushes + leaves; main() then exits normally
            role_obj.begin_drain()
        elif isinstance(role_obj, LocalServer):
            def drain():
                try:
                    role_obj.preempt_drain()
                except Exception:
                    pass  # the eviction path covers a failed drain
                finally:
                    stop_ev.set()

            threading.Thread(target=drain, daemon=True,
                             name=f"preempt-drain-{po.node}").start()
        else:
            stop_ev.set()

    try:
        signal.signal(signal.SIGTERM, handler)
    except ValueError:
        pass  # not the main thread (library use)


def _drain_if_preempted(po, kv) -> bool:
    """Worker epilogue for the notice path: when the loop broke on a
    preempt notice, wait out the drain (flush + graceful leave) and
    exit WITHOUT the end-of-training barrier or cluster shutdown — the
    survivors keep training.  Returns True when preempted."""
    ev = getattr(kv, "preempt_noticed", None)
    if ev is None or not ev.is_set():
        return False
    kv.finish_drain()
    print(f"{po.node}: preempted — drained and left gracefully "
          f"(drain_s={kv.last_drain_s})", flush=True)
    return True


def _test_step_sleep_s(node) -> float:
    """Per-node artificial per-step delay for acceptance runs that need
    deterministic heterogeneity (the ESync matrix): env
    ``GEOMX_TEST_STEP_SLEEP_MS='{"worker:1@p0": 60}'`` keyed by the
    node's ``str()`` form (``role:rank@party``)."""
    import json

    raw = os.environ.get("GEOMX_TEST_STEP_SLEEP_MS")
    if not raw:
        return 0.0
    try:
        return float(json.loads(raw).get(str(node), 0)) / 1000.0
    except (ValueError, AttributeError, TypeError):
        return 0.0


def _test_poison_steps(node) -> tuple:
    """Per-node poison injection for integrity acceptance runs
    (scripts/run_integrity_demo.sh): env
    ``GEOMX_TEST_POISON_STEPS='{"worker:1@p0": 40}'`` — from that step
    on, this worker's pushed gradients are all-NaN.  Returns
    ``(start_step,)`` or ``()``.  The payload corruption happens at the
    gradient source, so every hop downstream (codec, wire, server
    screen) sees exactly what a diverged or faulty worker produces."""
    import json

    raw = os.environ.get("GEOMX_TEST_POISON_STEPS")
    if not raw:
        return ()
    try:
        start = json.loads(raw).get(str(node))
    except (ValueError, AttributeError, TypeError):
        return ()
    return () if start is None else (int(start),)


def _worker_demo(po, kv, args, join_advertise=None):
    """The reference demo workload (examples/cnn.py) for launcher smoke
    runs: tiny CNN on synthetic data.  ``join_advertise``: this worker
    is an out-of-plan DYNAMIC JOINER — register with the party server
    before training, leave gracefully after, and stay out of the
    cluster's barriers (the static plan doesn't count us)."""
    import jax
    import numpy as np

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker, run_worker_hfa

    joining = join_advertise is not None or args.join
    x, y = synthetic_classification(n=512, shape=(12, 12, 1), seed=0)
    _, params, grad_fn = create_cnn_state(
        jax.random.PRNGKey(0), input_shape=(1, 12, 12, 1))
    sleep_s = _test_step_sleep_s(po.node)
    if sleep_s > 0:
        # deterministic pacing for harnesses that must outlive a fault
        # window (run_status_demo.sh) — same knob the ESync matrix uses
        inner = grad_fn

        def grad_fn(p, xb, yb):  # noqa: F811 — deliberate wrap
            time.sleep(sleep_s)
            return inner(p, xb, yb)

    poison_from = _test_poison_steps(po.node)
    if poison_from:
        # integrity-demo byzantine worker: from step N on, every pushed
        # gradient is all-NaN.  The server screen zeroes the merge and
        # answers with a typed rejection; claim those acks so this
        # worker keeps stepping (a real diverged worker wouldn't stop
        # either) instead of raising out of wait_all.
        inner_g = grad_fn
        step_ctr = [0]

        def grad_fn(p, xb, yb):  # noqa: F811 — deliberate wrap
            loss, acc, grads = inner_g(p, xb, yb)
            step, step_ctr[0] = step_ctr[0], step_ctr[0] + 1
            if step >= poison_from[0]:
                grads = jax.tree_util.tree_map(
                    lambda g: np.full(np.shape(g), np.nan, np.float32),
                    grads)
            return loss, acc, grads

        prev_handler = kv.worker.error_handler

        def _claim_poison_ack(m, _prev=prev_handler):
            err = str((m.body or {}).get("error", ""))
            if "poisoned push rejected" in err:
                return True
            return bool(_prev is not None and _prev(m))

        kv.worker.error_handler = _claim_poison_ack

    def train(kv, params, it, steps, barrier_init):
        # HFA servers average WEIGHTS — pushing gradients at them (the
        # pre-r5 --hfa path) silently replaced the model with a mean
        # gradient.  The HFA client loop is the only correct driver.
        if args.hfa:
            return run_worker_hfa(kv, params, grad_fn, it, steps,
                                  k1=args.hfa_k1,
                                  barrier_init=barrier_init)
        return run_worker(kv, params, grad_fn, it, steps,
                          barrier_init=barrier_init)
    if joining:
        info = kv.join_party(advertise=join_advertise)
        print(f"{po.node}: joined as rank {info['rank']} "
              f"(num_workers={info['num_workers']})", flush=True)
        # adopt the CLUSTER's current weights before contributing — a
        # gradient computed at our own random init point would fold one
        # garbage step into everyone's mean.  init (no-op server-side)
        # publishes shapes; the pulls fetch the live replica.
        from geomx_tpu.training import flatten_params

        leaves, treedef = flatten_params(params)
        for tid, leaf in enumerate(leaves):
            kv.init(tid, leaf)
        pulled = [kv.pull_sync(tid) for tid in range(len(leaves))]
        params = jax.tree_util.tree_unflatten(treedef, pulled)
        # shard by the POST-join party size: the static plan's indexing
        # would alias another worker's shard (widx past num_all_workers
        # wraps into a subset of worker 0's slice)
        widx, num_all = int(info["rank"]), int(info["num_workers"])
    else:
        _configure_worker(po, kv, args)
        widx, num_all = kv.party * kv.num_workers + kv.rank, \
            kv.num_all_workers
        # chaos harnesses key their kill timing off this marker: a
        # SIGKILL before configuration completes tests the bring-up
        # race, after it the mid-training failover path
        print(f"{po.node}: configured — training begins", flush=True)
    it = ShardedIterator(x, y, args.batch, widx, num_all)
    hist = train(kv, params, it, args.steps, barrier_init=not joining)
    if _drain_if_preempted(po, kv):
        return
    if joining:
        kv.wait_all()
        kv.leave_party()
        print(f"{po.node}: steps={len(hist)} left cleanly", flush=True)
        return
    print(f"{po.node}: steps={len(hist)} first_loss={hist[0][0]:.4f} "
          f"last_loss={hist[-1][0]:.4f}", flush=True)
    kv.barrier()
    if kv.party == 0 and kv.rank == 0:
        time.sleep(0.5)  # let sibling parties drain their last rounds
        shutdown_cluster(po)


def _worker_demo_lm(po, kv, args):
    """Flagship LM workload over the real topology (VERDICT r3 item 5):
    the transformer from models/transformer.py at a non-toy size
    (>=10 M params) trained through the two-tier kvstore, printing
    tokens/s and parameter count.  Size via GEOMX_LM_* env overrides."""
    from geomx_tpu.data import TokenIterator
    from geomx_tpu.training import build_flagship_lm, run_worker

    cfg, params, n_params, grad_fn, data = build_flagship_lm()
    widx = kv.party * kv.num_workers + kv.rank
    _configure_worker(po, kv, args)
    it = TokenIterator(data, args.batch, widx, kv.num_all_workers)
    stamps = []

    def log(step, _l, _a):
        stamps.append(time.perf_counter())

    hist = run_worker(kv, params, grad_fn, it, args.steps,
                      barrier_init=True, log_fn=log)
    if _drain_if_preempted(po, kv):
        return
    # steady tokens/s excludes the first step (jit compile + INIT
    # broadcast dominate it; bench.py's lm child splits the same way)
    if len(stamps) > 1:
        steady = (args.batch * cfg.max_seq * (len(stamps) - 1)
                  / max(stamps[-1] - stamps[0], 1e-9))
    else:
        steady = float("nan")
    print(f"{po.node}: steps={len(hist)} first_loss={hist[0][0]:.4f} "
          f"last_loss={hist[-1][0]:.4f} n_params={n_params} "
          f"tokens_per_sec={steady:.1f}", flush=True)
    kv.barrier()
    if kv.party == 0 and kv.rank == 0:
        time.sleep(0.5)
        shutdown_cluster(po)


def _worker_demo_esync(po, kv, args):
    """ESync acceptance workload: the esync client loop with optional
    injected per-step heterogeneity, printing the per-round (assigned
    steps, reach-server seconds) pairs the matrix asserts on."""
    import jax
    import numpy as np

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker_esync

    x, y = synthetic_classification(n=2048, shape=(12, 12, 1), seed=0)
    _, params, grad_fn = create_cnn_state(
        jax.random.PRNGKey(0), input_shape=(1, 12, 12, 1))
    sleep_s = _test_step_sleep_s(po.node)
    if sleep_s > 0:
        inner = grad_fn

        def grad_fn(p, xb, yb):  # noqa: F811 — deliberate wrap
            time.sleep(sleep_s)
            return inner(p, xb, yb)

    widx = kv.party * kv.num_workers + kv.rank
    _configure_worker(po, kv, args)
    # ShardedIterator samples with replacement — never runs dry, which
    # the esync loop needs (rounds x up-to-max_local_steps batches)
    it = ShardedIterator(x, y, args.batch, widx, kv.num_all_workers)
    # warm up ALL the jit compiles (grad + optimizer update) OUTSIDE the
    # measured loop: round 0's step time seeds the planner's EWMA, and a
    # multi-second compile spike would make every worker look equally
    # slow for the whole short acceptance run
    import optax

    opt = optax.adam(1e-2)
    xb, yb = next(iter(it))
    _loss, _acc, g = grad_fn(params, xb, yb)
    upd, _ = opt.update(g, opt.init(params), params)
    optax.apply_updates(params, upd)  # discarded — warmup only
    rounds_info: list = []
    hist = run_worker_esync(kv, params, grad_fn, it, args.steps,
                            optimizer=opt, barrier_init=True,
                            max_local_steps=16, rounds_out=rounds_info)
    if _drain_if_preempted(po, kv):
        return
    # steps= counts SYNC rounds (the --steps contract); local steps vary
    # per worker by design — that variance is the feature
    print(f"{po.node}: steps={len(rounds_info)} "
          f"first_loss={hist[0][0]:.4f} "
          f"last_loss={hist[-1][0]:.4f} local_steps={len(hist)}",
          flush=True)
    print(f"{po.node}: esync_rounds={rounds_info!r}", flush=True)
    kv.barrier()
    if kv.party == 0 and kv.rank == 0:
        time.sleep(0.5)
        shutdown_cluster(po)


def _worker_demo_staged(po, kv, args):
    """P3 acceptance workload: a staged MLP through the overlapped loop
    (``overlap.run_worker_overlapped``) — backward pushes deepest stage
    FIRST, so the shallow stages' later, higher-priority pushes must
    overtake queued deep slices in the van's priority queue (the
    observable: ``pq_overtakes`` in this process's exit stats).  Stage
    params carry a large ballast leaf so socket writes outlast the VJP
    chain and the queue actually holds contending messages."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.overlap import StagedModel, run_worker_overlapped

    dims = [144, 64, 64, 64, 64, 10]
    key = jax.random.PRNGKey(0)
    fns, params = [], []
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        k1, key = jax.random.split(key)
        params.append({
            "w": jax.random.normal(k1, (din, dout)) / np.sqrt(din),
            "b": jnp.zeros((dout,), jnp.float32),
            "ballast": jnp.zeros((256_000,), jnp.float32),
        })
        last = i == len(dims) - 2

        def fn(p, x, last=last):
            h = x @ p["w"] + p["b"] + 1e-9 * jnp.sum(p["ballast"])
            return h if last else jax.nn.relu(h)

        fns.append(fn)

    def ce(logits, y):
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
        return loss, jnp.mean(jnp.argmax(logits, -1) == y)

    x, y = synthetic_classification(n=512, shape=(12, 12, 1), seed=0)
    x = x.reshape(len(x), -1)
    widx = kv.party * kv.num_workers + kv.rank
    _configure_worker(po, kv, args)
    it = ShardedIterator(x, y, args.batch, widx, kv.num_all_workers)
    model = StagedModel(fns, ce)
    hist = run_worker_overlapped(kv, model, params, it, args.steps)
    print(f"{po.node}: steps={len(hist)} first_loss={hist[0][0]:.4f} "
          f"last_loss={hist[-1][0]:.4f}", flush=True)
    kv.barrier()
    if kv.party == 0 and kv.rank == 0:
        time.sleep(0.5)
        shutdown_cluster(po)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default=os.environ.get("GEOMX_ROLE"))
    ap.add_argument("--parties", type=int,
                    default=int(os.environ.get("GEOMX_NUM_PARTIES", "1")))
    ap.add_argument("--workers", type=int,
                    default=int(os.environ.get("GEOMX_WORKERS_PER_PARTY", "1")))
    ap.add_argument("--global-servers", type=int,
                    default=int(os.environ.get("GEOMX_NUM_GLOBAL_SERVERS", "1")))
    ap.add_argument("--global-shards", type=int,
                    default=int(os.environ.get("GEOMX_GLOBAL_SHARDS", "0")),
                    help="shard the global tier horizontally into M "
                         "independent key-range servers (alias of "
                         "--global-servers; wins when both are given). "
                         "Each shard is its own failure domain: run each "
                         "as --role global_server:K, optionally backed "
                         "by --role standby_global:K (per-shard "
                         "failover; see docs/deployment.md)")
    ap.add_argument("--standby-globals", type=int,
                    default=int(os.environ.get("GEOMX_NUM_STANDBY_GLOBALS",
                                               "0")),
                    help="hot standbys for the global tier: standby rank "
                         "K backs global server rank K; run each as "
                         "--role standby_global:K (every process must "
                         "pass the same count — the port plan includes "
                         "the standbys)")
    ap.add_argument("--replicas", type=int,
                    default=int(os.environ.get("GEOMX_SERVE_REPLICAS",
                                               "0")),
                    help="read-serving replica tier: K replicas, each "
                         "holding a staleness-bounded local copy of the "
                         "whole model and answering SERVE_PULL/PREDICT "
                         "reads; run each as --role replica:K (every "
                         "process must pass the same count — the port "
                         "plan includes the replicas; docs/serving.md)")
    ap.add_argument("--serve-staleness", type=float,
                    default=float(os.environ.get("GEOMX_SERVE_STALENESS_S",
                                                 "0") or 0),
                    help="replica read-staleness bound in seconds "
                         "(default Config.serve_staleness_s = 5.0)")
    ap.add_argument("--base-port", type=int,
                    default=int(os.environ.get("GEOMX_BASE_PORT", "9200")))
    ap.add_argument("--advertise", default=os.environ.get("GEOMX_ADVERTISE"),
                    metavar="HOST:PORT",
                    help="replacement node: bind+announce this address "
                         "instead of the static plan's slot")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--workload", default="cnn", choices=["cnn", "lm"],
                    help="worker demo: the reference CNN or the flagship "
                         "transformer LM (>=10M params, GEOMX_LM_* sized)")
    ap.add_argument("--join", action="store_true",
                    help="this worker is OUT-OF-PLAN: register with the "
                         "party server mid-training (ADD_NODE), train, "
                         "then leave gracefully; requires --advertise "
                         "for TCP so peers can dial the new slot")
    ap.add_argument("--compression", default="none")
    ap.add_argument("--hfa", action="store_true")
    ap.add_argument("--hfa-k1", type=int,
                    default=int(os.environ.get("GEOMX_HFA_K1", "2")),
                    help="HFA local steps between weight syncs "
                         "(ref: MXNET_KVSTORE_HFA_K1)")
    ap.add_argument("--esync", action="store_true",
                    help="straggler-balancing local steps (HFA-mode "
                         "servers + per-round step assignment)")
    ap.add_argument("--p3", action="store_true")
    ap.add_argument("--tsengine", action="store_true")
    ap.add_argument("--tsengine-inter", action="store_true")
    ap.add_argument("--tsengine-inter-push", action="store_true")
    ap.add_argument("--sync", default="fsa", choices=["fsa", "mixed"])
    ap.add_argument("--dgt", type=int, default=0, choices=[0, 1, 2, 3])
    ap.add_argument("--central-worker", action="store_true",
                    help="topology includes a dedicated master worker in "
                         "the central party (ref: DMLC_ENABLE_CENTRAL_WORKER)")
    ap.add_argument("--trace-sample-every", type=int,
                    default=int(os.environ.get("GEOMX_TRACE_SAMPLE_EVERY",
                                               "0")),
                    help="distributed tracing: trace every N-th round "
                         "end-to-end (0 = off); the global scheduler "
                         "merges all nodes' spans and writes the timeline "
                         "+ critical-path report to --trace-dir")
    ap.add_argument("--trace-dir",
                    default=os.environ.get("GEOMX_TRACE_DIR", ""))
    ap.add_argument("--obs", action="store_true",
                    help="cluster telemetry plane: per-node metrics "
                         "pumps ship time-series samples to a collector "
                         "+ SLO health engine on the global scheduler; "
                         "query live state with python -m "
                         "geomx_tpu.status (GEOMX_OBS_* tune it; see "
                         "docs/observability.md)")
    ap.add_argument("--obs-interval", type=float,
                    default=float(os.environ.get("GEOMX_OBS_INTERVAL",
                                                 "0") or 0),
                    help="pump/health cadence in seconds (implies --obs "
                         "when > 0)")
    ap.add_argument("--adaptive-wan", action="store_true",
                    help="closed-loop WAN codec autotuning: a controller "
                         "on the global scheduler retunes compression "
                         "mid-training via epoch-fenced SET_WAN_POLICY "
                         "broadcasts (GEOMX_ADAPT_* tune the loop; see "
                         "docs/adaptive-wan.md)")
    ap.add_argument("--server-shards", type=int,
                    default=int(os.environ.get("GEOMX_SERVER_SHARDS", "0")),
                    help="key-sharded server merge: lock stripes + "
                         "serial merge lanes per server (0 = auto "
                         "min(8, cpus); 1 = the single-lock server; "
                         "see docs/perf.md)")
    ap.add_argument("--transport",
                    default=os.environ.get("GEOMX_TRANSPORT", ""),
                    choices=["", "threads", "reactor"],
                    help="transport engine: threads (default) = the "
                         "thread-per-endpoint fabric; reactor = every "
                         "endpoint in the process serviced by a shared "
                         "selector-loop pool + timer wheel "
                         "(GEOMX_REACTOR_LOOPS sizes it; see "
                         "docs/perf.md 'Event-driven transport')")
    ap.add_argument("--merge-backend",
                    default=os.environ.get("GEOMX_MERGE_BACKEND", "auto"),
                    choices=["auto", "numpy", "jax"],
                    help="server merge lane engine: numpy = host "
                         "reference path (default off-accelerator), "
                         "jax = on-device accumulate + mesh psum party "
                         "aggregation, auto = jax iff a TPU/GPU "
                         "backend is live (see docs/merge-backends.md)")
    ap.add_argument("--optimizer", default="adam",
                    choices=["sgd", "adam", "dcasgd"])
    args = ap.parse_args(argv)
    if not args.role:
        ap.error("--role or GEOMX_ROLE required")
    if (args.esync or args.hfa) and args.workload == "lm":
        # --esync/--hfa force HFA-mode servers (weight averaging); the
        # lm workload pushes GRADIENTS — dispatching it against HFA
        # servers would silently train garbage
        ap.error("--workload lm is mutually exclusive with --esync/--hfa")
    if args.join and (args.esync or args.p3 or args.workload != "cnn"):
        # the KVSTORE layer is join-uniform across every mode —
        # test_join_under_{intra_ts,hfa,p3,esync} prove it — but the
        # p3/esync DEMO workloads (staged MLP / esync loop) have no
        # joiner bootstrap in this launcher, so their flags stay gated
        # here; --hfa and --tsengine joiners run the full flow
        ap.error("--join supports the cnn workload (plain, --hfa or "
                 "--tsengine); p3/esync joins are library-level "
                 "(see tests/test_dynamic_join.py), lm has none")
    if args.join and not args.advertise:
        # without an advertised bind address the out-of-plan node has no
        # slot in the TCP plan and dies with a bare KeyError at bind
        ap.error("--join requires --advertise HOST:PORT")

    from geomx_tpu.core.platform import apply_platform_from_env

    apply_platform_from_env()

    node = NodeId.parse(args.role)
    # env supplies the full documented knob surface (drop injection,
    # resend, heartbeats, tuning — docs/env-vars.md); CLI flags override
    cfg = Config.from_env()
    central = (args.central_worker
               or cfg.topology.central_worker
               or node.role is Role.MASTER_WORKER)
    cfg.topology = Topology(num_parties=args.parties,
                            workers_per_party=args.workers,
                            num_global_servers=(args.global_shards
                                                or args.global_servers),
                            num_standby_globals=args.standby_globals,
                            num_replicas=(args.replicas
                                          or cfg.topology.num_replicas),
                            central_worker=central)
    if args.serve_staleness > 0:
        cfg.serve_staleness_s = args.serve_staleness
    if args.transport:
        cfg.transport = args.transport
    cfg.compression = args.compression
    # ESync exchanges weights like HFA — servers must run in HFA mode
    # (ref: examples/cnn.py wires --esync the same way)
    cfg.use_hfa = args.hfa or args.esync or cfg.use_hfa
    cfg.enable_p3 = args.p3 or cfg.enable_p3
    cfg.enable_intra_ts = args.tsengine or cfg.enable_intra_ts
    cfg.enable_inter_ts = (args.tsengine_inter or args.tsengine_inter_push
                           or cfg.enable_inter_ts)
    cfg.enable_inter_ts_push = (args.tsengine_inter_push
                                or cfg.enable_inter_ts_push)
    cfg.sync_global_mode = (args.sync == "fsa") and cfg.sync_global_mode
    cfg.enable_dgt = args.dgt or cfg.enable_dgt
    cfg.trace_sample_every = (args.trace_sample_every
                              or cfg.trace_sample_every)
    cfg.trace_dir = args.trace_dir or cfg.trace_dir
    cfg.adaptive_wan = args.adaptive_wan or cfg.adaptive_wan
    cfg.enable_obs = args.obs or args.obs_interval > 0 or cfg.enable_obs
    if args.obs_interval > 0:
        cfg.obs_interval_s = args.obs_interval
    cfg.server_shards = args.server_shards or cfg.server_shards
    cfg.merge_backend = args.merge_backend or cfg.merge_backend
    # CLI overrides bypass dataclass construction — re-run the invariant
    # checks so invalid combinations fail here, not as a runtime hang
    cfg.__post_init__()
    advertise = None
    if args.advertise:
        host, sep, port = args.advertise.rpartition(":")
        if not sep or not port.isdigit():
            ap.error(f"--advertise needs HOST:PORT, got {args.advertise!r}")
        advertise = (host or "127.0.0.1", int(port))
    po, role_obj, stop_ev = build_runtime(node, cfg, args.base_port,
                                          advertise=advertise)
    # black-box flight recorder crash/exit trigger: dump this node's
    # ring to GEOMX_OBS_DIR at interpreter exit and on SIGTERM/SIGINT
    # (SIGKILL leaves no dump — the postmortem assembler infers the
    # victim from the survivors' rings; docs/observability.md)
    from geomx_tpu.obs.flight import install_process_hooks

    install_process_hooks(po)
    if cfg.enable_preempt:
        # spot semantics: SIGTERM = the preemption NOTICE (graceful
        # drain — installed after the flight hooks, so it owns the
        # signal; the exit-path dump still lands via atexit).  SIGKILL
        # keeps the ungraceful eviction/rejoin path.
        install_preempt_handler(po, role_obj, stop_ev)
    print(f"{node}: up", flush=True)
    if node.role is Role.WORKER:
        if args.workload == "lm":
            _worker_demo_lm(po, role_obj, args)
        elif args.esync:
            _worker_demo_esync(po, role_obj, args)
        elif cfg.enable_p3:
            # P3 deployments train through the staged overlap loop —
            # that IS the feature (priority-scheduled per-stage rounds)
            _worker_demo_staged(po, role_obj, args)
        elif args.join:
            _worker_demo(po, role_obj, args, join_advertise=advertise)
        else:
            _worker_demo(po, role_obj, args)
    elif node.role is Role.MASTER_WORKER:
        # the master worker's whole life: configure, then return before
        # training (ref: examples/cnn.py:96 — master returns after setup)
        role_obj.set_optimizer({"type": args.optimizer, "lr": 0.01})
        role_obj.set_sync_global_mode(args.sync == "fsa")
        if args.compression != "none":
            role_obj.set_gradient_compression({"type": args.compression})
        print(f"{node}: configured (optimizer={args.optimizer}, "
              f"sync={args.sync}, compression={args.compression}); "
              "returning before training", flush=True)
    else:
        stop_ev.wait()
        print(f"{node}: terminating", flush=True)
    fab = po.van.fabric
    udp_tx = getattr(fab, "udp_datagrams_sent", 0)
    udp_rx = getattr(fab, "udp_datagrams_recv", 0)
    udp_drop = getattr(fab, "udp_dropped", 0)
    if udp_tx or udp_rx or udp_drop:
        # observability for DGT acceptance runs: proves the lossy
        # channels actually rode UDP datagrams, not the reliable conn
        print(f"{node}: udp_tx={udp_tx} udp_rx={udp_rx} "
              f"udp_dropped={udp_drop}", flush=True)
    # per-feature observables for the acceptance matrix: each proves the
    # feature's mechanism actually fired, not just that training finished
    feats = []
    for attr, tag in (("ts_relays_received", "ts_relays"),
                      ("hfa_gated_key_rounds", "hfa_gated_key_rounds"),
                      ("ts_deliveries", "ts_deliveries"),
                      ("stale_pull_skips", "stale_skips")):
        v = getattr(role_obj, attr, 0)
        if v:
            feats.append(f"{tag}={v}")
    pc = getattr(role_obj, "push_codec", None)
    if pc is not None and getattr(pc, "bsc_picks", 0) + getattr(
            pc, "fp16_picks", 0) > 0:
        feats.append(f"mpq_bsc={pc.bsc_picks} mpq_fp16={pc.fp16_picks}")
    # DGT mode-3 observable: 4-bit requant chunks sent/decoded (the
    # KVWorker apps hold the sender; every app holds a reassembler)
    dgt4_tx = dgt4_rx = 0
    for app in (getattr(role_obj, "worker", None),
                getattr(role_obj, "up", None),
                getattr(role_obj, "server", None)):
        if app is None:
            continue
        s = getattr(app, "dgt_sender", None)
        if s is not None:
            dgt4_tx += getattr(s, "dgt4_chunks", 0)
        r = getattr(app, "_dgt_reasm", None)
        if r is not None:
            dgt4_rx += getattr(r, "dgt4_decoded", 0)
    if dgt4_tx or dgt4_rx:
        feats.append(f"dgt4_tx={dgt4_tx} dgt4_rx={dgt4_rx}")
    # WAN traffic observable (ref: send_bytes_/recv_bytes_ van.h:180-181)
    if po.van.wan_send_bytes or po.van.wan_recv_bytes:
        feats.append(f"wan_tx={po.van.wan_send_bytes} "
                     f"wan_rx={po.van.wan_recv_bytes}")
    # dynamic membership observable (ADD_NODE joins/leaves served)
    if getattr(role_obj, "joined_workers", 0) or getattr(
            role_obj, "left_workers", 0):
        feats.append(f"joined={role_obj.joined_workers} "
                     f"left={role_obj.left_workers}")
    if po.van.pq_overtakes:
        feats.append(f"pq_overtakes={po.van.pq_overtakes}")
    if po.flight is not None and po.flight.dumps:
        # flight-recorder observable: incident/operator dumps taken
        # during the run (the atexit dump lands after this line)
        feats.append(f"flight_dumps={po.flight.dumps}")
    # merge backend observable (kvstore/backend.py): which engine this
    # server's lanes actually ran, + the jax path's device counters
    be = getattr(role_obj, "_backend", None)
    if be is not None:
        bs = be.stats()
        feats.append(f"merge_backend={bs.get('merge_backend')}")
        if bs.get("h2d_bytes"):
            feats.append(f"h2d_bytes={bs['h2d_bytes']} "
                         f"merge_device_ms={bs.get('merge_device_ms')}")
        # device-resident optimizer stage (docs/merge-backends.md):
        # round closes that never left the device + the D2H the serve/
        # checkpoint events actually paid
        dev_opt = getattr(role_obj, "_dev_opt", None)
        if dev_opt is not None:
            feats.append(f"opt_device={dev_opt.kind} "
                         f"opt_device_ms={bs.get('opt_device_ms')} "
                         f"d2h_bytes={bs.get('d2h_bytes')}")
    # global-tier failover observables (replication stream, promotions,
    # term fencing, client-side retarget+replay)
    for attr, tag in (("failover_events", "failover_events"),
                      ("promotions", "promotions"),
                      ("fenced_rejects", "fenced_rejects"),
                      # sharded global tier: key-range drains shipped /
                      # adopted (live reassignment)
                      ("drains", "drains"),
                      ("merged_handoffs", "merged_handoffs"),
                      # crash-tolerant membership observables: evictions
                      # actuated (schedulers), fenced zombies + warm
                      # boots (local servers), party folds (global tier),
                      # replay-on-recovery (workers)
                      ("evictions", "worker_evictions"),
                      ("evicted_workers", "evicted_workers"),
                      ("eviction_fenced_pushes", "eviction_fenced"),
                      ("warm_boots", "warm_boots"),
                      ("party_folds", "party_folds"),
                      ("party_unfolds", "party_unfolds"),
                      ("server_recoveries", "server_recoveries"),
                      # serve tier observables: reads answered, the
                      # staleness contract's park/expire counters, the
                      # refresh cadence, membership events, and the
                      # tracked-view prunes (replicas + global servers)
                      ("serve_pulls", "serve_pulls"),
                      ("serve_predicts", "serve_predicts"),
                      ("staleness_violations", "staleness_violations"),
                      ("stale_rejects", "stale_rejects"),
                      ("refresh_rounds", "replica_refreshes"),
                      ("dense_resyncs", "dense_resyncs"),
                      ("replica_evictions", "replica_evictions"),
                      ("replica_rejoins", "replica_rejoins"),
                      ("subscriber_prunes", "subscriber_prunes")):
        v = getattr(role_obj, attr, 0)
        if v:
            feats.append(f"{tag}={v}")
    repl = getattr(role_obj, "_repl", None)
    if repl is not None and repl.acked_seq:
        feats.append(f"replicated_seq={repl.acked_seq}")
    if getattr(role_obj, "_repl_seq", 0):
        feats.append(f"applied_repl_seq={role_obj._repl_seq}")
    if getattr(role_obj, "term", 0):
        feats.append(f"term={role_obj.term}")
    if feats:
        print(f"{node}: " + " ".join(feats), flush=True)
    if cfg.trace_sample_every > 0:
        from geomx_tpu.trace import get_tracer

        get_tracer(str(node)).flush()
        coll = getattr(po, "trace_collector", None)
        if coll is not None:
            # grace for the last TRACE_REPORT batches to land, then dump
            # the merged timeline + critical-path report
            time.sleep(1.0)
            out_dir = cfg.trace_dir or "."
            os.makedirs(out_dir, exist_ok=True)
            trace_path = os.path.join(out_dir, "geomx_trace.json")
            coll.dump(trace_path)
            report_path = os.path.join(out_dir, "geomx_trace_report.json")
            import json as _json

            with open(report_path, "w") as f:
                _json.dump(coll.critical_path(), f, indent=1)
            print(f"{node}: merged trace -> {trace_path}; critical-path "
                  f"report -> {report_path}", flush=True)
            txt = coll.report_text()
            if txt:
                print(txt, flush=True)
    # telemetry exit lines (global scheduler): the final cluster state
    # + health transition totals, and — when GEOMX_OBS_DIR names a
    # directory — the Prometheus exposition + alert history artifacts
    svc = getattr(po, "state_service", None)
    if svc is not None:
        from geomx_tpu.obs.state import render_text as _render_state

        state = svc.compose()
        health = state.get("health") or {}
        mc = getattr(po, "metrics_collector", None)
        shard_bits = ", ".join(
            "{}:{}@t{}".format(k, v["holder"], v["term"])
            for k, v in sorted(state.get("shards", {}).items()))
        print(f"{node}: cluster_state shards={{{shard_bits}}} "
              f"health_alerts={health.get('transitions_total', 0)} "
              f"obs_reports={mc.reports_received if mc else 0}",
              flush=True)
        print(_render_state(state), flush=True)
        obs_dir = os.environ.get("GEOMX_OBS_DIR", "")
        if obs_dir and mc is not None:
            import json as _json

            os.makedirs(obs_dir, exist_ok=True)
            with open(os.path.join(obs_dir, "geomx_metrics.prom"),
                      "w") as f:
                f.write(mc.prometheus_text())
            with open(os.path.join(obs_dir, "geomx_cluster_state.json"),
                      "w") as f:
                _json.dump(state, f, indent=1)
            print(f"{node}: metrics exposition + cluster state -> "
                  f"{obs_dir}", flush=True)
    po.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
