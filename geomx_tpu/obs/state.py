"""Live cluster-state query service + operator dashboard rendering.

``Ctrl.CLUSTER_STATE`` is answered by the global scheduler — the one
node that already holds every piece of the answer: the failover
monitor's per-shard holders/terms, the recovery monitor's party fold
state, its own heartbeat table (per-node freshness), the adaptive-WAN
controller's policy epoch, the health engine's active alerts, and the
metrics collector's freshest per-node stats.  :meth:`compose` merges
them into one JSON-safe dict; :func:`render_text` turns that dict into
the text dashboard both ``python -m geomx_tpu.status`` and the launch
exit lines print.

The service costs nothing until queried (no threads, no per-step work),
so it is always on wherever a global scheduler runs.
"""

from __future__ import annotations

import time
from typing import Optional

from geomx_tpu.core.config import NodeId, Role
from geomx_tpu.obs.health import _json_safe
from geomx_tpu.utils.metrics import system_snapshot


class ClusterStateService:
    """One per deployment, on the global scheduler's postoffice.
    Monitor references may be bound after construction (the launchers
    build them in their own order) via plain attribute assignment."""

    def __init__(self, postoffice, config=None, failover_monitor=None,
                 recovery_monitor=None, wan_controller=None,
                 collector=None, health=None):
        from geomx_tpu.kvstore.common import Ctrl
        from geomx_tpu.obs.endpoint import get_endpoint

        assert postoffice.node.role is Role.GLOBAL_SCHEDULER, \
            "the cluster-state service runs on the global scheduler"
        self.po = postoffice
        self.config = config or postoffice.config
        self.failover_monitor = failover_monitor
        self.recovery_monitor = recovery_monitor
        self.wan_controller = wan_controller
        self.collector = collector
        self.health = health
        self.queries_served = 0
        self.flight_requests = 0
        self._endpoint = get_endpoint(postoffice).acquire()
        self._endpoint.route(Ctrl.CLUSTER_STATE, self._on_query)
        # operator flight-dump trigger (python -m geomx_tpu.status
        # --dump-flight): relayed as a Control.FLIGHT_DUMP broadcast so
        # every node snapshots its ring under one incident id
        self._endpoint.route(Ctrl.FLIGHT_DUMP, self._on_flight_dump)

    # ---- wire query ---------------------------------------------------------
    def _on_query(self, msg):
        # out-of-plan querier (the status CLI): install its reply
        # address like a dynamic joiner's, so the response can dial
        self._install_reply_addr(msg)
        self.queries_served += 1
        try:
            self.po.van.send(msg.reply_to(body=self.compose()))
        except (KeyError, OSError):
            pass  # querier vanished between ask and answer

    def _install_reply_addr(self, msg):
        body = msg.body if isinstance(msg.body, dict) else {}
        addr = body.get("addr")
        if addr:
            add = getattr(self.po.van.fabric, "add_address", None)
            if add is not None:
                try:
                    add(str(msg.sender), (str(addr[0]), int(addr[1])))
                except (TypeError, ValueError, IndexError):
                    pass

    def _on_flight_dump(self, msg):
        """Ctrl.FLIGHT_DUMP from the status console: broadcast the ring
        snapshot to every node and answer with the dump dir + expected
        per-node paths."""
        import os

        self._install_reply_addr(msg)
        body = msg.body if isinstance(msg.body, dict) else {}
        out_dir = str(body.get("dir")
                      or os.environ.get("GEOMX_OBS_DIR", ""))
        if not out_dir:
            reply = {"ok": False,
                     "error": "no dump directory: set GEOMX_OBS_DIR on "
                              "the cluster or pass --flight-dir"}
        else:
            from geomx_tpu.obs.flight import broadcast_flight_dump

            self.flight_requests += 1
            incident = f"operator-{self.flight_requests}"
            paths = broadcast_flight_dump(self.po, out_dir, incident,
                                          reason="operator request")
            reply = {"ok": True, "dir": out_dir, "incident": incident,
                     "nodes": len(paths), "paths": paths}
        try:
            self.po.van.send(msg.reply_to(body=reply))
        except (KeyError, OSError):
            pass  # querier vanished between ask and answer

    def _pressure_of(self, node: str) -> dict:
        """The node's freshest flight-recorder pressure gauges (shipped
        through the metrics pump; docs/metrics.md) — the status
        console's pressure column."""
        from geomx_tpu.obs.flight import PRESSURE_GAUGES

        out = {}
        if self.collector is None:
            return out
        for key in PRESSURE_GAUGES:
            v = self.collector.value(node, key)
            if isinstance(v, (int, float)):
                out[key] = round(float(v), 6)
        return out

    # ---- composition --------------------------------------------------------
    def compose(self) -> dict:
        topo = self.po.topology
        cfg = self.config
        now = time.monotonic()
        hb, epoch = self.po.heartbeat_info()
        hb_on = cfg.heartbeat_interval_s > 0

        def node_entry(n) -> dict:
            s = str(n)
            t, boot = hb.get(s, (None, 0))
            age = now - (t if t is not None else epoch)
            alive = None  # unknown: heartbeats off, nothing to judge by
            if hb_on:
                alive = age <= cfg.heartbeat_timeout_s
            return {"age_s": round(age, 3), "alive": alive, "boot": boot}

        nodes = {}
        for n in (list(topo.global_servers()) + list(topo.standby_globals())
                  + list(topo.servers()) + list(topo.replicas())):
            nodes[str(n)] = node_entry(n)

        fm = self.failover_monitor
        shard_reg = system_snapshot("global_shard")
        table = fm.shard_table() if fm is not None else {}
        shards = {}
        for k in range(topo.num_global_servers):
            if k in table:
                holder = table[k]["holder"]
                term = table[k]["term"]
                promoted = table[k]["promoted"]
            else:
                # no monitor on this node: the registry gauges its
                # monitors (if any ever ran here) left behind
                holder = str(NodeId(Role.GLOBAL_SERVER, k))
                term = int(shard_reg.get(f"global_shard{k}.term", 0) or 0)
                promoted = term > 0
            sb = topo.standby_for(k)
            entry = {
                "holder": holder, "term": term, "promoted": promoted,
                "standby": str(sb) if sb is not None else None,
                "promotions": int(shard_reg.get(
                    f"global_shard{k}.promotions", 0) or 0),
                "reassignments": int(shard_reg.get(
                    f"global_shard{k}.reassignments", 0) or 0),
                "alive": nodes.get(holder, {}).get("alive"),
            }
            if self.collector is not None:
                st = self.collector.latest_stats(holder) or {}
                for key in ("draining", "policy_epoch",
                            "num_global_workers", "key_rounds",
                            "merge_backend"):
                    if key in st:
                        entry[key] = st[key]
                press = self._pressure_of(holder)
                if press:
                    entry["pressure"] = press
            shards[k] = entry

        rm = self.recovery_monitor
        folded = set(rm._folded) if rm is not None else set()
        quarantined = set(getattr(rm, "_quarantined", ())) \
            if rm is not None else set()
        parties = {}
        for p in range(topo.num_parties):
            server = str(topo.server(p))
            entry = {"server": server, "folded": p in folded,
                     "quarantined": p in quarantined,
                     "alive": nodes.get(server, {}).get("alive"),
                     "workers": topo.workers_per_party}
            if self.collector is not None:
                st = self.collector.latest_stats(server) or {}
                for key in ("wan_push_rounds", "policy_epoch", "uptime_s",
                            "merge_backend", "degraded",
                            "degraded_rounds", "quarantined_workers"):
                    if key in st:
                        entry[key] = st[key]
                press = self._pressure_of(server)
                if press:
                    entry["pressure"] = press
            parties[p] = entry

        # serve replicas (geomx_tpu/serve): per-replica staleness / QPS
        # / version lag vs the shard holders' current round progress
        replicas = {}
        if topo.num_replicas:
            cur_rounds = None
            if self.collector is not None:
                vals = []
                for k, s in shards.items():
                    kr = s.get("key_rounds")
                    if isinstance(kr, (int, float)):
                        vals.append(kr)
                if vals:
                    cur_rounds = int(sum(vals))
            for r in topo.replicas():
                s = str(r)
                entry = {"node": s, "alive": nodes.get(s, {}).get("alive")}
                if self.collector is not None:
                    st = self.collector.latest_stats(s) or {}
                    for key in ("staleness_s", "serve_pulls",
                                "serve_predicts", "staleness_violations",
                                "stale_rejects", "replica_refreshes",
                                "rounds_at_refresh", "keys",
                                "serve_p50_ms", "serve_p99_ms",
                                "serve_sheds", "inflight",
                                "max_inflight", "retired"):
                        if st.get(key) is not None:
                            entry[key] = st[key]
                    qps = self.collector.rate(s, "serve_pulls")
                    if qps is not None:
                        entry["serve_qps"] = round(qps, 2)
                    shed = self.collector.rate(s, "serve_sheds")
                    if shed is not None:
                        entry["shed_rate"] = round(shed, 2)
                    if (cur_rounds is not None
                            and isinstance(st.get("rounds_at_refresh"),
                                           (int, float))):
                        # clamped at 0: the replica's LIST_KEYS snapshot
                        # and the holder's pump sample are taken at
                        # different instants, so a fresh replica can
                        # read "ahead" of the collector by a few rounds
                        entry["version_lag_rounds"] = max(0, int(
                            cur_rounds - st["rounds_at_refresh"]))
                replicas[r.rank] = entry

        policy = None
        if self.wan_controller is not None:
            s = self.wan_controller.status()
            policy = {"epoch": s["epoch"],
                      "compression": s["compression"],
                      "decisions": s["decisions"]}
        elif self.collector is not None:
            epochs = [self.collector.value(str(n), "policy_epoch")
                      for n in topo.global_servers()]
            epochs = [e for e in epochs if isinstance(e, (int, float))]
            if epochs:
                policy = {"epoch": int(max(epochs))}

        health = None
        if self.health is not None:
            with self.health._mu:
                total = len(self.health.alerts)
                recent = [dict(a) for a in self.health.alerts[-5:]]
            health = {"active": self.health.active_alerts(),
                      "transitions_total": total, "recent": recent}

        telemetry = None
        if self.collector is not None:
            telemetry = {
                "reports": self.collector.reports_received,
                "nodes_reporting": len(self.collector.nodes()),
                "node_restarts": dict(self.collector.node_restarts),
            }

        return _json_safe({
            "t": time.time(),
            "node": str(self.po.node),
            "topology": {
                "num_parties": topo.num_parties,
                "workers_per_party": topo.workers_per_party,
                "global_shards": topo.num_global_servers,
                "standby_globals": topo.num_standby_globals,
                "replicas": topo.num_replicas,
            },
            "heartbeats": hb_on,
            "shards": shards,
            "parties": parties,
            "replicas": replicas,
            "nodes": nodes,
            "policy": policy,
            "health": health,
            "telemetry": telemetry,
        })

    def stop(self):
        self._endpoint.release()


def _alive_tag(alive) -> str:
    if alive is None:
        return "?"
    return "up" if alive else "DOWN"


def _press_tag(entry: dict) -> str:
    """Compact pressure column for one console row: merge-lock wait,
    lane/send-queue depth, codec backlog (absent gauges are omitted)."""
    p = entry.get("pressure") or {}
    if not p:
        return ""
    bits = []
    if "lock_wait_s" in p:
        bits.append(f"lock={p['lock_wait_s'] * 1e3:.1f}ms")
    for key, short in (("lane_depth", "lane"),
                       ("van_sendq_depth", "sq"),
                       ("codec_pool_busy", "codec"),
                       ("process_threads", "thr"),
                       ("reactor_fds", "rfds")):
        if key in p:
            bits.append(f"{short}={int(p[key])}")
    if "reactor_loop_lag_ms" in p:
        bits.append(f"rlag={p['reactor_loop_lag_ms']:.1f}ms")
    return " press[" + " ".join(bits) + "]" if bits else ""


def render_text(state: dict) -> str:
    """The operator dashboard: one screen of text for
    ``python -m geomx_tpu.status`` and the demo scripts."""
    topo = state.get("topology", {})
    when = time.strftime("%H:%M:%S", time.localtime(state.get("t", 0)))
    lines = [
        f"cluster @ {when} (via {state.get('node', '?')})",
        f"topology: {topo.get('num_parties', '?')} parties x "
        f"{topo.get('workers_per_party', '?')} workers, "
        f"{topo.get('global_shards', '?')} global shard(s)"
        + (f" (+{topo['standby_globals']} standby)"
           if topo.get("standby_globals") else "")
        + (f", {topo['replicas']} serve replica(s)"
           if topo.get("replicas") else ""),
    ]
    lines.append("shards:")
    shards = state.get("shards", {})
    for k in sorted(shards, key=int):  # keys are ints in-proc, strings
        s = shards[k]                  # after a JSON round trip
        extra = ""
        if s.get("promoted"):
            extra += " PROMOTED"
        if s.get("draining"):
            extra += " draining"
        if s.get("key_rounds") is not None:
            extra += f" rounds={int(s['key_rounds'])}"
        if s.get("merge_backend"):
            extra += f" merge={s['merge_backend']}"
        lines.append(
            f"  shard {k}: holder={s.get('holder')} term={s.get('term')} "
            f"[{_alive_tag(s.get('alive'))}]"
            f" standby={s.get('standby') or '-'}{extra}{_press_tag(s)}")
    lines.append("parties:")
    parties = state.get("parties", {})
    for p in sorted(parties, key=int):
        e = parties[p]
        extra = " FOLDED-OUT" if e.get("folded") else ""
        if e.get("quarantined"):
            # heartbeat-dead but probe-alive: folded out REVERSIBLY
            # (never alongside FOLDED-OUT — escalation moves the party
            # from one set to the other)
            extra += " QUARANTINED"
        if e.get("degraded"):
            extra += f" DEGRADED({int(e.get('degraded_rounds', 0))}r)"
        if e.get("quarantined_workers"):
            extra += f" qworkers={int(e['quarantined_workers'])}"
        if e.get("wan_push_rounds") is not None:
            extra += f" wan_rounds={int(e['wan_push_rounds'])}"
        if e.get("merge_backend"):
            extra += f" merge={e['merge_backend']}"
        lines.append(f"  p{p}: {e.get('server')} "
                     f"[{_alive_tag(e.get('alive'))}]{extra}{_press_tag(e)}")
    replicas = state.get("replicas") or {}
    if replicas:
        lines.append("replicas:")
        for r in sorted(replicas, key=int):
            e = replicas[r]
            extra = ""
            if e.get("staleness_s") is not None:
                extra += f" staleness={e['staleness_s']:.2f}s"
            if e.get("version_lag_rounds") is not None:
                extra += f" lag={int(e['version_lag_rounds'])}r"
            if e.get("serve_qps") is not None:
                extra += f" qps={e['serve_qps']:.1f}"
            if e.get("serve_pulls") is not None:
                extra += f" pulls={int(e['serve_pulls'])}"
            if e.get("shed_rate") is not None:
                extra += f" shed_rate={e['shed_rate']:.1f}/s"
            elif e.get("serve_sheds"):
                extra += f" sheds={int(e['serve_sheds'])}"
            if e.get("inflight") is not None:
                extra += f" inflight={int(e['inflight'])}"
                if e.get("max_inflight"):
                    extra += f"/{int(e['max_inflight'])}"
            if e.get("staleness_violations"):
                extra += (f" violations="
                          f"{int(e['staleness_violations'])}")
            if e.get("retired"):
                extra += " RETIRED"
            lines.append(f"  replica {r}: {e.get('node')} "
                         f"[{_alive_tag(e.get('alive'))}]{extra}")
    pol = state.get("policy")
    if pol:
        line = f"wan policy: epoch={pol.get('epoch')}"
        comp = pol.get("compression")
        if isinstance(comp, dict):
            line += f" codec={comp.get('type', 'none')}"
        lines.append(line)
    h = state.get("health")
    if h is not None:
        active = h.get("active") or []
        lines.append(f"health: {len(active)} active alert(s), "
                     f"{h.get('transitions_total', 0)} transition(s)")
        for a in active:
            lines.append(f"  ALERT {a.get('rule')} {a.get('subject')} — "
                         f"{a.get('message')}")
    t = state.get("telemetry")
    if t is not None:
        restarts = sum((t.get("node_restarts") or {}).values())
        lines.append(f"telemetry: {t.get('reports', 0)} reports from "
                     f"{t.get('nodes_reporting', 0)} node(s)"
                     + (f", {restarts} restart(s)" if restarts else ""))
    return "\n".join(lines)
