"""Adaptive WAN control plane (PR 4 tentpole): closed-loop codec
retuning over the HiPS tree — signal estimators, hysteresis policy,
and the epoch-fenced SET_WAN_POLICY reconfiguration protocol — plus the
codec-layer satellites (per-endpoint decoder state, unknown-tag fencing,
the shared compatibility predicate).

Fast tests are tier-1 (in-proc fabric, manual controller ticks via
``adapt_interval_s=0``); the throttled-bandwidth e2e with loss parity
against an uninterrupted static-BSC control is marked slow.
"""

import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.common import Cmd, Ctrl
from geomx_tpu.utils.metrics import system_snapshot


def _cfg(parties=2, workers=1, **kw):
    kw.setdefault("adaptive_wan", True)
    kw.setdefault("adapt_interval_s", 0.0)  # manual tick (deterministic)
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _round(ws, g, tid=0):
    for w in ws:
        w.push(tid, g)
    outs = [w.pull_sync(tid) for w in ws]
    for w in ws:
        w.wait_all()
    return outs


# --------------------------------------------------------------------------
# tentpole: closed loop + epoch protocol
# --------------------------------------------------------------------------

def test_controller_downshifts_and_both_tiers_adopt():
    """The whole loop: an impossible round budget drives the engine down
    the ladder; every decision is broadcast under a fresh epoch, adopted
    by the global tier immediately and by the local servers at their
    next round boundary; the decisions are visible in the metrics
    registry; and training stays correct throughout."""
    base = system_snapshot()
    sim = Simulation(_cfg(adapt_round_budget_s=1e-4, adapt_cooldown_s=0.0))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(1000, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(1000, np.float32)
        for _ in range(8):
            outs = _round(ws, g)
            sim.wan_controller.tick()
        st = sim.wan_controller.status()
        assert st["epoch"] >= 1, "controller never actuated"
        assert st["compression"]["type"] != "none", "never left vanilla"
        # both tiers converged to the controller's epoch
        for ls in sim.local_servers:
            assert ls._policy_epoch == st["epoch"]
            assert ls.compression["type"] == st["compression"]["type"]
        assert sim.global_servers[0]._policy_epoch == st["epoch"]
        # correctness through the switches: replicas identical and exact
        # (sum grads = 2, /2 contributors, lr 1 → -1 per round)
        np.testing.assert_allclose(outs[0], outs[1])
        assert np.isfinite(outs[0]).all()
        # decisions are in the registry (gauge + per-action counters)
        snap = system_snapshot()
        assert snap.get("global_scheduler:0.wan_policy_epoch") == st["epoch"]
        assert (snap.get("global_scheduler:0.wan_policy_downshifts", 0)
                - base.get("global_scheduler:0.wan_policy_downshifts", 0)) >= 1
    finally:
        sim.shutdown()


def test_old_epoch_push_fenced_then_retried_no_corrupt_merge():
    """The epoch fence end-to-end: the receiver adopts a policy the
    senders have not heard of; their next push (old epoch) is rejected
    with a retryable error, the fence reply's policy is adopted, the
    stashed raw gradients are re-encoded and retried, and the round
    completes with EXACT values — no corrupted merge, no wedge."""
    sim = Simulation(_cfg())
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(1000, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(1000, np.float32)
        _round(ws, g)  # round 1: everyone at epoch 0
        # push the policy to the RECEIVER only — the broadcast the
        # senders would normally get is "lost"
        gs_node = sim.topology.global_servers()[0]
        reply = sim.wan_controller._app.rpc(
            gs_node, Ctrl.SET_WAN_POLICY,
            body={"epoch": 7, "compression": {"type": "fp16"}})
        assert reply == {"epoch": 7}
        outs = _round(ws, g)  # round 2: fenced → adopt → retry
        gs = sim.global_servers[0]
        assert gs.policy_fenced_pushes >= 2  # both parties fenced once
        for ls in sim.local_servers:
            assert ls.policy_fence_retries >= 1
            assert ls.policy_drops == 0
            assert ls._policy_epoch == 7
            assert ls.compression["type"] == "fp16"
        # exact math survived the fence+retry: two rounds of mean grad 1
        # at lr 1 → weights exactly -2 (fp16-exact values)
        np.testing.assert_allclose(outs[0], -2.0)
        np.testing.assert_allclose(outs[0], outs[1])
    finally:
        sim.shutdown()


def test_manual_override_via_simulation():
    """Simulation.set_wan_policy drives the same epoch protocol as
    automatic decisions (and refuses constraint-violating codecs)."""
    sim = Simulation(_cfg())
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        _round(ws, np.ones(64, np.float32))
        info = sim.set_wan_policy({"type": "2bit", "threshold": 0.5})
        assert info["epoch"] == 1
        assert info["compression"]["type"] == "2bit"
        outs = _round(ws, np.ones(64, np.float32))
        # 2bit emits ±threshold: grads 1 → +0.5 each, mean 0.5; weights
        # moved by exactly lr*0.5 past the first (vanilla) round
        np.testing.assert_allclose(outs[0], -1.5)
        assert sim.global_servers[0]._policy_epoch == 1
    finally:
        sim.shutdown()


def test_hysteresis_deadband_and_cooldown_bound_decisions():
    """Engine unit test on a fake clock: an oscillating signal inside
    the patience window produces ZERO decisions, and a sustained
    over-budget signal produces at most one decision per cooldown."""
    from geomx_tpu.control.policy import WanPolicyEngine
    from geomx_tpu.control.signals import WanSignals

    now = [0.0]
    clock = lambda: now[0]  # noqa: E731

    def sig(rt):
        return WanSignals(t=now[0], round_time_s=rt, goodput_bps=None,
                          wan_bytes_rate={}, rtt_s=None,
                          dominant_stage=None, straggler_party=None,
                          rounds_total=0)

    # oscillation: alternating over/under never reaches patience=2
    eng = WanPolicyEngine({"type": "none"}, budget_s=1.0, deadband=0.2,
                          cooldown_s=5.0, patience=2, clock=clock)
    for i in range(50):
        now[0] += 1.0
        d = eng.observe(sig(3.0 if i % 2 == 0 else 0.1))
        assert d is None, "oscillation broke the hysteresis"
    assert eng.decisions == []

    # sustained overload: decisions rate-limited by the cooldown
    eng = WanPolicyEngine({"type": "none"}, budget_s=1.0, deadband=0.2,
                          cooldown_s=10.0, patience=2, clock=clock)
    now[0] = 0.0
    for _ in range(40):  # 40 "seconds" of overload
        now[0] += 1.0
        eng.observe(sig(5.0))
    # at most ceil(40/10) + the initial free shift
    assert 1 <= len(eng.decisions) <= 5
    for a, b in zip(eng.decisions, eng.decisions[1:]):
        assert a.compression != b.compression  # monotone down the ladder

    # compute-bound veto: WAN compression can't fix a merge bottleneck
    eng = WanPolicyEngine({"type": "none"}, budget_s=1.0, deadband=0.2,
                          cooldown_s=0.0, patience=1, clock=clock)
    s = sig(5.0)
    s.dominant_stage = "global_merge"
    for _ in range(5):
        now[0] += 1.0
        assert eng.observe(s) is None
    assert eng.vetoes == 5
    assert eng.decisions == []


def test_ladder_constraint_gating_under_ts_and_hfa():
    """The policy ladder is filtered by the SAME predicate as config
    validation: no bsc/mpq under the inter-party TS overlay, only
    weight-safe codecs under HFA; and runtime overrides that violate
    the constraints are refused end-to-end."""
    from geomx_tpu.control.policy import build_ladder

    plain = [r["type"] for r in build_ladder({"type": "none"})]
    assert plain == ["none", "fp16", "bsc", "bsc", "2bit"]
    ts = [r["type"] for r in build_ladder({"type": "none"}, inter_ts=True)]
    assert "bsc" not in ts and "mpq" not in ts and "2bit" in ts
    hfa = [r["type"] for r in build_ladder({"type": "none"}, hfa=True)]
    assert hfa == ["none", "fp16"]
    # MPQ base → size-bound retuning rungs
    mpq = build_ladder({"type": "mpq", "size_bound": 160_000})
    bounds = [r["size_bound"] for r in mpq if r["type"] == "mpq"]
    assert bounds == [160_000, 40_000, 10_000]

    # end-to-end: a manual bsc override under HFA is refused before any
    # broadcast happens
    sim = Simulation(_cfg(parties=1, workers=1, use_hfa=True, hfa_k2=1))
    try:
        with pytest.raises(ValueError, match="weight-safe"):
            sim.set_wan_policy({"type": "bsc"})
        assert sim.wan_controller.epoch == 0
    finally:
        sim.shutdown()


def test_disabled_path_is_one_flag_check():
    """Default config: no controller, no stash, no epoch stamping, no
    fence state — the acceptance bar's 'behavior unchanged' guard."""
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=1)))
    try:
        assert sim.wan_controller is None
        ls = sim.local_servers[0]
        gs = sim.global_servers[0]
        assert ls._adaptive is False and gs._adaptive is False
        # the stash only exists when the feature is on
        assert not hasattr(ls, "_policy_stash")
        assert ls.up.error_handler is None
        # capture the actual wire traffic of one round
        seen = []
        orig = sim.fabric.deliver
        sim.fabric.deliver = lambda m: (seen.append(m), orig(m))[1]
        w = sim.worker(0, 0)
        w.init(0, np.zeros(32, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        w.push(0, np.ones(32, np.float32))
        w.pull_sync(0)
        w.wait_all()
        assert seen, "tap saw no traffic"
        assert all(m.policy_epoch == 0 for m in seen)
        assert gs.policy_fenced_pushes == 0
        assert gs._policy_epoch == 0 and ls._policy_epoch == 0
    finally:
        sim.shutdown()


# --------------------------------------------------------------------------
# satellites: codec-layer fixes
# --------------------------------------------------------------------------

def test_twobit_decoder_state_is_per_endpoint():
    """Two concurrent Simulations with different 2-bit thresholds must
    not share decoder state (the old module-level cache did): each
    global server decodes with its OWN threshold, exactly."""
    sims = {
        0.25: Simulation(Config(topology=Topology())),
        0.75: Simulation(Config(topology=Topology())),
    }
    try:
        for thr, sim in sims.items():
            w = sim.worker(0, 0)
            w.init(0, np.zeros(64, np.float32))
            w.set_optimizer({"type": "sgd", "lr": 1.0})
            w.set_gradient_compression({"type": "2bit", "threshold": thr})
        # interleave the rounds so both decoders are live simultaneously
        for thr, sim in sims.items():
            sim.worker(0, 0).push(0, np.ones(64, np.float32))
        for thr, sim in sims.items():
            w = sim.worker(0, 0)
            out = w.pull_sync(0)
            w.wait_all()
            # grad 1 > thr → emit +thr; lr 1 → weights exactly -thr
            np.testing.assert_allclose(out, -thr)
        banks = [sim.global_servers[0]._decoders for sim in sims.values()]
        assert banks[0] is not banks[1]
    finally:
        for sim in sims.values():
            sim.shutdown()


def test_decoder_bank_bounded():
    from geomx_tpu.compression import DecoderBank

    bank = DecoderBank(cap=8)
    for i in range(100):
        bank.twobit(float(i))
    assert len(bank._decoders) <= 8
    # LRU: the most recent threshold survives and is reused
    d = bank.twobit(99.0)
    assert bank.twobit(99.0) is d


def test_unknown_compr_tag_fenced_names_node_and_tag():
    """A malformed/foreign compr tag is fenced at message-decode time
    with an error naming the offender — it must never raise a bare
    ValueError inside the merge or poison later rounds."""
    from geomx_tpu.ps import KVPairs

    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=1)))
    try:
        w = sim.worker(0, 0)
        w.init(0, np.zeros(64, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 1.0})
        ls = sim.local_servers[0]
        gs = sim.global_servers[0]
        # forge a push with a garbage tag straight up the WAN link
        ls.up.zpush(KVPairs(np.array([0], np.int64),
                            np.ones(64, np.float32),
                            np.array([64], np.int64)),
                    cmd=Cmd.DEFAULT, compr="evil", wait=True)
        assert gs.rejected_compr_tags == 1
        errs = "; ".join(ls.up.errors)
        assert "evil" in errs and "server:0@p0" in errs
        # the merge was not poisoned: a normal round still works exactly
        w.push(0, np.ones(64, np.float32))
        np.testing.assert_allclose(w.pull_sync(0), -1.0)
        w.wait_all()
    finally:
        sim.shutdown()


def test_compression_allowed_full_matrix():
    """The shared predicate, exhaustively (the same matrix config
    validation, the runtime gates, and the ladder builder consume)."""
    from geomx_tpu.compression import compression_allowed

    matrix = {
        # codec: (plain, inter_ts, hfa-runtime)
        "none": (True, True, True),
        "fp16": (True, True, True),
        "2bit": (True, True, False),
        "bsc":  (True, False, False),
        "mpq":  (True, False, False),
    }
    for codec, (plain, ts, hfa) in matrix.items():
        assert compression_allowed(codec)[0] is plain, codec
        assert compression_allowed(codec, inter_ts=True)[0] is ts, codec
        assert compression_allowed(codec, hfa=True)[0] is hfa, codec
    ok, why = compression_allowed("garbage")
    assert not ok and "unknown" in why
    # config validation consumes it (inter_ts context)
    with pytest.raises(ValueError, match="relay payload"):
        Config(topology=Topology(), enable_inter_ts=True,
               enable_intra_ts=True, compression="bsc")


def test_policy_epoch_survives_wire_roundtrip():
    from geomx_tpu.transport.message import Message

    m = Message(keys=np.array([1], np.int64),
                vals=np.ones(4, np.float32),
                lens=np.array([4], np.int64),
                push=True, request=True, policy_epoch=42)
    back = Message.from_bytes(m.to_bytes())
    assert back.policy_epoch == 42
    assert back.reply_to().policy_epoch == 42


# --------------------------------------------------------------------------
# slow e2e: throttled WAN → downshift within K rounds → wall-time
# recovery + loss parity vs an uninterrupted static-BSC control
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_throttled_wan_downshift_recovers_wall_time_with_loss_parity():
    from geomx_tpu.transport.van import FaultPolicy

    N = 200_000
    LR = 0.1
    rng = np.random.default_rng(0)
    target = rng.standard_normal(N).astype(np.float32)

    def train(sim, rounds, throttle_at=None, throttle_bps=None,
              tick=False):
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(N, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": LR})
        walls, losses = [], []
        w_hat = np.zeros(N, np.float32)
        for r in range(rounds):
            if throttle_at is not None and r == throttle_at:
                sim.fabric.fault.wan_bandwidth_bps = throttle_bps
            t0 = time.perf_counter()
            grads = [w_hat - target for _ in ws]  # same shard both
            for w, g in zip(ws, grads):
                w.push(0, g.astype(np.float32))
            outs = [w.pull_sync(0) for w in ws]
            for w in ws:
                w.wait_all()
            w_hat = outs[0]
            walls.append(time.perf_counter() - t0)
            losses.append(float(np.mean((w_hat - target) ** 2)))
            if tick:
                sim.wan_controller.tick()
        return walls, losses

    ROUNDS, THROTTLE_AT = 16, 4
    BPS = 4e6  # ~0.2 s per dense 800 KB push → dense rounds blow budget

    # adaptive run: starts vanilla, bandwidth collapses mid-run.  The
    # 1 s cooldown is load-bearing: it makes the engine observe each
    # tier's STEADY state (bsc's first pull is a one-time dense resync)
    # instead of overshooting down the ladder on transients.
    fault = FaultPolicy(wan_bandwidth_bps=1e12)  # send threads on
    sim = Simulation(_cfg(adapt_round_budget_s=0.15, adapt_cooldown_s=1.0,
                          adapt_window=3), fault=fault)
    try:
        walls_a, losses_a = train(sim, ROUNDS, throttle_at=THROTTLE_AT,
                                  throttle_bps=BPS, tick=True)
        st = sim.wan_controller.status()
    finally:
        sim.shutdown()
    assert st["epoch"] >= 1, "controller never downshifted"
    assert st["compression"]["type"] in ("fp16", "bsc", "2bit")
    # wall-time recovery: the last rounds run at a fraction of the worst
    # throttled-dense round AND inside the budget band the controller
    # was asked to hold
    worst = max(walls_a[THROTTLE_AT:THROTTLE_AT + 3])
    steady = float(np.median(walls_a[-3:]))
    assert steady < worst * 0.5, (worst, steady, walls_a)
    assert steady < 0.15 * 1.5, (steady, walls_a)

    # control: uninterrupted static BSC, full bandwidth, same rounds
    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=1)))
    try:
        ws = sim.all_workers()
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression(
                {"type": "bsc", "ratio": 0.01})
        _, losses_c = train(sim, ROUNDS)
    finally:
        sim.shutdown()
    # loss parity: both descended, and the adaptive run's final loss is
    # within tolerance of the static control's
    assert losses_a[-1] < losses_a[0] * 0.9
    assert losses_a[-1] <= losses_c[-1] * 1.5 + 1e-3, (
        losses_a[-1], losses_c[-1])
