from geomx_tpu.data.synthetic import (  # noqa: F401
    ShardedIterator, TokenIterator, synthetic_classification, synthetic_lm)
from geomx_tpu.data.recordio import (  # noqa: F401
    RecordReader, RecordWriter, pack_array, unpack_array,
    write_array_dataset,
)
from geomx_tpu.data.iterators import (  # noqa: F401
    AugmentIter, CSVIter, LibSVMIter, MNISTIter, PrefetchIter,
    RecordDatasetIter,
)
