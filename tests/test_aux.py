"""Aux subsystems: profiler + remote control, heartbeat/dead nodes,
server checkpoint/restore (SURVEY.md §5 parity)."""

import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.checkpoint import load_server_state, save_server_state
from geomx_tpu.utils import Profiler


def test_profiler_spans_and_dump(tmp_path):
    p = Profiler("test")
    p.start()
    with p.span("step"):
        with p.span("push", category="comm"):
            time.sleep(0.001)
    p.count("wan_bytes", 123)
    out = tmp_path / "trace.json"
    p.dump(str(out))
    import json
    trace = json.loads(out.read_text())
    names = [e["name"] for e in trace["traceEvents"]]
    assert "step" in names and "push" in names and "wan_bytes" in names
    assert p.stats()["counters"]["wan_bytes"] == 123
    p.pause()
    with p.span("ignored"):
        pass
    assert "ignored" not in [e["name"] for e in p._events]


def test_remote_profiler_control(tmp_path):
    sim = Simulation(Config(topology=Topology(num_parties=1, workers_per_party=1)))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        stats = w.set_server_profiler("state", run=True)
        assert all(isinstance(s, dict) for s in stats)
        w.push(0, np.ones(8, np.float32))
        w.pull_sync(0)
        w.set_server_profiler("dump", path=str(tmp_path / "prof"))
        dumps = list(tmp_path.glob("prof.*.json"))
        assert len(dumps) >= 2  # local + global server
    finally:
        sim.shutdown()


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    store = {5: np.arange(4, dtype=np.float32), 9: np.ones(2, np.float32)}
    save_server_state(path, store, {"opt": {"lr": 0.1}}, {"meta": 1})
    s2, opt, meta = load_server_state(path)
    np.testing.assert_array_equal(s2[5], store[5])
    assert opt == {"opt": {"lr": 0.1}} and meta == {"meta": 1}


def test_server_checkpoint_restore_resumes_training(tmp_path):
    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1))
    sim = Simulation(cfg)
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(16, np.float32))
        w.set_optimizer({"type": "adam", "lr": 0.1})
        for _ in range(3):
            w.push(0, np.ones(16, np.float32))
            before = w.pull_sync(0)
        paths = w.save_server_checkpoints(str(tmp_path))
        assert all((tmp_path / p.split("/")[-1]).exists() for p in paths)

        # wreck the state, then restore
        sim.global_servers[0].store = {
            k: np.zeros_like(v) for k, v in sim.global_servers[0].store.items()
        }
        w.load_server_checkpoints(str(tmp_path))
        after = w.pull_sync(0)
        np.testing.assert_allclose(after, before, rtol=1e-6)
        # adam state survived: another step keeps moving smoothly
        w.push(0, np.ones(16, np.float32))
        nxt = w.pull_sync(0)
        assert np.all(nxt < after)
    finally:
        sim.shutdown()


def test_heartbeat_dead_node_detection():
    cfg = Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        heartbeat_interval_s=0.05,
        heartbeat_timeout_s=0.5,
    )
    sim = Simulation(cfg)
    try:
        w = sim.all_workers()[0]
        time.sleep(0.2)
        assert w.num_dead_nodes() == 0
        # kill worker 1's postoffice (stops its heartbeat thread)
        dead = sim.topology.workers(0)[1]
        sim.offices[str(dead)].stop()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if w.num_dead_nodes() >= 1:
                break
            time.sleep(0.1)
        assert w.num_dead_nodes() >= 1
        names = sim.offices[str(sim.topology.scheduler(0))].dead_nodes()
        assert str(dead) in names
    finally:
        sim.shutdown()


def test_measure_phase_report_and_cluster_aggregate(tmp_path):
    """Per-phase step timing (ref: examples/utils.py:120-192 Measure)
    + cross-node aggregation (ref: src/profiler/aggregate_stats.cc)."""
    import json
    import time

    from geomx_tpu.utils import Measure, aggregate_reports

    m = Measure()
    for _ in range(3):
        m.step_start()
        with m.phase("grad"):
            time.sleep(0.005)
        with m.phase("push"):
            time.sleep(0.001)
        m.step_end()
    rep = m.report()
    assert rep["grad"]["count"] == 3
    assert rep["grad"]["mean_s"] >= 0.004
    assert rep["step"]["total_s"] >= rep["push"]["total_s"]
    m.dump(str(tmp_path / "measure.json"))
    loaded = json.load(open(tmp_path / "measure.json"))
    assert loaded["steps"] == 3

    agg = aggregate_reports({"worker:0@p0": loaded,
                             "worker:1@p0": {"phases": rep}})
    assert agg["grad"]["count"] == 6
    assert agg["grad"]["max_node"] in ("worker:0@p0", "worker:1@p0")


def test_run_worker_fills_measure():
    """The worker loop brackets grad/push/pull phases when handed a
    Measure; the profiler stats() now carries the per-span aggregate
    table for remote collection."""
    import numpy as np

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation
    from geomx_tpu.training import run_worker
    from geomx_tpu.utils import Measure, get_profiler

    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=1)))
    try:
        kv = sim.all_workers()[0]
        kv.set_optimizer({"type": "sgd", "lr": 0.1})
        m = Measure()

        def grad_fn(p, x, y):
            import jax.numpy as jnp
            g = {"w": jnp.ones_like(p["w"])}
            return jnp.float32(1.0), jnp.float32(0.0), g

        import jax.numpy as jnp
        params = {"w": jnp.zeros(16)}
        data = [(jnp.zeros(1), jnp.zeros(1))] * 3
        run_worker(kv, params, grad_fn, data, 3, barrier_init=False,
                   measure=m)
        rep = m.report()
        for phase in ("grad", "push", "pull_wait", "step"):
            assert rep[phase]["count"] == 3, rep
    finally:
        sim.shutdown()


def test_profiler_aggregate_table():
    from geomx_tpu.utils import get_profiler

    p = get_profiler("agg-test")
    p.start()
    import time as _t
    for _ in range(4):
        with p.span("merge"):
            _t.sleep(0.001)
    agg = p.aggregate()
    assert agg["merge"]["count"] == 4
    assert agg["merge"]["avg_us"] >= 900
    assert p.stats()["aggregate"]["merge"]["count"] == 4
