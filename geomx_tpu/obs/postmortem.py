"""Offline cross-node postmortem forensics over flight-recorder dumps.

``python -m geomx_tpu.obs.postmortem <dir>`` loads every
``flight_*.json`` the nodes dumped into ``<dir>`` (crash/exit hooks,
health-alert broadcasts, operator requests — see obs/flight.py),
rebases every node's events onto the global scheduler's clock using
the heartbeat RTT/2 offset estimates each dump carries (the same
chaining the trace collector uses: ``resolve_clock_offsets``), and
assembles ONE causal timeline plus a report that answers "why did
round X stall":

- **dead nodes** — plan nodes that left no dump (SIGKILL leaves none
  by definition), with the last instant any *surviving* node heard
  from them (peers' RECV events);
- **stalled shards/rounds** — per global shard, the last completed
  key-round and how long before the window end it happened; a shard
  whose holder is dead is named with the round it stalled at;
- **who fenced whom** — every FENCE event in the window;
- **saturation** — peak pressure readings per node (merge-lock wait,
  lane depth, van send-queue depth, codec-pool backlog);
- **straggler attribution** — per party, the last local round
  completion (the slowest party bounds the stalled FSA round);
- **transitions** — promotions / evictions / folds / handoffs, so the
  recovery that followed the incident is on the same timeline.

The assembler is pure offline file reading — it never touches a live
cluster.  See docs/observability.md ("Postmortem forensics").
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from geomx_tpu.trace.collector import _party_of, _shard_of, \
    resolve_clock_offsets

_GSCHED_PREFIX = "global_scheduler:"


def load_dumps(dump_dir: str) -> List[dict]:
    """Every parseable flight dump in ``dump_dir`` (a node may have
    several: per-incident + exit)."""
    out = []
    for path in sorted(glob.glob(os.path.join(dump_dir, "flight_*.json"))):
        try:
            with open(path) as f:
                body = json.load(f)
        except (OSError, ValueError):
            continue  # a torn/foreign file must not kill the assembly
        if isinstance(body, dict) and body.get("node"):
            body["_path"] = path
            out.append(body)
    return out


def assemble(dump_dir: str) -> dict:
    """Merge the dumps into one rebased timeline + findings dict."""
    dumps = load_dumps(dump_dir)
    if not dumps:
        return {"error": f"no flight dumps in {dump_dir}", "nodes": [],
                "dead": [], "timeline": [], "shards": {}, "fences": [],
                "transitions": [], "pressure": {}, "parties": {}}

    # ---- clock rebasing -----------------------------------------------------
    gname = None
    offsets_in: Dict[str, Dict[str, float]] = {}
    expected: set = set()
    by_node: Dict[str, List[dict]] = {}
    for d in dumps:
        node = str(d["node"])
        offs = d.get("clock_offsets") or {}
        if offs:
            offsets_in[node] = {str(k): float(v) for k, v in offs.items()}
        for n in d.get("topology") or ():
            expected.add(str(n))
            if str(n).startswith(_GSCHED_PREFIX):
                gname = gname or str(n)
        by_node.setdefault(node, []).append(d)
    if gname is None:  # no topology metadata: pick any scheduler target
        for o in offsets_in.values():
            for tgt in o:
                if tgt.startswith(_GSCHED_PREFIX):
                    gname = tgt
                    break
    offsets = resolve_clock_offsets(offsets_in, gname or "")

    # ---- merge events (dedup across a node's incident + exit dumps) ---------
    timeline: List[dict] = []
    seen = set()
    for node, ds in by_node.items():
        off = offsets.get(node, 0.0)
        for d in ds:
            for ev in d.get("events") or ():
                key = (node, ev.get("t"), ev.get("ev"), ev.get("a"),
                       ev.get("b"), ev.get("c"), ev.get("d"),
                       ev.get("peer"), ev.get("note"))
                if key in seen:
                    continue
                seen.add(key)
                e = dict(ev)
                e["node"] = node
                e["t"] = float(ev.get("t", 0.0)) + off
                timeline.append(e)
    timeline.sort(key=lambda e: e["t"])
    t0 = timeline[0]["t"] if timeline else 0.0
    t1 = timeline[-1]["t"] if timeline else 0.0

    # ---- dead nodes + last-heard attribution --------------------------------
    # A node may have dumped EARLIER incidents (a warn-level alert at
    # startup) and still have died later — "left any dump" is not
    # alive.  When exit-class dumps exist (the atexit/signal hooks'
    # incident, or an in-proc Simulation.dump_flight final sweep), a
    # plan node MISSING one is the corpse: a SIGKILL leaves no exit
    # dump by definition.  With no exit-class dump anywhere (a
    # mid-incident assembly), fall back to "left no dump at all".
    def _exit_class(inc) -> bool:
        return inc is None or str(inc).startswith(("exit", "signal"))

    dumped = set(by_node)
    have_exit = {n for n, ds in by_node.items()
                 if any(_exit_class(d.get("incident")) for d in ds)}
    alive = have_exit if have_exit else dumped
    dead = []
    for n in sorted(expected - alive):
        last, via = None, None
        for e in timeline:
            if e["ev"] == "RECV" and e.get("peer") == n:
                last, via = e["t"], e["node"]
        dead.append({"node": n, "last_heard_t": last, "last_heard_by": via})

    # ---- per-shard round progress ------------------------------------------
    shards: Dict[int, dict] = {}
    rounds_by_holder: Dict[str, int] = {}
    for e in timeline:
        k = _shard_of(e["node"])
        if k is None:
            continue
        s = shards.setdefault(k, {"holders": [], "last_complete_t": None,
                                  "key_rounds": 0, "stalled": False,
                                  "stalled_round": None, "dead_holder": None})
        if e["node"] not in s["holders"]:
            s["holders"].append(e["node"])
        if e["ev"] == "ROUND_COMPLETE":
            s["last_complete_t"] = e["t"]
            s["key_rounds"] = max(s["key_rounds"], int(e.get("b") or 0))
            rounds_by_holder[e["node"]] = max(
                rounds_by_holder.get(e["node"], 0), int(e.get("b") or 0))
    dead_names = {d["node"] for d in dead}
    for k, s in shards.items():
        dead_holders = [h for h in s["holders"] if h in dead_names] + [
            d["node"] for d in dead
            if _shard_of(d["node"]) == k and d["node"] not in s["holders"]]
        if dead_holders:
            s["dead_holder"] = dead_holders[0]
            s["stalled"] = True
            # prefer the DEAD holder's own last completed round (its
            # earlier incident dumps carry it) — the round the shard
            # stalled at is the one after the last round the corpse
            # finished, not whatever a promoted standby completed later
            own = rounds_by_holder.get(s["dead_holder"])
            s["stalled_round"] = (own if own is not None
                                  else s["key_rounds"]) + 1
        if s["last_complete_t"] is not None:
            s["gap_to_window_end_s"] = round(t1 - s["last_complete_t"], 3)
    # a dead plan global server with NO events anywhere still names its
    # shard as stalled (it died before any surviving dump's window)
    for d in dead:
        k = _shard_of(d["node"])
        if k is not None and k not in shards:
            shards[k] = {"holders": [], "last_complete_t": None,
                         "key_rounds": 0, "stalled": True,
                         "stalled_round": 1, "dead_holder": d["node"]}

    # ---- fences / transitions ----------------------------------------------
    fences = [e for e in timeline if e["ev"] == "FENCE"]
    transitions = [e for e in timeline
                   if e["ev"] in ("PROMOTE", "EVICT", "FOLD", "UNFOLD",
                                  "HANDOFF", "WARM_BOOT")]

    # ---- pressure peaks -----------------------------------------------------
    pressure: Dict[str, dict] = {}
    for e in timeline:
        if e["ev"] != "PRESSURE" or not e.get("note"):
            continue
        p = pressure.setdefault(e["node"], {})
        v = float(e.get("a") or 0) / 1e6  # recorded scaled by 1e6
        if v > p.get(e["note"], float("-inf")):
            p[e["note"]] = v

    # ---- straggler attribution (per party, last local round) ----------------
    parties: Dict[str, dict] = {}
    for e in timeline:
        if not e["node"].startswith("server:"):
            continue
        p = parties.setdefault(_party_of(e["node"]), {
            "server": e["node"], "last_round_t": None, "wan_rounds": 0})
        if e["ev"] == "ROUND_COMPLETE":
            p["last_round_t"] = e["t"]
            p["wan_rounds"] = max(p["wan_rounds"], int(e.get("b") or 0))
    straggler = None
    timed = {p: d["last_round_t"] for p, d in parties.items()
             if d["last_round_t"] is not None}
    if timed:
        straggler = min(timed, key=timed.get)

    return {
        "dir": dump_dir,
        "nodes": sorted(dumped),
        "num_dumps": len(dumps),
        "window": [t0, t1],
        "clock_offsets_s": offsets,
        "dead": dead,
        "shards": shards,
        "fences": fences,
        "transitions": transitions,
        "pressure": pressure,
        "parties": parties,
        "straggler_party": straggler,
        "timeline": timeline,
    }


def _rel(t: Optional[float], t0: float) -> str:
    return "?" if t is None else f"+{t - t0:.3f}s"


def report_text(result: dict) -> str:
    """The human-readable postmortem (what the demo script asserts on)."""
    if result.get("error"):
        return f"postmortem: {result['error']}"
    t0 = result["window"][0]
    lines = [
        f"postmortem: {result['num_dumps']} dump(s) from "
        f"{len(result['nodes'])} node(s), window "
        f"{result['window'][1] - t0:.3f}s "
        f"[{', '.join(result['nodes'])}]",
    ]
    for d in result["dead"]:
        heard = ("never heard from in the window" if d["last_heard_t"] is
                 None else f"last heard {_rel(d['last_heard_t'], t0)} "
                           f"by {d['last_heard_by']}")
        lines.append(f"DEAD: {d['node']} — no exit/crash dump; {heard}")
    for k in sorted(result["shards"]):
        s = result["shards"][k]
        if s["stalled"]:
            # ">=": the ring data between the corpse's last dump and
            # its death died with it — the recorded round is the best
            # (lower-bound) evidence a black box can leave
            lines.append(
                f"shard {k}: STALLED at round >={s['stalled_round']} — "
                f"holder {s['dead_holder']} dead; shard's last recorded "
                f"key-round {s['key_rounds']} at "
                f"{_rel(s['last_complete_t'], t0)}")
        else:
            lines.append(
                f"shard {k}: healthy — {s['key_rounds']} key-rounds, "
                f"last completed {_rel(s['last_complete_t'], t0)}")
    for e in result["transitions"]:
        if e["ev"] == "PROMOTE":
            lines.append(f"PROMOTED: {e.get('peer') or e['node']} "
                         f"(term {e.get('a')}) at {_rel(e['t'], t0)} "
                         f"[seen by {e['node']}]")
        elif e["ev"] == "HANDOFF":
            lines.append(f"HANDOFF: {e['node']} -> {e.get('peer')} "
                         f"(term {e.get('a')}) at {_rel(e['t'], t0)}")
        else:
            lines.append(f"{e['ev']}: {e.get('peer') or ''} at "
                         f"{_rel(e['t'], t0)} [by {e['node']}]")
    for e in result["fences"][-16:]:
        lines.append(f"FENCE: {e['node']} fenced {e.get('peer') or '-'} "
                     f"({e.get('note')}) at {_rel(e['t'], t0)}")
    for node in sorted(result["pressure"]):
        p = result["pressure"][node]
        bits = " ".join(f"{k}={v:.4g}" for k, v in sorted(p.items()))
        lines.append(f"pressure peak {node}: {bits}")
    if result.get("straggler_party") is not None:
        lines.append(f"straggler party: {result['straggler_party']} "
                     "(oldest last-completed local round)")
    # the causal tail: the last events involving each dead node, so the
    # report shows WHAT was in flight when the evidence stops
    for d in result["dead"]:
        tail = [e for e in result["timeline"]
                if e.get("peer") == d["node"]][-5:]
        for e in tail:
            lines.append(
                f"  tail[{d['node']}]: {_rel(e['t'], t0)} {e['node']} "
                f"{e['ev']} a={e.get('a')} c={e.get('c')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m geomx_tpu.obs.postmortem",
        description="assemble per-node flight-recorder dumps into one "
                    "causal timeline + stall report")
    ap.add_argument("dir", help="directory holding flight_*.json dumps "
                                "(GEOMX_OBS_DIR)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full findings dict (timeline "
                         "included) instead of the text report")
    ap.add_argument("--out", default="",
                    help="also write the findings JSON here (default "
                         "<dir>/postmortem.json; '-' disables)")
    args = ap.parse_args(argv)
    result = assemble(args.dir)
    if args.as_json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        print(report_text(result))
    out = args.out or os.path.join(args.dir, "postmortem.json")
    if out != "-":
        try:
            with open(out, "w") as f:
                json.dump(result, f, indent=1)
        except OSError:
            pass
    return 1 if result.get("error") else 0


if __name__ == "__main__":
    sys.exit(main())
