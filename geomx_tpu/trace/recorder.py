"""Per-node span recorder + batched reporter.

One ``Tracer`` per node (keyed like ``utils.get_profiler``).  Spans are
recorded as Chrome-trace events **into the node's existing Profiler
event buffer** (one buffer per node — the remote-profiler dump and the
distributed trace cannot drift apart), with the causal identity
(trace_id / span / parent) in ``args``.  A second reference to each
event dict sits in the tracer's pending batch until it is shipped to the
scheduler-side collector (``Ctrl.TRACE_REPORT``) — the dicts are shared,
never copied.

Timestamps: events carry the profiler-relative ``ts`` (so a per-node
``Profiler.dump`` stays coherent) plus an absolute ``t_mono_us`` in
``args`` — the collector merges on the monotonic clock, corrected by the
per-node offset estimated from heartbeat RTTs.

Overhead: ``span()`` / ``round()`` return the shared ``_NULL_SPAN``
whenever tracing is inactive or the current thread carries no sampled
context — no allocation, no branch beyond the gate, nothing stamped.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from geomx_tpu.trace import context as _ctx
from geomx_tpu.utils.profiler import Profiler, get_profiler


class _NullSpan:
    """Shared no-op span: the entire cost of an instrumented site when
    tracing is off (``tracer.span(...) is _NULL_SPAN``)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "cat", "_enter_ctx", "_prev", "span_id",
                 "parent", "trace_id", "_t0", "_t0_mono")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 trace_id: int, parent: int):
        self._tr = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.parent = parent
        self.span_id = _ctx.new_span_id()

    def __enter__(self):
        self._prev = _ctx.swap(_ctx.TraceContext(self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        self._t0_mono = time.monotonic()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter() - self._t0) * 1e6
        _ctx.restore(self._prev)
        self._tr._record(self.name, self.cat, dur_us, self.trace_id,
                         self.span_id, self.parent, self._t0_mono)
        return False


class Tracer:
    """Span recorder for one node; ship via :meth:`attach` + flush."""

    def __init__(self, node: str, profiler: Optional[Profiler] = None):
        self.node = node
        self.profiler = profiler or get_profiler(node)
        self._mu = threading.Lock()
        self._pending: List[dict] = []
        self._po = None  # postoffice, once attached
        self._collector = None  # in-proc shortcut (collector on this node)
        self.batch_events = 256
        self.dropped_events = 0
        self._cap = 100_000

    # ---- recording ----------------------------------------------------------
    def span(self, name: str, cat: str = "trace"):
        """Timed child span of the thread's current context (no-op when
        tracing is off or the context is unsampled)."""
        if not _ctx.ACTIVE:
            return _NULL_SPAN
        cur = _ctx.current()
        if cur is None:
            return _NULL_SPAN
        return _Span(self, name, cat, cur.trace_id, cur.span_id)

    def round(self, round_idx: int, sample_every: int):
        """Root span of one sampled round: every node derives the same
        ``trace_id`` from the round index, so the collector can merge
        all parties' round-N spans into one tree."""
        if (not _ctx.ACTIVE or sample_every <= 0
                or round_idx % sample_every != 0):
            return _NULL_SPAN
        return _Span(self, "round", "round",
                     _ctx.trace_id_for_round(round_idx), 0)

    def instant(self, name: str, span: int = 0, parent: int = 0,
                trace_id: int = 0, **extra):
        """Zero-duration event.  With ``trace_id`` (the message hooks:
        wan.send / wan.recv) it joins that trace; without one it adopts
        the thread's context when present, else records traceless — how
        failover / eviction control events land on the shared timeline
        even though no sampled round is open around them."""
        if not _ctx.ACTIVE:
            return
        if trace_id == 0:
            cur = _ctx.current()
            if cur is not None:
                trace_id, parent = cur.trace_id, cur.span_id
        self._record(name, "event", 0.0, trace_id,
                     span or _ctx.new_span_id(), parent,
                     time.monotonic(), **extra)

    def _record(self, name: str, cat: str, dur_us: float, trace_id: int,
                span: int, parent: int, t_mono: float, **extra):
        prof = self.profiler
        ev = {
            "name": name, "cat": cat, "ph": "X" if dur_us else "i",
            "ts": (t_mono - prof.t0_mono) * 1e6,
            "dur": dur_us,
            "pid": self.node, "tid": threading.current_thread().name,
            "args": {"trace_id": trace_id, "span": span, "parent": parent,
                     "t_mono_us": t_mono * 1e6, **extra},
        }
        prof.add_event(ev)
        with self._mu:
            if len(self._pending) >= self._cap:
                self.dropped_events += 1
                return
            self._pending.append(ev)
            ship = (self._po is not None
                    and len(self._pending) >= self.batch_events)
        if ship:
            self.flush()

    # ---- shipping -----------------------------------------------------------
    def attach(self, postoffice, collector=None) -> "Tracer":
        """Bind to this node's postoffice; completed spans batch-ship to
        the global scheduler's collector (or straight into ``collector``
        when it lives on this very node)."""
        self._po = postoffice
        self._collector = collector
        return self

    def flush(self) -> int:
        """Ship every pending span to the collector; returns the count.
        Safe to call with nothing attached (spans just keep pending)."""
        with self._mu:
            if not self._pending or self._po is None:
                return 0
            batch, self._pending = self._pending, []
        body = {"node": self.node, "spans": batch,
                "offsets": self._po.clock_offsets()}
        if self._collector is not None:
            self._collector.ingest(body)
            return len(batch)
        from geomx_tpu.kvstore.common import APP_PS, Ctrl
        from geomx_tpu.transport.message import Domain, Message

        with _ctx.suppressed():  # trace traffic never traces itself
            try:
                self._po.van.send(Message(
                    recipient=self._po.topology.global_scheduler(),
                    domain=Domain.GLOBAL, app_id=APP_PS, customer_id=0,
                    request=True, cmd=int(Ctrl.TRACE_REPORT), body=body))
            except (KeyError, OSError):
                # collector down/unreachable: re-queue rather than lose
                # the batch (bounded by _cap like everything else)
                with self._mu:
                    self._pending = batch + self._pending
                    del self._pending[self._cap:]
                return 0
        return len(batch)

    def pending(self) -> int:
        with self._mu:
            return len(self._pending)

    def reset(self) -> None:
        """Drop unshipped spans (a fresh deployment reusing this node
        name must not inherit a previous run's leftovers — round-derived
        trace ids would collide across runs)."""
        with self._mu:
            self._pending.clear()


_tracers: Dict[str, Tracer] = {}
_mu = threading.Lock()


def get_tracer(node: str) -> Tracer:
    with _mu:
        t = _tracers.get(node)
        if t is None:
            t = _tracers[node] = Tracer(node)
        return t
