"""TCP fabric + multi-process launcher tests (the reference's
pseudo-distributed acceptance style, ref: tests/local.sh launching
role-tagged local processes)."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.transport import Domain, Message, Van
from geomx_tpu.transport.tcp import TcpFabric, default_address_plan


def free_base_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_tcp_fabric_roundtrip():
    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=free_base_port())
    fab = TcpFabric(plan)
    a, b = topo.workers(0)[0], topo.server(0)
    van_a, van_b = Van(a, fab), Van(b, fab)
    got = []
    ev = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m), ev.set()))
    van_a.send(Message(recipient=b, timestamp=3,
                       keys=np.array([1], np.int64),
                       vals=np.arange(5, dtype=np.float32),
                       lens=np.array([5], np.int64)))
    assert ev.wait(5)
    np.testing.assert_array_equal(got[0].vals, np.arange(5, dtype=np.float32))
    assert got[0].sender == a and got[0].timestamp == 3
    van_a.stop(); van_b.stop(); fab.shutdown()


@pytest.mark.slow
def test_launcher_full_topology_subprocess():
    """Stand up 1 party (scheduler+server+worker) + global tier as real
    OS processes over TCP; the worker trains and shuts the cluster down."""
    topo = Topology(num_parties=1, workers_per_party=1)
    base = free_base_port()
    env = dict(os.environ)
    env["JAX_PLATFORM_NAME"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    roles = [str(n) for n in topo.all_nodes()]
    procs = {}
    try:
        for r in roles:
            procs[r] = subprocess.Popen(
                [sys.executable, "-m", "geomx_tpu.launch", "--role", r,
                 "--parties", "1", "--workers", "1",
                 "--base-port", str(base), "--steps", "3"],
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        worker_out = outputs[str(topo.workers(0)[0])]
        assert "steps=3" in worker_out, worker_out
        for r, p in procs.items():
            assert p.returncode == 0, f"{r} rc={p.returncode}: {outputs[r][-800:]}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
