#!/usr/bin/env bash
# Serve-tier demo: a real OS-process topology over TCP with TWO read
# replicas serving pull traffic while training runs; SIGKILL replica 0
# mid-serve and assert
# (a) the SURVIVOR keeps answering reads within the staleness bound
#     (serve.load --assert-staleness against replica 1),
# (b) the console (`python -m geomx_tpu.status`) flips replica 0 to
#     DOWN and the global scheduler logs the eviction (tracked views
#     pruned at every shard),
# (c) a RESTARTED replica 0 rejoins (the eviction/recovery pair in the
#     scheduler log) and serves within the bound again, and
# (d) training ran to completion throughout.
#
# The pytest acceptance (tests/test_serve.py::test_e2e_reads_survive_
# shard_sigkill_under_training) is the in-proc shard-failover version;
# this script is the operator-facing replica-churn tour.
# See docs/serving.md.
#
# A fourth phase (ISSUE 15, the serving plane) drives BALANCED reads
# (`serve.load --balance`: p2c over both replicas, health ejection,
# shed honoring) and SIGKILLs replica 1 mid-load: the balancer must
# fail over within the staleness bound (failovers >= 1, reads stay
# staleness-asserted) and the shed fraction must stay bounded.
#
# Env: GEOMX_BASE_PORT (default 9560), STEPS (default 900)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_SERVE_REPLICAS=2
export GEOMX_SERVE_STALENESS_S=2.0
export GEOMX_SERVE_REFRESH_S=0.2
export GEOMX_HEARTBEAT_INTERVAL=0.2
export GEOMX_HEARTBEAT_TIMEOUT=1.5
export GEOMX_REQUEST_RETRY_S=1.0
export GEOMX_RETRY_BACKOFF_CAP=2
export GEOMX_OBS=1
export GEOMX_OBS_INTERVAL=0.2
# pace the worker (~40 ms/step): training must outlive the kill +
# restart + the console polls
export GEOMX_TEST_STEP_SLEEP_MS='{"worker:0@p0": 40}'

BASE=${GEOMX_BASE_PORT:-9560}
export GEOMX_BASE_PORT=$BASE
STEPS=${STEPS:-900}
OUT=$(mktemp -d)
trap 'kill $(jobs -p) 2>/dev/null || true; rm -rf "$OUT"' EXIT

launch() { # role  (bsc pull compression so the replicas ride the
  #                 sparse-delta subscription, and the eviction prune
  #                 has tracked views to free)
  python -m geomx_tpu.launch --role "$1" --parties 1 --workers 1 \
    --replicas 2 --base-port "$BASE" --obs-interval 0.2 \
    --compression bsc --steps "$STEPS" >"$OUT/${1//[:@]/_}.log" 2>&1 &
}

launch global_scheduler:0
launch global_server:0
launch scheduler:0@p0
launch server:0@p0
launch replica:0
REPLICA0_PID=$!
launch replica:1
REPLICA1_PID=$!
launch worker:0@p0
WORKER_PID=$!

for _ in $(seq 1 240); do
  grep -q "training begins" "$OUT/worker_0_p0.log" 2>/dev/null && break
  sleep 0.5
done
grep -q "training begins" "$OUT/worker_0_p0.log" \
  || { echo "FAIL: worker never started training"; tail "$OUT/worker_0_p0.log"; exit 1; }
sleep 2  # a few rounds + replica refreshes

echo "== reads against BOTH replicas (staleness-asserted) =="
python -m geomx_tpu.serve.load --replica 0 --seconds 2 --assert-staleness \
  >"$OUT/load0_before.txt" || { echo "FAIL: replica 0 load"; cat "$OUT/load0_before.txt"; exit 1; }
cat "$OUT/load0_before.txt"
python -m geomx_tpu.serve.load --replica 1 --seconds 2 --assert-staleness \
  >"$OUT/load1_before.txt" || { echo "FAIL: replica 1 load"; cat "$OUT/load1_before.txt"; exit 1; }
cat "$OUT/load1_before.txt"

echo "== SIGKILL replica 0 (pid $REPLICA0_PID) =="
kill -9 "$REPLICA0_PID"

echo "== survivor keeps serving within the bound =="
python -m geomx_tpu.serve.load --replica 1 --seconds 3 --assert-staleness \
  >"$OUT/load1_after.txt" || { echo "FAIL: survivor violated the staleness bound"; cat "$OUT/load1_after.txt"; exit 1; }
cat "$OUT/load1_after.txt"

# console: replica 0 flips to DOWN once its heartbeats expire
FLIPPED=0
for _ in $(seq 1 20); do
  kill -0 "$WORKER_PID" 2>/dev/null \
    || { echo "FAIL: training ended before the console saw the kill"; exit 1; }
  python -m geomx_tpu.status --timeout 3 >"$OUT/status_after.txt" 2>/dev/null || true
  if grep -q "replica 0: replica:0 \[DOWN\]" "$OUT/status_after.txt" \
     && grep -q "replica 1: replica:1 \[up\]" "$OUT/status_after.txt"; then
    FLIPPED=1; break
  fi
  sleep 0.5
done
echo "== status after the kill =="
cat "$OUT/status_after.txt"
[ "$FLIPPED" = 1 ] \
  || { echo "FAIL: console never showed replica 0 DOWN / replica 1 up"; exit 1; }

GS="$OUT/global_scheduler_0.log"
for _ in $(seq 1 20); do
  grep -q "evicted replica replica:0" "$GS" && break
  sleep 0.5
done
grep -q "evicted replica replica:0" "$GS" \
  || { echo "FAIL: scheduler never logged the replica eviction"; grep replica "$GS" || true; exit 1; }
grep -q "pruned .* tracked pull view" "$OUT/global_server_0.log" \
  || { echo "FAIL: global server never pruned the dead replica's views"; exit 1; }

echo "== restart replica 0 (rejoin) =="
launch replica:0
for _ in $(seq 1 30); do
  grep -q "replica replica:0 resumed heartbeats" "$GS" && break
  sleep 0.5
done
grep -q "replica replica:0 resumed heartbeats" "$GS" \
  || { echo "FAIL: scheduler never logged the rejoin"; grep replica "$GS" || true; exit 1; }
python -m geomx_tpu.serve.load --replica 0 --seconds 2 --assert-staleness \
  >"$OUT/load0_after.txt" || { echo "FAIL: rejoined replica 0 load"; cat "$OUT/load0_after.txt"; exit 1; }
cat "$OUT/load0_after.txt"

echo "== serving-plane churn: balanced reads fail over a SIGKILL =="
# replica 0 is back, replica 1 about to die: the balancer must absorb
# the kill with ONE bounded failed attempt, keep every successful read
# under the staleness bound, and keep sheds explicit and bounded
( sleep 1.5; kill -9 "$REPLICA1_PID" 2>/dev/null || true ) &
KILLER_PID=$!
python -m geomx_tpu.serve.load --balance --seconds 5 --assert-staleness \
  --max-shed-frac 0.5 >"$OUT/load_balance.txt" \
  || { echo "FAIL: balanced load under replica churn"; cat "$OUT/load_balance.txt"; exit 1; }
wait "$KILLER_PID" 2>/dev/null || true
cat "$OUT/load_balance.txt"
FAILOVERS=$(sed -n 's/.*failovers=\([0-9][0-9]*\).*/\1/p' "$OUT/load_balance.txt")
[ "${FAILOVERS:-0}" -ge 1 ] \
  || { echo "FAIL: balancer never failed over after the SIGKILL"; exit 1; }

wait "$WORKER_PID" || true
grep -q "steps=$STEPS" "$OUT/worker_0_p0.log" \
  || { echo "FAIL: training did not finish all steps"; exit 1; }
echo "OK: survivor served within the bound through the kill, console + logs showed the eviction/rejoin pair, the balancer failed over the SIGKILL with bounded sheds, training completed"
