"""ctypes bindings for the native codec library.

Build is on-demand: first import compiles ``libgeocodecs.so`` with the
Makefile (g++; pybind11 isn't available in this environment, so the C ABI
+ ctypes is the binding layer).  If no toolchain is present the import
degrades gracefully — ``available() == False`` and callers fall back to
the numpy implementations, which remain the semantic reference.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libgeocodecs.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i64 = ctypes.c_int64
_f32 = ctypes.c_float


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "-C", _DIR, "libgeocodecs.so"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_SO)
    except (OSError, subprocess.SubprocessError):
        return False


def _stale() -> bool:
    """True when any source is newer than the built library (a rebuilt
    tree with an old .so would otherwise miss newly added symbols)."""
    try:
        so_mtime = os.path.getmtime(_SO)
    except OSError:
        return True
    for f in os.listdir(_DIR):
        if f.endswith(".cc") and os.path.getmtime(os.path.join(_DIR, f)) > so_mtime:
            return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if _stale() and not _build() and not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.geo_pack2bit.argtypes = [_f32p, _f32p, _u8p, _i64, _f32]
        lib.geo_unpack2bit.argtypes = [_u8p, _f32p, _i64, _f32]
        lib.geo_dgc_update.argtypes = [_f32p, _f32p, _f32p, _i64, _f32]
        lib.geo_topk_abs.argtypes = [_f32p, _i64, _i64, _i64p]
        lib.geo_topk_abs.restype = _i64
        lib.geo_select_threshold.argtypes = [_f32p, _i64, _f32, _i64, _i64p]
        lib.geo_select_threshold.restype = _i64
        lib.geo_sparse_add.argtypes = [_f32p, _f32p, _i64p, _i64]
        # newer symbols may be absent from a stale .so we couldn't rebuild
        # (no toolchain); callers probe with hasattr so the codec symbols
        # above keep accelerating either way
        if hasattr(lib, "geo_recordio_index"):
            lib.geo_recordio_index.argtypes = [_u8p, _i64, _i64, _i64p, _i64p]
            lib.geo_recordio_index.restype = _i64
        if hasattr(lib, "geo_axpy_acc"):
            lib.geo_axpy_acc.argtypes = [_f32p, _f32p, _i64, ctypes.c_int]
        _lib = lib
        return _lib


def lib() -> Optional[ctypes.CDLL]:
    return _load()


def available() -> bool:
    return _load() is not None


def accumulate(acc: np.ndarray, v: np.ndarray, threads: int = 0) -> None:
    """acc += v with the native threaded kernel when available (the
    server merge hot loop; ref: engine-pool-scheduled merge,
    kvstore_dist_server.h:1277-1296).  ``threads`` 0 = one per core.
    Falls back to numpy (single-threaded) without the library."""
    l = _load()
    if (l is not None and hasattr(l, "geo_axpy_acc")
            and acc.dtype == np.float32 and v.dtype == np.float32
            and len(acc) == len(v)
            and acc.flags.c_contiguous and v.flags.c_contiguous):
        if threads <= 0:
            threads = os.cpu_count() or 1
        l.geo_axpy_acc(acc, v, len(acc), threads)
    else:
        acc += v
