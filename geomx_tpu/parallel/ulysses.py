"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to ring attention (absent from the
reference — SURVEY.md §2.3 lists no SP/CP anywhere; this is a TPU-design
addition): instead of rotating K/V blocks around a ring, one all-to-all
re-shards the activations from sequence-sharded to head-sharded, every
device runs *dense* attention over the full sequence for its slice of
heads, and a second all-to-all restores sequence sharding.

Trade-off vs the ring: 2 collectives total instead of ``sp`` neighbor
hops (better for small ``sp`` over fast ICI all-to-alls; requires the
per-shard head count to divide by ``sp``), and the full sequence's K/V
for one head group must fit on a device.  Use inside ``shard_map`` over a mesh with
an ``sp`` axis, q/k/v pre-sharded on their sequence dimension.
"""

from __future__ import annotations

import jax
from jax import lax

from geomx_tpu.compat import axis_size as _axis_size

from geomx_tpu.parallel.ring_attention import (
    dense_attention, fast_dense_attention)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    fast: bool = False,
) -> jax.Array:
    """Exact attention via head↔sequence all-to-all re-sharding.

    Shapes (per device): q/k/v ``[B, T_local, H, D]`` with the global
    sequence laid out contiguously by sp rank (same contract as
    ring_attention).  Returns ``[B, T_local, H, D]`` in q.dtype.
    """
    P = _axis_size(axis_name)
    H = q.shape[2]
    if H % P != 0:
        raise ValueError(
            f"ulysses_attention needs the per-shard head count ({H} heads "
            f"visible inside shard_map) divisible by the '{axis_name}' "
            f"axis size ({P}); use ring_attention otherwise")

    def seq_to_heads(x):  # [B, T/P, H, D] -> [B, T, H/P, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # [B, T, H/P, D] -> [B, T/P, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    attn = fast_dense_attention if fast else dense_attention
    o = attn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
             causal=causal)
    return heads_to_seq(o).astype(q.dtype)
