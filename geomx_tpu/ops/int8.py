"""INT8 post-training quantization for inference.

The reference ships an INT8 quantization subsystem (ref:
src/operator/quantization/ — quantize/dequantize/quantized_fully_connected
/quantized_conv with calibration) targeting VNNI/cuDNN int8 paths.  The
TPU-native equivalent targets the MXU's int8 systolic mode: weights are
quantized ahead of time (symmetric per-output-channel int8 + f32 scales),
activations dynamically per batch (symmetric per-tensor), and the matmul
runs int8×int8→int32 via ``lax.dot_general`` with
``preferred_element_type=int32`` — exactly the layout XLA lowers onto the
MXU — then dequantizes into f32.

Everything is functional and jit-friendly: no Python branching on data,
static shapes throughout.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def quantize_symmetric(x: jax.Array, axis=None) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization: returns (q, scale) with
    ``x ≈ q * scale``.  ``axis`` keeps independent scales along that axis
    (per-output-channel for weight matrices); None = per-tensor."""
    absmax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def int8_matmul(x: jax.Array, w_q: jax.Array, w_scale: jax.Array
                ) -> jax.Array:
    """``x @ w`` with dynamically-quantized activations.

    x: [..., K] float; w_q: [K, N] int8 with per-column scales
    w_scale: [1, N].  Accumulates in int32 (the MXU-native int8 path),
    dequantizes with the product of both scales.
    """
    x_q, x_scale = quantize_symmetric(x)
    acc = lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * x_scale * w_scale


def quantize_dense_tree(params):
    """Post-training quantization of a flax param tree: every 2-D kernel
    becomes (int8 kernel, per-column scale); biases and the tree layout
    are unchanged (ref: the calibration-then-convert flow of
    quantization.py quantize_model).

    Returns a tree of the same structure where each quantized kernel
    leaf is a dict {"q": int8 [K,N], "scale": f32 [1,N]}."""

    def convert(leaf):
        if getattr(leaf, "ndim", None) == 2:  # jax OR numpy kernels
            q, scale = quantize_symmetric(jnp.asarray(leaf), axis=0)
            return {"q": q, "scale": scale}
        return leaf

    return jax.tree_util.tree_map(convert, params)


def make_quantized_mlp_apply():
    """Quantized-inference forward for the zoo MLP family.

    The layout is the MLP's by construction — flatten, then
    ``Dense_0..Dense_{n-1}`` with ReLU between (see
    geomx_tpu/models/zoo.py MLP); every Dense runs through int8_matmul.

    Usage::

        _, params, _ = create_mlp_state(rng)
        qtree = quantize_dense_tree(params)
        q_apply = make_quantized_mlp_apply()
        logits = q_apply(qtree, x)
    """

    def apply(qparams, x):
        layers = qparams["params"]
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        n = len(layers)
        for i in range(n):
            lyr = layers[f"Dense_{i}"]
            x = int8_matmul(x, lyr["kernel"]["q"], lyr["kernel"]["scale"])
            x = x + lyr["bias"].astype(jnp.float32)
            if i < n - 1:
                x = jax.nn.relu(x)
        return x

    return apply
