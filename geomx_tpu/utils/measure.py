"""Per-phase step timing: the reference examples' ``Measure`` report
(ref: examples/utils.py:120-192 — each training phase timed per
iteration, dumped as a JSON report) so perf regressions between rounds
are attributable to a phase, not just a slower total.

``Measure`` is handed to the worker loop, which brackets its phases
(grad compute / push / pull-wait); ``report()`` gives per-phase
aggregates and ``dump()`` writes the JSON artifact.  Cross-node
aggregation (the reference's aggregate-stats table,
ref: src/profiler/aggregate_stats.cc) merges reports or profiler stats
from many nodes into one table.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Dict, List


class Measure:
    def __init__(self):
        self._mu = threading.Lock()
        self._durs: Dict[str, List[float]] = {}
        self._step_t0: float | None = None
        self.steps = 0

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._mu:
                self._durs.setdefault(name, []).append(dt)

    def step_start(self):
        self._step_t0 = time.perf_counter()

    def step_end(self):
        if self._step_t0 is not None:
            with self._mu:
                self._durs.setdefault("step", []).append(
                    time.perf_counter() - self._step_t0)
            self.steps += 1
            self._step_t0 = None

    def report(self) -> dict:
        """Per-phase {count, total_s, mean_s, max_s} (ref: the per-phase
        rows of examples/utils.py's report)."""
        with self._mu:
            out = {}
            for name, ds in self._durs.items():
                out[name] = {
                    "count": len(ds),
                    "total_s": round(sum(ds), 6),
                    "mean_s": round(sum(ds) / len(ds), 6),
                    "max_s": round(max(ds), 6),
                }
            return out

    def dump(self, path: str):
        with open(path, "w") as f:
            json.dump({"steps": self.steps, "phases": self.report()}, f,
                      indent=2)


def aggregate_reports(reports: Dict[str, dict]) -> dict:
    """Merge per-node phase reports into one cluster table
    (ref: aggregate_stats.cc — one row per op/phase across devices):
    {phase: {count, total_s, mean_s, max_s, max_node}}."""
    agg: Dict[str, dict] = {}
    for node, report in reports.items():
        phases = report.get("phases", report)
        for name, row in phases.items():
            a = agg.setdefault(name, {"count": 0, "total_s": 0.0,
                                      "max_s": 0.0, "max_node": None})
            a["count"] += row["count"]
            a["total_s"] = round(a["total_s"] + row["total_s"], 6)
            if row["max_s"] >= a["max_s"]:
                a["max_s"] = row["max_s"]
                a["max_node"] = node
    for a in agg.values():
        a["mean_s"] = round(a["total_s"] / max(1, a["count"]), 6)
    return agg
