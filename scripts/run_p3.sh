#!/usr/bin/env bash
# Acceptance config: p3 (mirrors the reference scripts/cpu/run_p3.sh)
exec "$(dirname "$0")/run_cluster.sh" --p3
