"""Epoch-fenced WAN reconfiguration controller (global scheduler).

The controller closes the loop PR 3 left open: it samples the signal
estimators, asks the hysteresis policy engine for a decision, and
actuates it with a two-phase, epoch-fenced broadcast of
``Ctrl.SET_WAN_POLICY {epoch, compression}``:

1. **receivers first** — every global server adopts the new policy
   immediately (decode parameters + a rebuilt pull compressor whose
   tracked views are invalidated through the existing version-handshake
   path, so subscribers resync dense on their next pull);
2. **senders second** — every local server stores the policy as
   *pending* and applies it atomically at its next WAN round boundary
   (a round's whole push batch is always encoded under one epoch).

Gradient pushes carry ``Message.policy_epoch``; a receiver on a
different epoch fences the payload with a **retryable** error that also
carries its current policy, and the sender re-encodes the stashed raw
gradients under that policy and retries — so a broadcast lost to either
side never corrupts a merge and never wedges a round (see
docs/adaptive-wan.md for the full protocol walk-through).

Every decision is (a) counted/gauged in the system-metrics registry
(``<gsched>.wan_policy_*``), (b) stamped as a trace instant
(``wanpolicy.decision``) so it lands on the PR 3 merged timeline, and
(c) printed — three independent ways to audit what the loop did.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from geomx_tpu.core.config import Config, Role
from geomx_tpu.control.policy import Decision, WanPolicyEngine
from geomx_tpu.control.signals import SignalEstimator
from geomx_tpu.kvstore.common import APP_PS, Ctrl
from geomx_tpu.ps import Postoffice
from geomx_tpu.ps.kv_app import _App
from geomx_tpu.trace.recorder import get_tracer
from geomx_tpu.transport.message import Domain, Message
from geomx_tpu.utils.metrics import system_counter, system_gauge

# customer id for the controller's command endpoint on the scheduler's
# postoffice (the TraceCollector owns customer 0 when tracing is on;
# responses route by exact (app, customer), so they never collide)
_CTRL_CUSTOMER = 96


class _CmdEndpoint(_App):
    """Command-channel-only app: sends Ctrl.* requests, collects
    replies.  Never sees data traffic."""

    def _process(self, msg: Message):
        if not msg.push and not msg.pull:
            self._handle_command(msg)
        # a stray data message at the controller endpoint is dropped

    def rpc(self, recipient, head, body=None, timeout: float = 3.0,
            domain: Domain = Domain.GLOBAL) -> Optional[dict]:
        """One command round trip; None on timeout (peer down — the
        next sweep retries, same contract as the eviction monitors)."""
        ts = self.send_cmd(recipient, head, body=body, domain=domain,
                           wait=False)
        try:
            self.customer.wait(ts, timeout=timeout)
        except TimeoutError:
            return None
        reply = self.cmd_response(ts)
        return reply if isinstance(reply, dict) else {}


class AdaptiveWanController:
    """One per deployment, on the global scheduler's postoffice."""

    def __init__(self, postoffice: Postoffice,
                 config: Optional[Config] = None, collector=None,
                 metrics=None):
        assert postoffice.node.role is Role.GLOBAL_SCHEDULER, \
            "the adaptive WAN controller runs on the global scheduler"
        self.po = postoffice
        self.config = config or postoffice.config
        self.topology = postoffice.topology
        self.collector = collector  # TraceCollector (optional)
        self.metrics = metrics      # MetricsCollector (optional): when
        #                             the telemetry plane already pumps
        #                             QUERY_STATS-equivalent samples on
        #                             an interval, the controller reads
        #                             those instead of issuing its own
        #                             per-server QUERY_STATS sweeps
        self.metrics_samples = 0    # sweeps served from collected series
        cfg = self.config
        base = self._base_compression(cfg)
        self.engine = WanPolicyEngine(
            base,
            inter_ts=cfg.enable_inter_ts, hfa=cfg.use_hfa,
            budget_s=cfg.adapt_round_budget_s,
            deadband=cfg.adapt_deadband,
            cooldown_s=cfg.adapt_cooldown_s,
        )
        self.signals = SignalEstimator(window=cfg.adapt_window)
        self.epoch = 0
        self._mu = threading.Lock()
        self._acked: Dict[str, int] = {}   # server -> last acked epoch
        self._tr = get_tracer(str(postoffice.node))
        self._epoch_gauge = system_gauge(f"{postoffice.node}.wan_policy_epoch")
        self._epoch_gauge.set(0)
        self._counters = {a: system_counter(
            f"{postoffice.node}.wan_policy_{a}s")
            for a in ("downshift", "upshift", "manual")}
        self.refused = 0   # servers that rejected a policy (constraint)
        # global-tier failover / key-range reassignment: a promoted
        # standby (or a drain's merge target) replaces the old holder in
        # the broadcast target set.  ShardTargets is the shared
        # NEW_PRIMARY tracker every shard-addressing component uses (the
        # failover monitor self-delivers its broadcasts so this hook
        # fires even though both live on the same postoffice);
        # _broadcast_missing then reaches the new node
        from geomx_tpu.kvstore.replication import ShardTargets

        self._shard_targets = ShardTargets(postoffice)
        self._app = _CmdEndpoint(APP_PS, _CTRL_CUSTOMER, postoffice)
        self._stop = threading.Event()
        self._thread = None
        if cfg.adapt_interval_s > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"adaptive-wan-{postoffice.node}")
            self._thread.start()

    @staticmethod
    def _base_compression(cfg: Config) -> dict:
        base = {"type": cfg.compression or "none",
                "ratio": cfg.bsc_ratio,
                "momentum": cfg.bsc_momentum,
                "sample_rate": cfg.bsc_sample_rate,
                "threshold": cfg.twobit_threshold}
        if base["type"] == "mpq":
            base["size_bound"] = cfg.mpq_size_bound
        return base

    # ---- sampling loop ------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.config.adapt_interval_s):
            try:
                self.tick()
            except Exception:  # a sweep error must not kill the loop
                import logging

                logging.getLogger(__name__).exception(
                    "%s: adaptive-WAN sweep failed", self.po.node)

    def tick(self) -> Optional[Decision]:
        """One control iteration: sample -> decide -> actuate.  Also the
        deterministic entry point tests drive directly
        (``adapt_interval_s=0`` runs no sweep thread)."""
        stats = self._sample_servers()
        report = None
        if self.collector is not None:
            try:
                report = self.collector.critical_path()
            except Exception:  # pragma: no cover - collector mid-stop
                report = None
        sig = self.signals.ingest(time.monotonic(), stats, report)
        decision = self.engine.observe(sig)
        if decision is not None:
            self._actuate(decision)
        else:
            # re-deliver the current policy to any server that has not
            # acked it (it was down / unreachable at decision time) —
            # this is what bounds how long a fence-retry loop can last
            self._broadcast_missing()
        return decision

    def _sample_servers(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        max_age = max(2.0 * self.config.adapt_interval_s,
                      2.0 * getattr(self.config, "obs_interval_s", 0.0),
                      2.0)
        for s in self.topology.servers():
            if self.metrics is not None:
                # collected-series fast path: the pump's sample IS the
                # QUERY_STATS body, so a fresh ring entry replaces one
                # RPC round trip per server per sweep
                stats = self.metrics.latest_stats(str(s), max_age_s=max_age)
                if stats is not None:
                    out[str(s)] = stats
                    self.metrics_samples += 1
                    continue
            reply = self._app.rpc(s, Ctrl.QUERY_STATS, timeout=2.0)
            if reply is not None:
                out[str(s)] = reply
        return out

    # ---- actuation ----------------------------------------------------------
    def set_policy(self, compression: dict,
                   reason: str = "manual") -> Decision:
        """Manual override (``Simulation.set_wan_policy`` / operators):
        validated against the same constraint predicate as automatic
        decisions, then broadcast under a fresh epoch."""
        from geomx_tpu.compression.codecs import compression_allowed

        ok, why = compression_allowed(
            compression.get("type", "none"),
            inter_ts=self.config.enable_inter_ts, hfa=self.config.use_hfa)
        if not ok:
            raise ValueError(why)
        d = self.engine.force(dict(compression), reason=reason)
        self._actuate(d)
        return d

    def _actuate(self, decision: Decision):
        with self._mu:
            self.epoch += 1
            epoch = self.epoch
        self._epoch_gauge.set(epoch)
        self._counters.get(decision.action,
                           self._counters["manual"]).inc()
        # the decision lands on the PR 3 merged timeline even when no
        # sampled round is open (traceless instant, like failover events)
        self._tr.instant(
            "wanpolicy.decision", epoch=epoch, action=decision.action,
            codec=decision.compression.get("type"),
            reason=decision.reason,
            round_time_s=decision.round_time_s,
            budget_s=decision.budget_s)
        print(f"{self.po.node}: WAN policy epoch {epoch} "
              f"[{decision.action}] -> {decision.compression} "
              f"({decision.reason})", flush=True)
        self._broadcast(epoch, decision.compression)

    def _policy_body(self, epoch: int, compression: dict) -> dict:
        body = {"epoch": epoch, "compression": dict(compression)}
        # fill codec knobs from config so every server sees a complete
        # parameter set (same defaulting as set_gradient_compression)
        defaults = {"ratio": self.config.bsc_ratio,
                    "momentum": self.config.bsc_momentum,
                    "sample_rate": self.config.bsc_sample_rate,
                    "threshold": self.config.twobit_threshold,
                    "size_bound": self.config.mpq_size_bound}
        body["compression"] = {**defaults, **body["compression"]}
        return body

    def _targets(self) -> List:
        """Receivers FIRST (the CURRENT holder of every global shard —
        failover- and reassignment-aware — adopts immediately), then the
        senders (local servers, apply at their next round boundary) —
        the ordering that makes an in-flight old-epoch push the rare
        case rather than the common one.  One policy epoch covers every
        shard: the broadcast walks all holders under the same epoch
        number, so cross-shard pushes of one round can never straddle
        two codecs."""
        return (self._shard_targets.global_servers()
                + list(self.topology.servers()))

    def _broadcast(self, epoch: int, compression: dict):
        body = self._policy_body(epoch, compression)
        with self._mu:
            self._current_body = body
        for node in self._targets():
            reply = self._app.rpc(node, Ctrl.SET_WAN_POLICY,
                                  body=dict(body), timeout=3.0)
            with self._mu:
                if reply is None:
                    continue  # down — _broadcast_missing retries
                if "error" in reply:
                    # a constraint the server enforces that we missed
                    # (should be impossible: same predicate both ends)
                    self.refused += 1
                    import logging

                    logging.getLogger(__name__).error(
                        "%s refused WAN policy epoch %d: %s",
                        node, epoch, reply["error"])
                else:
                    self._acked[str(node)] = epoch

    def _broadcast_missing(self):
        targets = self._targets()  # outside _mu (it locks internally)
        with self._mu:
            epoch = self.epoch
            body = getattr(self, "_current_body", None)
            missing = [n for n in targets
                       if self._acked.get(str(n), 0) < epoch]
        if not body or epoch == 0 or not missing:
            return
        for node in missing:
            reply = self._app.rpc(node, Ctrl.SET_WAN_POLICY,
                                  body=dict(body), timeout=2.0)
            if reply is not None and "error" not in reply:
                with self._mu:
                    self._acked[str(node)] = epoch

    # ---- introspection ------------------------------------------------------
    def status(self) -> dict:
        with self._mu:
            return {
                "epoch": self.epoch,
                "compression": self.engine.current,
                "budget_s": self.engine.budget_s,
                "decisions": len(self.engine.decisions),
                "vetoes": self.engine.vetoes,
                "acked": dict(self._acked),
            }

    def stop(self):
        self._stop.set()
        self._app.stop()
