"""Checker: the Config ↔ ``GEOMX_*`` env ↔ docs/env-vars.md contract.

The configuration surface is a three-way contract: every ``Config`` (or
``Topology``) field is settable in code, has a ``GEOMX_*`` env fallback
wired in ``Config.from_env`` / ``__post_init__``, and has a row in
``docs/env-vars.md``.  Fields that deliberately have *no* env knob
document that with ``—`` in the row's env column — the row is still
required, so the exception is visible and reviewed.

Rules:

``field-no-env``        a Config/Topology field with no GEOMX_* read
                        anywhere in config.py and no ``—`` env cell in
                        its doc row
``field-undocumented``  a field with no docs/env-vars.md row at all
``env-undocumented``    a ``GEOMX_*`` name read anywhere in the package
                        but absent from the doc's env column (orphaned
                        env reads land here too: an env var consulted
                        by code that nobody can discover)
``doc-env-unread``      a ``GEOMX_*`` name documented in the env column
                        but never read by any source file (a row that
                        outlived a rename)

This generalizes the ``test_metrics_doc`` grep-audit idea (docs as a
machine-checked contract) onto the shared framework; the metrics-doc
checker itself lives in :mod:`geomx_tpu.analysis.doc_drift`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from geomx_tpu.analysis.core import Checker, Finding, Project, SourceFile

CONFIG_REL = "geomx_tpu/core/config.py"
DOC_NAME = "env-vars.md"

_ENV_READER = re.compile(r"^(?:get|getenv|_e|env|_env(?:_\w+)?)$")
_ENV_NAME = re.compile(r"^GEOMX_[A-Z0-9_]+$")
_DOC_ENV = re.compile(r"`(GEOMX_[A-Z0-9_]+)`")
_ENV_TOKEN = re.compile(r"[\"'](GEOMX_[A-Z0-9_]+)[\"']")
#: repo files outside the package whose env knobs the doc also catalogs
_EXTRA_GLOBS = ("bench.py", "scripts/*.py", "scripts/*.sh",
                "examples/*.py")
#: fields that are pure code-level plumbing, not operator knobs
_INTERNAL_FIELDS = frozenset({"topology"})


class ConfigDrift(Checker):
    name = "config-drift"
    description = ("every Config field has its GEOMX_* env fallback and "
                   "docs/env-vars.md row; no orphaned or stale env names")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        sf = project.by_rel.get(CONFIG_REL)
        doc_path = project.docs_dir / DOC_NAME
        if sf is None or not doc_path.exists():
            return findings
        doc_text = doc_path.read_text()
        doc_rel = doc_path.relative_to(project.root).as_posix()

        fields = self._dataclass_fields(sf, "Config")
        fields.update({f"topology.{n}": ln for n, ln
                       in self._dataclass_fields(sf, "Topology").items()})
        field_envs = self._field_env_map(sf)
        doc_rows = self._doc_rows(doc_text)
        documented_fields: Set[str] = set()
        documented_envs: Set[str] = set()
        noenv_fields: Set[str] = set()
        for env_cell, field_cell in doc_rows:
            for m in _DOC_ENV.finditer(env_cell):
                documented_envs.add(m.group(1))
            for tok in re.findall(r"`([A-Za-z0-9_.]+)`", field_cell):
                documented_fields.add(tok)
                if not _DOC_ENV.search(env_cell):
                    noenv_fields.add(tok)

        # every GEOMX_* literal anywhere in config.py: __post_init__
        # fallbacks that stage through a local variable (the
        # GEOMX_GLOBAL_SHARDS pattern) still count as the field's env
        # wiring when the doc row names that env
        config_literals = self._env_literals(sf.tree)
        doc_env_by_field: Dict[str, Set[str]] = {}
        for env_cell, field_cell in doc_rows:
            row_envs = {m.group(1) for m in _DOC_ENV.finditer(env_cell)}
            for tok in re.findall(r"`([A-Za-z0-9_.]+)`", field_cell):
                doc_env_by_field.setdefault(tok, set()).update(row_envs)

        for fname, line in sorted(fields.items()):
            if fname in _INTERNAL_FIELDS:
                continue
            envs = field_envs.get(fname, set())
            if not envs:
                envs = doc_env_by_field.get(fname, set()) & config_literals
            if not envs and fname not in noenv_fields:
                findings.append(self.finding(
                    CONFIG_REL, line, "Config", f"noenv:{fname}",
                    f"Config field {fname!r} has no GEOMX_* env fallback "
                    "in from_env/__post_init__ and its doc row does not "
                    "declare '—' (no-env) — directly-constructed configs "
                    "and launch scripts cannot set it from the "
                    "environment"))
            if fname not in documented_fields:
                findings.append(self.finding(
                    CONFIG_REL, line, "Config", f"undoc:{fname}",
                    f"Config field {fname!r} has no row in "
                    f"docs/{DOC_NAME}"))

        env_reads = self._env_reads(project)
        for env, sites in sorted(env_reads.items()):
            if env not in documented_envs:
                rel, line = sites[0]
                findings.append(self.finding(
                    rel, line, "env", f"envundoc:{env}",
                    f"env var {env} is read here but has no row in "
                    f"docs/{DOC_NAME} (env column)"))
        # stale-row check is read against ANY mention in the repo's
        # tooling files too (bench.py / scripts / examples carry knobs
        # the doc legitimately catalogs)
        mentioned = set(env_reads)
        for pat in _EXTRA_GLOBS:
            for p in project.root.glob(pat):
                mentioned.update(_ENV_TOKEN.findall(p.read_text()))
                mentioned.update(
                    re.findall(r"\b(GEOMX_[A-Z0-9_]+)=", p.read_text()))
        for env in sorted(documented_envs):
            if env not in mentioned:
                findings.append(Finding(
                    self.name, doc_rel, 1,
                    f"{doc_rel}::doc::stale:{env}",
                    f"docs/{DOC_NAME} documents {env} but no source "
                    "file reads it — a row that outlived a rename"))
        return findings

    # -- source side -------------------------------------------------------
    def _dataclass_fields(self, sf: SourceFile, cls: str
                          ) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == cls:
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name) \
                            and not stmt.target.id.startswith("_"):
                        out[stmt.target.id] = stmt.lineno
        return out

    def _env_literals(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and _ENV_NAME.match(n.value):
                out.add(n.value)
        return out

    def _field_env_map(self, sf: SourceFile) -> Dict[str, Set[str]]:
        """field (or ``topology.field``) -> GEOMX_* names consulted for
        it, from the ``from_env`` constructor kwargs and the
        ``__post_init__`` self-assignments."""
        out: Dict[str, Set[str]] = {}
        for fn in sf.functions:
            if fn.qualname == "Config.from_env":
                for n in ast.walk(fn.node):
                    if not isinstance(n, ast.Call):
                        continue
                    ctor = (n.func.id if isinstance(n.func, ast.Name)
                            else "")
                    if ctor not in ("Config", "Topology"):
                        continue
                    prefix = "topology." if ctor == "Topology" else ""
                    for kw in n.keywords:
                        if kw.arg is None:
                            continue
                        envs = self._env_literals(kw.value)
                        if envs:
                            out.setdefault(prefix + kw.arg,
                                           set()).update(envs)
            if fn.qualname == "Config.__post_init__":
                for n in ast.walk(fn.node):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1:
                        tgt = n.targets[0]
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            envs = self._env_literals(n.value)
                            if envs:
                                out.setdefault(tgt.attr,
                                               set()).update(envs)
                    # dataclasses.replace(self.topology, field=_env_int(..))
                    if isinstance(n, ast.Call):
                        fname = (n.func.attr
                                 if isinstance(n.func, ast.Attribute)
                                 else "")
                        if fname == "replace":
                            for kw in n.keywords:
                                if kw.arg is None:
                                    continue
                                envs = self._env_literals(kw.value)
                                if envs:
                                    out.setdefault(
                                        f"topology.{kw.arg}",
                                        set()).update(envs)
        return out

    def _env_reads(self, project: Project
                   ) -> Dict[str, List[Tuple[str, int]]]:
        out: Dict[str, List[Tuple[str, int]]] = {}
        for f in project.files:
            for fn_or_tree in (f.tree,):
                for n in ast.walk(fn_or_tree):
                    name: Optional[str] = None
                    if isinstance(n, ast.Call):
                        fname = (n.func.attr
                                 if isinstance(n.func, ast.Attribute)
                                 else n.func.id
                                 if isinstance(n.func, ast.Name) else "")
                        if _ENV_READER.match(fname) and n.args:
                            a0 = n.args[0]
                            if isinstance(a0, ast.Constant) \
                                    and isinstance(a0.value, str) \
                                    and _ENV_NAME.match(a0.value):
                                name = a0.value
                    elif isinstance(n, ast.Subscript):
                        sl = n.slice
                        if isinstance(sl, ast.Constant) \
                                and isinstance(sl.value, str) \
                                and _ENV_NAME.match(sl.value):
                            name = sl.value
                    if name is not None:
                        out.setdefault(name, []).append((f.rel, n.lineno))
        return out

    # -- doc side ----------------------------------------------------------
    def _doc_rows(self, text: str) -> List[Tuple[str, str]]:
        """(env_cell, field_cell) per table row.  The doc mixes
        5-column (``Env | Legacy | Field | ...``) and 4-column
        (``Env | Field | ...``) tables, so each table's header decides
        which cell is the field column."""
        rows: List[Tuple[str, str]] = []
        field_idx = 2
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("|") or line.startswith("|---"):
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            low = [c.lower() for c in cells]
            if low and low[0].startswith("env"):
                field_idx = next(
                    (i for i, c in enumerate(low) if "field" in c), 2)
                continue
            if len(cells) <= field_idx:
                continue
            rows.append((cells[0], cells[field_idx]))
        return rows
