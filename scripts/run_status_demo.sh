#!/usr/bin/env bash
# Telemetry-plane demo: a real OS-process topology over TCP with TWO
# global shards (each backed by a hot standby) and the full telemetry
# plane on; SIGKILL shard 1's primary mid-training and assert — from
# the status console and the health log alone — that
# (a) `python -m geomx_tpu.status` flips shard 1's holder to the
#     promoted standby under term 1,
# (b) the health engine logged a round_stall ALERT for shard:1 followed
#     by its RECOVERED record (exactly one pair), and
# (c) training ran to completion with telemetry reports collected.
#
# The pytest acceptance test (tests/test_obs.py::test_failover_visible_
# in_cluster_state_and_round_stall_alert) is the in-proc version; this
# script is the operator-facing tour.  See docs/observability.md.
#
# Env: GEOMX_BASE_PORT (default 9500), STEPS (default 600)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_GLOBAL_SHARDS=2
export GEOMX_NUM_STANDBY_GLOBALS=2
export GEOMX_HEARTBEAT_INTERVAL=0.2
export GEOMX_HEARTBEAT_TIMEOUT=1.5
export GEOMX_REQUEST_RETRY_S=1.0
export GEOMX_RETRY_BACKOFF_CAP=2
export GEOMX_OBS=1
export GEOMX_OBS_INTERVAL=0.2
export GEOMX_OBS_STALL_MIN=1.0
# pace the worker (~40 ms/step): the cluster must outlive the kill +
# the console polls — raw CNN steps finish in seconds
export GEOMX_TEST_STEP_SLEEP_MS='{"worker:0@p0": 40}'

BASE=${GEOMX_BASE_PORT:-9500}
export GEOMX_BASE_PORT=$BASE
STEPS=${STEPS:-600}
OUT=$(mktemp -d)
export GEOMX_OBS_DIR="$OUT/obs"
trap 'kill $(jobs -p) 2>/dev/null || true; rm -rf "$OUT"' EXIT

launch() { # role
  python -m geomx_tpu.launch --role "$1" --parties 1 --workers 1 \
    --global-shards 2 --standby-globals 2 --base-port "$BASE" \
    --obs-interval 0.2 --steps "$STEPS" >"$OUT/${1//[:@]/_}.log" 2>&1 &
}

launch global_scheduler:0
launch global_server:0
launch global_server:1
launch standby_global:0
launch standby_global:1
launch scheduler:0@p0
launch server:0@p0
launch worker:0@p0
WORKER_PID=$!

for _ in $(seq 1 240); do
  grep -q "training begins" "$OUT/worker_0_p0.log" 2>/dev/null && break
  sleep 0.5
done
grep -q "training begins" "$OUT/worker_0_p0.log" \
  || { echo "FAIL: worker never started training"; tail "$OUT/worker_0_p0.log"; exit 1; }
sleep 3  # several rounds + replication snapshots + telemetry samples

echo "== status before the kill =="
python -m geomx_tpu.status >"$OUT/status_before.txt"
cat "$OUT/status_before.txt"
grep -q "shard 1: holder=global_server:1 term=0" "$OUT/status_before.txt" \
  || { echo "FAIL: pre-kill status does not show the plan primary"; exit 1; }

VICTIM=$(pgrep -f "geomx_tpu.launch --role global_server:1 .*--base-port $BASE" | head -1)
echo "== SIGKILL shard 1 primary (pid $VICTIM) =="
kill -9 "$VICTIM"

# poll the console until the holder flips (one collection interval
# after the NEW_PRIMARY broadcast)
FLIPPED=0
for _ in $(seq 1 20); do
  kill -0 "$WORKER_PID" 2>/dev/null \
    || { echo "FAIL: training ended before the console saw the flip"; exit 1; }
  python -m geomx_tpu.status --timeout 3 >"$OUT/status_after.txt" 2>/dev/null || true
  if grep -q "shard 1: holder=standby_global:1 term=1" "$OUT/status_after.txt"; then
    FLIPPED=1; break
  fi
  sleep 0.5
done
echo "== status after the kill =="
cat "$OUT/status_after.txt"
[ "$FLIPPED" = 1 ] \
  || { echo "FAIL: status never showed the promoted holder"; exit 1; }

wait "$WORKER_PID" || true
sleep 1

echo "== health-log assertions (global scheduler) =="
GS="$OUT/global_scheduler_0.log"
grep -q "health ALERT round_stall shard:1" "$GS" \
  || { echo "FAIL: no round-stall alert for shard 1"; grep "health" "$GS" || true; exit 1; }
grep -q "health RECOVERED round_stall shard:1" "$GS" \
  || { echo "FAIL: round-stall never recovered"; grep "health" "$GS" || true; exit 1; }
[ "$(grep -c "health ALERT round_stall shard:1" "$GS")" = 1 ] \
  || { echo "FAIL: more than one round-stall alert for shard 1"; exit 1; }
# the FSA round gates on the killed shard, so shard 0 may legitimately
# stall too — but it must have recovered if it alerted
A0=$(grep -c "health ALERT round_stall shard:0" "$GS" || true)
R0=$(grep -c "health RECOVERED round_stall shard:0" "$GS" || true)
[ "$A0" = "$R0" ] \
  || { echo "FAIL: shard 0 round-stall never recovered"; exit 1; }
grep -q "cluster_state shards={0:global_server:0@t0, 1:standby_global:1@t1}" "$GS" \
  || { echo "FAIL: exit cluster_state line missing/wrong"; grep "cluster_state" "$GS" || true; exit 1; }
grep -q "steps=$STEPS" "$OUT/worker_0_p0.log" \
  || { echo "FAIL: training did not finish all steps"; exit 1; }
[ -s "$GEOMX_OBS_DIR/geomx_metrics.prom" ] \
  || { echo "FAIL: no Prometheus exposition dumped"; exit 1; }
echo "OK: holder flipped in the console, round_stall alert+recovery pair logged, training completed"
