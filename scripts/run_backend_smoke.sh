#!/usr/bin/env bash
# Merge-backend smoke lane: run the kvstore/failover/eviction test
# subset with the server merge lanes forced onto the JAX backend
# (GEOMX_MERGE_BACKEND shakes directly-constructed Configs too, the way
# GEOMX_SERVER_SHARDS does for the striped-merge path), so the device
# merge path cannot silently rot while tier-1 runs the numpy default.
# JAX_PLATFORMS=cpu: the point is the backend MACHINERY (staged H2D,
# donated-argument accumulate, mesh psum under the virtual 8-device
# conftest mesh), not accelerator hardware.
#
# Env: PYTEST_ARGS (extra pytest flags), GEOMX_MERGE_BACKEND (default jax)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_MERGE_BACKEND=${GEOMX_MERGE_BACKEND:-jax}

exec python -m pytest -q -m 'not slow' -p no:cacheprovider \
  tests/test_kvstore.py tests/test_failover.py tests/test_eviction.py \
  tests/test_sharded_merge.py tests/test_recovery.py \
  tests/test_merge_backend.py \
  ${PYTEST_ARGS:-}
