"""Per-process event-driven transport core: reactor loops + timer wheel.

The thread-per-endpoint harness caps realistic topology size: ``Van``
spawns recv/send/resend threads per node, ``TcpFabric`` adds an accept
loop, a UDP loop and one recv thread *per connection*, and every
monitor/pump owns a sleep-loop thread — a 128-party in-proc topology
means thousands of OS threads fighting the GIL, and the scheduler hot
spots the flight recorder's pressure gauges exist to name drown in pure
thread-switch noise.  This module is the classic reactor-over-
thread-per-connection move (the ps-lite/ZeroMQ design the reference
builds on; the TensorFlow paper's single-process multi-device harness
discipline, PAPERS.md):

- ``Reactor`` — a small FIXED pool of selector loop threads
  (``GEOMX_REACTOR_LOOPS``) servicing every registered socket in the
  process (non-blocking accept, readiness-driven reads, write-queue
  drains), plus ONE timer heap per loop (the timer wheel that absorbs
  ``Van._resend_thread``, the heartbeat loops and the monitor/pump
  sleep threads), plus a bounded worker pool
  (``GEOMX_REACTOR_WORKERS``) that executes handler work off the loop
  threads.
- ``SerialChannel`` — per-node FIFO dispatch over the shared worker
  pool: at most one in-flight drain per channel, so a node's inbound
  messages keep their exact arrival order (the ordering guarantee the
  per-node recv/customer threads provided) while the process runs
  O(loops + workers) threads instead of O(nodes).
- ``Periodic`` — a repeating tick that is a reactor timer when a
  reactor is present and a plain daemon thread otherwise, so the
  monitors migrate with one line and the legacy path stays untouched.

Selection: ``GEOMX_TRANSPORT=reactor|threads`` (``Config.transport``
wins when set; default ``threads`` until the reactor path has soaked).
``threads`` keeps the pre-reactor behavior bit-for-bit.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, List, Optional

_LOG = logging.getLogger(__name__)

_VALID_TRANSPORTS = ("threads", "reactor")


def resolve_transport(config=None) -> str:
    """The effective transport engine: ``Config.transport`` when set,
    else the ``GEOMX_TRANSPORT`` env (so a whole test suite can be
    shaken under the threaded fabric — ``GEOMX_TRANSPORT=threads
    pytest ...`` — without threading the knob through every fixture,
    the way GEOMX_SERVER_SHARDS / GEOMX_GLOBAL_SHARDS work), default
    ``reactor``.

    The reactor became the default after the flip checklist in
    docs/perf.md "Default-flip evidence" closed (clean blocking audits,
    full-suite parity, measured scaling); ``GEOMX_TRANSPORT=threads``
    stays supported as the escape hatch."""
    t = str(getattr(config, "transport", "") or "") if config is not None \
        else ""
    if not t:
        t = os.environ.get("GEOMX_TRANSPORT", "") or "reactor"
    t = t.strip().lower()
    if t not in _VALID_TRANSPORTS:
        raise ValueError(
            f"unknown transport {t!r} (GEOMX_TRANSPORT / Config.transport "
            f"must be one of {_VALID_TRANSPORTS})")
    return t


def resolve_reactor_loops(config=None) -> int:
    """Loop-thread count: ``Config.reactor_loops`` / GEOMX_REACTOR_LOOPS,
    0 = auto (min(4, cpus) — loops block in select(), more loops than
    cores only helps when one loop's callbacks are busy)."""
    n = int(getattr(config, "reactor_loops", 0) or 0) if config is not None \
        else 0
    if n <= 0:
        n = int(os.environ.get("GEOMX_REACTOR_LOOPS", "0") or 0)
    if n <= 0:
        n = min(4, os.cpu_count() or 1)
    return max(1, n)


def resolve_reactor_workers() -> int:
    """Handler-pool size (GEOMX_REACTOR_WORKERS, 0 = auto).  Handlers
    are event-driven (the push→merge→push-up→pull-down chain completes
    via callbacks, never parking a thread in wait()), so a small pool
    services hundreds of nodes; the floor of 8 leaves slack for the
    few blocking control paths (monitor RPCs, warm boots)."""
    n = int(os.environ.get("GEOMX_REACTOR_WORKERS", "0") or 0)
    if n <= 0:
        n = max(8, 2 * (os.cpu_count() or 1))
    return max(2, n)


class _Timer:
    __slots__ = ("due", "fn", "cancelled")

    def __init__(self, due: float, fn: Callable[[], None]):
        self.due = due
        self.fn = fn
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class Registration:
    """One registered socket: read/write callbacks run on the owning
    loop thread.  ``want_write`` arms/disarms write readiness (senders
    toggle it around a non-empty write queue); ``close`` unregisters
    and (by default) closes the socket.  All mutations marshal onto
    the loop thread — the selectors module is not thread-safe."""

    __slots__ = ("_loop", "sock", "read_cb", "write_cb", "_mask",
                 "closed", "_installed")

    def __init__(self, loop: "_Loop", sock, read_cb, write_cb):
        self._loop = loop
        self.sock = sock
        self.read_cb = read_cb
        self.write_cb = write_cb
        self._mask = (selectors.EVENT_READ if read_cb else 0)
        self.closed = False
        self._installed = False

    # ---- loop-thread only ----------------------------------------------------
    def _install(self):
        if self.closed:
            return
        try:
            self._loop._sel.register(self.sock, self._mask or
                                     selectors.EVENT_READ, self)
            self._installed = True
            if not self._mask:
                # registered purely for future write interest: park with
                # read interest off by modifying to 0-ish is invalid —
                # selectors require at least one event, so idle write-
                # only sockets register READ (a peer close shows up as
                # readable EOF, which the write_cb owner handles)
                self._mask = selectors.EVENT_READ
        except (OSError, ValueError, KeyError):
            self.closed = True

    def _set_mask(self, mask: int):
        if self.closed or not self._installed:
            return
        mask = mask or selectors.EVENT_READ
        if mask == self._mask:
            return
        try:
            self._loop._sel.modify(self.sock, mask, self)
            self._mask = mask
        except (OSError, ValueError, KeyError):
            pass

    # ---- any thread ----------------------------------------------------------
    def want_write(self, on: bool):
        base = selectors.EVENT_READ if self.read_cb else 0
        mask = base | (selectors.EVENT_WRITE if on else 0)
        self._loop.call_on_loop(lambda: self._set_mask(mask))

    def close(self, close_sock: bool = True):
        def _do():
            if not self.closed:
                self.closed = True
                if self._installed:
                    try:
                        self._loop._sel.unregister(self.sock)
                    except (OSError, ValueError, KeyError):
                        pass
            if close_sock:
                try:
                    self.sock.close()
                except OSError:
                    pass
        self._loop.call_on_loop(_do)


class _Loop:
    """One selector + timer heap serviced by one thread.  The waker
    socketpair interrupts select() for cross-thread register/timer
    operations (the standard self-pipe trick)."""

    def __init__(self, name: str):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._r, self._w = socket.socketpair()
        self._r.setblocking(False)
        self._w.setblocking(False)
        self._sel.register(self._r, selectors.EVENT_READ, None)
        self._mu = threading.Lock()
        self._pending: deque = deque()
        self._timers: list = []  # heap of (due, tie, _Timer)
        self._tie = itertools.count()
        self._stop = False
        self.last_lag_ms = 0.0  # scheduled-vs-actual delta of the most
        #                         recently fired timer: a loop that can't
        #                         keep up with its fds shows it here
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _wake(self):
        try:
            self._w.send(b"\0")
        except (OSError, BlockingIOError):
            pass  # a full waker buffer already guarantees a wakeup

    def call_on_loop(self, fn: Callable[[], None]):
        with self._mu:
            self._pending.append(fn)
        self._wake()

    def call_at(self, due: float, fn: Callable[[], None]) -> _Timer:
        t = _Timer(due, fn)
        with self._mu:
            heapq.heappush(self._timers, (due, next(self._tie), t))
        self._wake()
        return t

    def fd_count(self) -> int:
        """Registered sockets on this loop (the waker excluded)."""
        try:
            return max(0, len(self._sel.get_map()) - 1)
        except (OSError, RuntimeError):
            return 0

    def stop(self):
        self._stop = True
        self._wake()

    def _run(self):
        while not self._stop:
            with self._mu:
                timeout = None
                if self._timers:
                    timeout = max(0.0,
                                  self._timers[0][0] - time.monotonic())
            try:
                events = self._sel.select(timeout)
            except OSError:
                continue  # a socket closed mid-select; retry
            if self._stop:
                break
            for key, mask in events:
                if key.data is None:  # the waker
                    try:
                        while self._r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                reg: Registration = key.data
                if reg.closed:
                    continue
                try:
                    if mask & selectors.EVENT_READ and reg.read_cb:
                        reg.read_cb()
                    if (mask & selectors.EVENT_WRITE and reg.write_cb
                            and not reg.closed):
                        reg.write_cb()
                except Exception:  # pragma: no cover - surfaced via logs
                    _LOG.exception("%s: socket callback failed", self.name)
            # cross-thread operations (register/modify/close)
            while True:
                with self._mu:
                    if not self._pending:
                        break
                    fn = self._pending.popleft()
                try:
                    fn()
                except Exception:  # pragma: no cover
                    _LOG.exception("%s: loop op failed", self.name)
            # due timers
            now = time.monotonic()
            while True:
                with self._mu:
                    if not self._timers or self._timers[0][0] > now:
                        break
                    due, _, t = heapq.heappop(self._timers)
                if t.cancelled:
                    continue
                self.last_lag_ms = max(0.0, (now - due) * 1000.0)
                try:
                    t.fn()
                except Exception:  # pragma: no cover
                    _LOG.exception("%s: timer failed", self.name)
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._r, self._w):
            try:
                s.close()
            except OSError:
                pass


class SerialChannel:
    """FIFO dispatch lane over a shared pool: items are processed in
    exact ``put`` order with at most one in-flight drain task — the
    per-node ordering the dedicated recv/customer threads provided,
    at O(1) threads.  ``close`` drops queued items and makes further
    puts no-ops (a stopped node processes nothing further)."""

    # yield the pool worker back after this many items so one firehose
    # channel cannot starve every other node's dispatch
    _BATCH = 64

    __slots__ = ("_pool", "_cb", "_mu", "_items", "_active", "_closed",
                 "name")

    def __init__(self, pool, cb: Callable, name: str = ""):
        self._pool = pool
        self._cb = cb
        self._mu = threading.Lock()
        self._items: deque = deque()
        self._active = False
        self._closed = False
        self.name = name

    def put(self, item) -> None:
        with self._mu:
            if self._closed:
                return
            self._items.append(item)
            if self._active:
                return
            self._active = True
        self._pool.submit(self._drain)

    def qsize(self) -> int:
        with self._mu:
            return len(self._items)

    def _drain(self):
        for _ in range(self._BATCH):
            with self._mu:
                if self._closed or not self._items:
                    self._active = False
                    return
                item = self._items.popleft()
            try:
                self._cb(item)
            except Exception:  # pragma: no cover - surfaced via logs
                _LOG.exception("channel %s: handler failed", self.name)
        # batch exhausted with work left: requeue so siblings get a turn
        with self._mu:
            if self._closed or not self._items:
                self._active = False
                return
        self._pool.submit(self._drain)

    def close(self):
        with self._mu:
            self._closed = True
            self._items.clear()


class _RepeatingTask:
    """One ``call_every`` registration: fires on the timer wheel,
    executes on the worker pool, skips a tick while the previous run is
    still going (matching the thread-loop semantics where a long sweep
    simply delays the next)."""

    __slots__ = ("_reactor", "interval", "fn", "name", "_cancelled",
                 "_running", "_mu", "_timer")

    def __init__(self, reactor: "Reactor", interval: float, fn, name: str):
        self._reactor = reactor
        self.interval = max(1e-3, float(interval))
        self.fn = fn
        self.name = name
        self._cancelled = False
        self._running = False
        self._mu = threading.Lock()
        self._timer = None
        self._schedule()

    def _schedule(self):
        if self._cancelled:
            return
        loop = self._reactor._loop_for_timers()
        self._timer = loop.call_at(time.monotonic() + self.interval,
                                   self._fire)

    def _fire(self):  # loop thread: hand off, never block the selector
        if self._cancelled:
            return
        with self._mu:
            skip = self._running
            if not skip:
                self._running = True
        if not skip:
            self._reactor.submit(self._run)
        self._schedule()

    def _run(self):
        try:
            if not self._cancelled:
                self.fn()
        except Exception:  # pragma: no cover - surfaced via logs
            _LOG.exception("periodic %s failed", self.name)
        finally:
            with self._mu:
                self._running = False

    def cancel(self):
        self._cancelled = True
        t = self._timer
        if t is not None:
            t.cancel()

    # Periodic-compat alias
    stop = cancel


class Reactor:
    """The per-process event core: N selector loops + one worker pool.
    Create private instances for tests; production code shares ONE via
    :meth:`shared` (its threads are process-lifetime, named
    ``geomx-reactor-*`` — a fixed-size pool, O(1) in node count)."""

    _shared: Optional["Reactor"] = None
    _shared_mu = threading.Lock()

    def __init__(self, loops: int = 0, workers: int = 0,
                 name: str = "geomx-reactor"):
        from concurrent.futures import ThreadPoolExecutor

        n = loops or resolve_reactor_loops()
        self.name = name
        self._loops: List[_Loop] = [_Loop(f"{name}-loop-{i}")
                                    for i in range(n)]
        self._rr = itertools.count()
        self.workers = workers or resolve_reactor_workers()
        self._pool = ThreadPoolExecutor(max_workers=self.workers,
                                        thread_name_prefix=f"{name}-w")
        self._stopped = False

    @classmethod
    def shared(cls) -> "Reactor":
        with cls._shared_mu:
            if cls._shared is None or cls._shared._stopped:
                cls._shared = cls()
            return cls._shared

    # ---- sockets -------------------------------------------------------------
    def register(self, sock, read_cb=None, write_cb=None) -> Registration:
        """Register a NON-BLOCKING socket; callbacks run on the owning
        loop thread (level-triggered: keep them short, read until
        EAGAIN).  fds spread round-robin across the loops."""
        loop = self._loops[next(self._rr) % len(self._loops)]
        reg = Registration(loop, sock, read_cb, write_cb)
        loop.call_on_loop(reg._install)
        return reg

    # ---- timer wheel ---------------------------------------------------------
    def _loop_for_timers(self) -> _Loop:
        return self._loops[next(self._rr) % len(self._loops)]

    def call_later(self, delay: float, fn: Callable[[], None]) -> _Timer:
        """One-shot timer; ``fn`` runs ON THE LOOP THREAD — keep it
        tiny (or submit to the pool yourself)."""
        return self._loop_for_timers().call_at(
            time.monotonic() + max(0.0, delay), fn)

    def call_every(self, interval: float, fn: Callable[[], None],
                   name: str = "") -> _RepeatingTask:
        """Repeating tick executed on the WORKER POOL (safe to block
        briefly); overlapping ticks are skipped.  This is the timer
        wheel that absorbs the per-node resend/heartbeat/monitor sleep
        threads."""
        return _RepeatingTask(self, interval, fn, name or "tick")

    # ---- handler pool --------------------------------------------------------
    def submit(self, fn: Callable[[], None]):
        try:
            self._pool.submit(self._guard, fn)
        except RuntimeError:
            # raced stop(): a timer tick fired while the pool was
            # shutting down — dropping it matches the thread-loop
            # semantics (a stopped loop simply never runs its next turn)
            pass

    @staticmethod
    def _guard(fn):
        try:
            fn()
        except Exception:  # pragma: no cover - surfaced via logs
            _LOG.exception("reactor task failed")

    def channel(self, cb: Callable, name: str = "") -> SerialChannel:
        return SerialChannel(self._pool, cb, name=name)

    # ---- observability -------------------------------------------------------
    def loop_lag_ms(self) -> float:
        """Worst recent timer-fire lag across the loops — the
        ``reactor_loop_lag_ms`` pressure gauge: a loop whose callbacks
        hog it shows up here before anything deadlocks."""
        return max((lp.last_lag_ms for lp in self._loops), default=0.0)

    def fd_counts(self) -> List[int]:
        """Registered sockets per loop."""
        return [lp.fd_count() for lp in self._loops]

    def fd_count(self) -> int:
        """Total registered sockets (the ``reactor_fds`` gauge; per-loop
        detail via :meth:`fd_counts`)."""
        return sum(self.fd_counts())

    @property
    def loops(self) -> int:
        return len(self._loops)

    def stop(self):
        """Tear down (private/test reactors only — never the shared
        one: its channels and timers are owned process-wide)."""
        self._stopped = True
        for lp in self._loops:
            lp.stop()
        self._pool.shutdown(wait=False)


class Periodic:
    """A repeating background tick: a reactor timer when ``reactor`` is
    given (one timer-wheel entry, zero threads), else a daemon thread
    with the classic ``Event.wait(interval)`` loop (the pre-reactor
    behavior, bit-for-bit).  The one-line migration path for the
    monitor/pump loops."""

    def __init__(self, interval: float, fn: Callable[[], None],
                 name: str = "periodic", reactor: Optional[Reactor] = None):
        self.interval = float(interval)
        self.fn = fn
        self.name = name
        self._task = None
        self._stop_ev = None
        self._thread = None
        if reactor is not None:
            self._task = reactor.call_every(self.interval, fn, name=name)
        else:
            self._stop_ev = threading.Event()
            self._thread = threading.Thread(target=self._run, name=name,
                                            daemon=True)
            self._thread.start()

    def _run(self):
        while not self._stop_ev.wait(self.interval):
            try:
                self.fn()
            except Exception:  # pragma: no cover - surfaced via logs
                _LOG.exception("periodic %s failed", self.name)

    def stop(self):
        if self._task is not None:
            self._task.cancel()
        if self._stop_ev is not None:
            self._stop_ev.set()
