"""Operator cluster-state console: ``python -m geomx_tpu.status``.

Joins the deployment's TCP plan as an OUT-OF-PLAN querier (its reply
address travels in the request body, like a dynamic joiner's), asks the
global scheduler for ``Ctrl.CLUSTER_STATE``, and renders the live text
dashboard — shard holders/terms, party fold state, per-node heartbeat
freshness, WAN policy epoch, active health alerts, and the flight
recorder's pressure column.  ``--watch`` redraws on an interval until
interrupted; ``--dump-flight`` instead asks the scheduler to broadcast
a flight-recorder snapshot (every node dumps its black-box ring to
``GEOMX_OBS_DIR`` — see docs/observability.md "Postmortem
forensics").

Topology comes from the same env surface the launcher uses
(GEOMX_NUM_PARTIES / GEOMX_WORKERS_PER_PARTY / GEOMX_GLOBAL_SHARDS /
GEOMX_NUM_STANDBY_GLOBALS / GEOMX_BASE_PORT / GEOMX_NODE_HOSTS), with
CLI overrides.  See docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.kvstore.common import APP_PS, Ctrl
from geomx_tpu.obs.state import render_text
from geomx_tpu.ps import Postoffice
from geomx_tpu.ps.kv_app import _App
from geomx_tpu.transport.message import Domain
from geomx_tpu.transport.tcp import TcpFabric, default_address_plan

# out-of-plan rank for the console's node id: far above any planned
# master worker, so two operators can even watch at once (ranks differ
# by --status-port, the identity includes it)
_STATUS_RANK_BASE = 900


class _QueryApp(_App):
    """Command-channel-only endpoint: sends the query, collects the
    reply (the controller's _CmdEndpoint shape)."""

    def _process(self, msg):
        if not msg.push and not msg.pull:
            self._handle_command(msg)
        # stray data traffic at the console is dropped


class StatusClient:
    """One short-lived (or --watch long-lived) query endpoint."""

    def __init__(self, config: Config, base_port: int,
                 status_port: int, host: str = "127.0.0.1"):
        # the console is a passive querier: no heartbeats (it has no
        # scheduler slot to ping — they would only log dial noise)
        config.heartbeat_interval_s = 0.0
        self.config = config
        hosts = json.loads(os.environ.get("GEOMX_NODE_HOSTS", "{}"))
        plan = default_address_plan(config.topology, base_port, hosts)
        self.node = NodeId(Role.MASTER_WORKER,
                           _STATUS_RANK_BASE + status_port % 97)
        self.addr = (host, status_port)
        plan[str(self.node)] = self.addr
        self.fabric = TcpFabric(plan, config=config)
        self.po = Postoffice(self.node, config.topology, self.fabric,
                             config)
        self.po.start()
        self._app = _QueryApp(APP_PS, 0, self.po)

    def query(self, timeout: float = 5.0) -> dict:
        return self._cmd(Ctrl.CLUSTER_STATE, {}, timeout,
                         "empty cluster-state reply")

    def dump_flight(self, out_dir: str = "", timeout: float = 5.0) -> dict:
        """Ask the scheduler to broadcast a flight-recorder snapshot
        (Ctrl.FLIGHT_DUMP → Control.FLIGHT_DUMP to every node); returns
        the reply naming the dump dir + expected per-node paths."""
        body = {"dir": out_dir} if out_dir else {}
        return self._cmd(Ctrl.FLIGHT_DUMP, body, timeout,
                         "empty flight-dump reply")

    def _cmd(self, cmd, body: dict, timeout: float, err: str) -> dict:
        gsched = self.po.topology.global_scheduler()
        body = dict(body, addr=[self.addr[0], self.addr[1]])
        ts = self._app.send_cmd(gsched, cmd, body=body,
                                domain=Domain.GLOBAL, wait=False)
        self._app.customer.wait(ts, timeout=timeout)
        reply = self._app.cmd_response(ts)
        if not isinstance(reply, dict):
            raise RuntimeError(err)
        return reply

    def stop(self):
        self._app.stop()
        self.po.stop()
        self.fabric.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m geomx_tpu.status",
        description="live cluster-state console (Ctrl.CLUSTER_STATE)")
    ap.add_argument("--watch", action="store_true",
                    help="redraw every --interval seconds until ^C")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="dump the raw state dict instead of the "
                         "dashboard")
    ap.add_argument("--parties", type=int,
                    default=int(os.environ.get("GEOMX_NUM_PARTIES", "1")))
    ap.add_argument("--workers", type=int,
                    default=int(os.environ.get("GEOMX_WORKERS_PER_PARTY",
                                               "1")))
    ap.add_argument("--global-shards", type=int,
                    default=int(os.environ.get(
                        "GEOMX_GLOBAL_SHARDS",
                        os.environ.get("GEOMX_NUM_GLOBAL_SERVERS", "1"))))
    ap.add_argument("--standby-globals", type=int,
                    default=int(os.environ.get("GEOMX_NUM_STANDBY_GLOBALS",
                                               "0")))
    ap.add_argument("--replicas", type=int,
                    default=int(os.environ.get("GEOMX_SERVE_REPLICAS",
                                               "0")))
    ap.add_argument("--base-port", type=int,
                    default=int(os.environ.get("GEOMX_BASE_PORT", "9200")))
    ap.add_argument("--status-port", type=int,
                    default=int(os.environ.get("GEOMX_STATUS_PORT", "0"))
                    or None,
                    help="local reply port (default base-port + 177)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--dump-flight", action="store_true",
                    help="ask every node to snapshot its black-box "
                         "flight-recorder ring to the cluster's "
                         "GEOMX_OBS_DIR (or --flight-dir), then exit; "
                         "assemble with python -m "
                         "geomx_tpu.obs.postmortem <dir>")
    ap.add_argument("--flight-dir", default="",
                    help="dump directory override sent with "
                         "--dump-flight (must be writable by the "
                         "cluster's processes)")
    args = ap.parse_args(argv)

    cfg = Config.from_env()
    cfg.topology = Topology(num_parties=args.parties,
                            workers_per_party=args.workers,
                            num_global_servers=args.global_shards,
                            num_standby_globals=args.standby_globals,
                            num_replicas=args.replicas)
    client = StatusClient(cfg, args.base_port,
                          args.status_port or args.base_port + 177)
    try:
        if args.dump_flight:
            try:
                reply = client.dump_flight(args.flight_dir,
                                           timeout=args.timeout)
            except (TimeoutError, RuntimeError) as e:
                print(f"status: flight dump failed ({e})",
                      file=sys.stderr)
                return 1
            if not reply.get("ok"):
                print(f"status: flight dump refused — "
                      f"{reply.get('error')}", file=sys.stderr)
                return 1
            print(f"flight dump requested: incident "
                  f"{reply.get('incident')} -> {reply.get('dir')} "
                  f"({reply.get('nodes')} node(s)); assemble with "
                  f"python -m geomx_tpu.obs.postmortem "
                  f"{reply.get('dir')}")
            return 0
        while True:
            try:
                state = client.query(timeout=args.timeout)
            except (TimeoutError, RuntimeError) as e:
                print(f"status: no answer from the global scheduler "
                      f"({e})", file=sys.stderr)
                if not args.watch:
                    return 1
                time.sleep(args.interval)
                continue
            if args.as_json:
                print(json.dumps(state, indent=1, sort_keys=True))
            else:
                if args.watch:
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(render_text(state), flush=True)
            if not args.watch:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.stop()


if __name__ == "__main__":
    sys.exit(main())
