"""Pin the r4 ownership rules that produced the 13x server-merge win
(kvstore/server.py: Message.donated adoption, frozen store aliasing,
copy-on-write at the BSC decode).  The stress bench covers throughput;
these tests pin the MECHANISM — on a faster host a reintroduced copy
would not show up as a wall-clock regression until real scale.
"""

import numpy as np

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation


def _sim(**cfg):
    return Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=1), **cfg))


def test_pull_response_aliases_frozen_store():
    """The worker-facing pull response must ALIAS the local server's
    stored weights (frozen read-only), not copy them — and the store
    array itself must be frozen so any in-place decode COWs."""
    sim = _sim()
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(1024, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 1.0})
        w.push(0, np.ones(1024, np.float32))
        _ = w.pull_sync(0)
        w.wait_all()
        store_arr = sim.local_servers[0].store[0]
        # serving the pull froze the stored array in place
        assert not store_arr.flags.writeable, (
            "store array not frozen: responses are copying again")
    finally:
        sim.shutdown()


def test_push_up_donates_accumulator_to_global_tier():
    """The local server's push-up transfers ownership: the global tier
    must ADOPT the aggregation buffer (same memory), not copy it."""
    sim = _sim()
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(1024, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.arange(1024, dtype=np.float32)
        w.push(0, g)
        _ = w.pull_sync(0)
        w.wait_all()
        # SGD's update_scaled builds the new weights IN the donated
        # accumulator; if the global tier had copied the push payload,
        # the arithmetic still works but an extra 4MB/round memcpy is
        # back.  Detect via the value path: new weights = -lr * grad
        # (sum of 1 worker, scale 1/1 party), stored in a buffer built
        # from the donated accum.
        gs = sim.global_servers[0].store[0]
        np.testing.assert_allclose(gs, -g)
        # the local replica ADOPTED the (frozen) global response alias —
        # in-proc they are the same buffer
        ls = sim.local_servers[0].store[0]
        assert np.shares_memory(ls, gs), (
            "pull-down copied instead of adopting the frozen alias")
    finally:
        sim.shutdown()


def test_bsc_decode_copies_on_write_not_in_place():
    """Under pull-direction BSC the local replica is updated by a
    sparse delta; when the current replica is frozen (aliased by
    responses/upstream), the decode must COW — never mutate the frozen
    buffer other readers alias."""
    sim = _sim(compression="bsc")
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(4096, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        w.set_gradient_compression({"type": "bsc", "ratio": 0.05})
        rng = np.random.default_rng(0)
        ls = sim.local_servers[0]
        prev = None
        for _ in range(3):
            w.push(0, rng.standard_normal(4096).astype(np.float32))
            _ = w.pull_sync(0)
            w.wait_all()
            cur = ls.store[0]
            if prev is not None and not prev.flags.writeable:
                # the frozen snapshot from the previous round must be
                # intact — a COW produced a NEW buffer for this round
                assert cur is not prev, "in-place mutation of frozen buf"
            prev = cur
    finally:
        sim.shutdown()
