"""Checker: every raw-buffer decode in the transport / codec / durable-
state paths sits behind explicit length validation.

``unchecked-decode``
    ``np.frombuffer(...)``, ``struct.unpack(...)`` / ``unpack_from`` and
    compiled ``Struct.unpack*`` calls reinterpret attacker-reachable (or
    disk-rotted) bytes.  Without a preceding length/bounds check they
    either raise a bare ``ValueError``/``struct.error`` deep inside the
    framing (taking the reactor thread with it) or — worse — silently
    produce a short array that the merge path scatters into the wrong
    coordinates.  The rule: inside the enclosing function, BEFORE the
    decode call (in line order), there must be at least one of

    - a branch / loop condition / assert that inspects a size
      (``len(...)``, ``.nbytes``, ``.size``, ``.itemsize``), or
    - a call to a validation helper (name contains ``check``, ``verify``
      or ``valid``), or
    - the decode sits inside a ``try`` whose handler catches the decode
      error classes (``struct.error`` / ``ValueError`` / a typed
      corruption error) — the catch-and-fence idiom.

    Findings that are individually audited and defensible (e.g. a
    buffer whose length the caller already pinned) belong in
    ``analysis-baseline.toml`` with a one-sentence justification, like
    every other checker's.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from geomx_tpu.analysis.core import (Checker, Finding, FunctionInfo,
                                     Project, _attr_chain)

#: modules whose decode sites face the wire or durable state — the
#: data/ file readers parse trusted local training files and are out of
#: scope (a corrupt dataset fails loudly at startup, not mid-round)
DECODE_SCOPES = (
    "geomx_tpu/transport/",
    "geomx_tpu/compression/",
    "geomx_tpu/kvstore/checkpoint.py",
)

#: call names that reinterpret raw bytes
_DECODE_NAMES = frozenset({"frombuffer", "unpack", "unpack_from"})

#: attribute names whose mere mention in a condition counts as a size
#: inspection
_SIZE_ATTRS = frozenset({"nbytes", "size", "itemsize"})

#: exception names that make an enclosing try/except a legitimate
#: catch-and-fence guard for a decode
_FENCE_EXCS = frozenset({
    "error", "ValueError", "Exception", "struct", "CodecError",
    "WireCorruption", "CheckpointCorruption", "OSError", "KeyError",
    "IndexError", "TypeError",
})


def _mentions_size(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            fname = n.func.id if isinstance(n.func, ast.Name) else (
                n.func.attr if isinstance(n.func, ast.Attribute) else "")
            if fname == "len":
                return True
            low = fname.lower()
            if "check" in low or "verify" in low or "valid" in low:
                return True
        if isinstance(n, ast.Attribute) and n.attr in _SIZE_ATTRS:
            return True
    return False


def _handler_names(handler: ast.ExceptHandler) -> Set[str]:
    if handler.type is None:
        return {"Exception"}  # bare except catches everything
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    out: Set[str] = set()
    for t in types:
        ch = _attr_chain(t)
        if ch:
            out.update(ch.split("."))
    return out


class DecodeBounds(Checker):
    name = "decode-bounds"
    description = ("np.frombuffer / struct.unpack in transport+codec "
                   "paths must follow an explicit length check (or sit "
                   "in a typed catch-and-fence try block)")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for sf in project.files:
            if not any(sf.rel.startswith(s) if s.endswith("/")
                       else sf.rel == s for s in DECODE_SCOPES):
                continue
            for fn in sf.functions:
                if isinstance(fn.node, ast.Lambda):
                    continue
                findings.extend(self._check_function(sf.rel, fn))
        return findings

    # ------------------------------------------------------------------
    def _check_function(self, rel: str, fn: FunctionInfo) -> List[Finding]:
        node = fn.node
        decode_sites: List[Tuple[str, int]] = []
        for call in fn.calls:
            if call.name not in _DECODE_NAMES:
                continue
            # unpack()/unpack_from() with no receiver is some local
            # helper, not a struct decode; frombuffer always counts
            if call.name != "frombuffer" and call.recv is None:
                continue
            decode_sites.append((call.name, call.line))
        if not decode_sites:
            return []
        guard_lines = self._guard_lines(node)
        helper_lines = self._helper_call_lines(fn)
        fenced = self._fenced_ranges(node)
        out: List[Finding] = []
        seen_per_name: dict = {}
        for name, line in sorted(decode_sites, key=lambda s: s[1]):
            if any(g < line for g in guard_lines):
                continue
            if any(h < line for h in helper_lines):
                continue
            if any(lo <= line <= hi for lo, hi in fenced):
                continue
            ordinal = seen_per_name.get(name, 0)
            seen_per_name[name] = ordinal + 1
            out.append(self.finding(
                rel, line, fn.qualname, f"{name}:{ordinal}",
                f"{name}() decodes raw bytes with no preceding length/"
                "bounds check in this function and no typed catch-and-"
                "fence around it — a truncated or bit-rotted buffer "
                "either raises inside the framing or returns a silently "
                "short array"))
        return out

    def _guard_lines(self, node: ast.AST) -> List[int]:
        """Lines of size-inspecting branch conditions / asserts,
        excluding nested function bodies (their guards protect their own
        decodes, not ours)."""
        out: List[int] = []
        for n in self._walk_same_function(node):
            if isinstance(n, (ast.If, ast.While)) and _mentions_size(n.test):
                out.append(n.lineno)
            elif isinstance(n, ast.Assert) and _mentions_size(n.test):
                out.append(n.lineno)
            elif isinstance(n, ast.IfExp) and _mentions_size(n.test):
                out.append(n.lineno)
        return out

    def _helper_call_lines(self, fn: FunctionInfo) -> List[int]:
        out: List[int] = []
        for call in fn.calls:
            low = call.name.lower()
            if "check" in low or "verify" in low or "valid" in low:
                out.append(call.line)
        return out

    def _fenced_ranges(self, node: ast.AST) -> List[Tuple[int, int]]:
        """(first, last) line ranges of try-bodies whose handlers catch
        a decode error class."""
        out: List[Tuple[int, int]] = []
        for n in self._walk_same_function(node):
            if not isinstance(n, ast.Try):
                continue
            if not any(_handler_names(h) & _FENCE_EXCS
                       for h in n.handlers):
                continue
            last = max((getattr(s, "end_lineno", s.lineno) or s.lineno)
                       for s in n.body)
            out.append((n.body[0].lineno, last))
        return out

    def _walk_same_function(self, node: ast.AST):
        """ast.walk, but do not descend into nested def/lambda."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))
