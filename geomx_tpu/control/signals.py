"""Sliding-window WAN signal estimators.

Everything here derives from observability the system already ships —
no new probes on any data path:

- **goodput / byte rate** — deltas of the per-codec ``wan_bytes_*``
  counters the vans mirror into the system-metrics registry (PR 3), or,
  cross-process, the ``wan_send_bytes`` totals each local server reports
  via ``Ctrl.QUERY_STATS``.
- **round rate** — deltas of the local servers' ``wan_push_rounds``
  counter (one per WAN push-up batch), the controller's primary "is the
  pipeline keeping up" signal: ``round_time ≈ Δt / Δrounds``.
- **RTT** — the heartbeat echo RTT gauges (``Postoffice.heartbeat_rtts``,
  reported back through QUERY_STATS as ``hb_rtt_s``).
- **dominant stage / straggler party** — the trace collector's per-round
  critical-path report, when tracing is on.  The policy engine uses it
  as a veto: if rounds are slow but the dominant stage is compute
  (local/global merge), more WAN compression cannot help.

The estimator is deliberately pull-based (the controller calls
:meth:`ingest` with whatever stats it sampled); it holds no locks shared
with any data path.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Deque, Dict, Optional, Tuple


@dataclasses.dataclass
class WanSignals:
    """One fused observation the policy engine decides on."""

    t: float                          # monotonic sample time
    round_time_s: Optional[float]     # Δt/Δrounds of the slowest party
    #                                   (None until a round completed in
    #                                   the window)
    goodput_bps: Optional[float]      # WAN bytes/s over the window
    wan_bytes_rate: Dict[str, float]  # per-codec-tag bytes/s
    rtt_s: Optional[float]            # worst heartbeat RTT across servers
    dominant_stage: Optional[str]     # from the critical-path report
    straggler_party: Optional[str]    # party of the dominant stage's
    #                                   worst node
    rounds_total: int                 # cumulative WAN rounds observed


class _Window:
    """Fixed-length window of (t, value) samples with delta-rate math."""

    def __init__(self, n: int):
        self._q: Deque[Tuple[float, float]] = collections.deque(maxlen=n)

    def push(self, t: float, v: float) -> None:
        self._q.append((t, v))

    def rate(self) -> Optional[float]:
        """(last - first) / elapsed over the window (None if < 2 samples
        or no time elapsed)."""
        if len(self._q) < 2:
            return None
        (t0, v0), (t1, v1) = self._q[0], self._q[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def delta(self) -> Optional[Tuple[float, float]]:
        """(Δvalue, Δt) across the window."""
        if len(self._q) < 2:
            return None
        (t0, v0), (t1, v1) = self._q[0], self._q[-1]
        return v1 - v0, t1 - t0


class SignalEstimator:
    def __init__(self, window: int = 8):
        self.window = max(2, int(window))
        self._rounds: Dict[str, _Window] = {}    # per local server
        self._bytes: Dict[str, _Window] = {}     # per codec tag
        self._rtt: Dict[str, float] = {}
        self._boots: Dict[str, int] = {}
        self._rounds_total = 0

    # ---- ingestion ----------------------------------------------------------
    def ingest(self, now: float, server_stats: Dict[str, dict],
               report: Optional[dict] = None) -> WanSignals:
        """Fold one sampling sweep into the windows and return the fused
        observation.  ``server_stats`` maps local-server node string ->
        its QUERY_STATS body; ``report`` is an optional critical-path
        report (``TraceCollector.critical_path()``)."""
        total_rounds = 0
        for node, stats in server_stats.items():
            # boot fence: a warm-booted replacement reports from zero —
            # restart this node's windows so the reset neither reads as
            # "no rounds completing" (Δ <= 0 forever against the old
            # totals) nor as a goodput collapse
            boot = int(stats.get("boot", 0) or 0)
            if boot and self._boots.get(node, boot) != boot:
                self._rounds.pop(node, None)
                self._bytes.pop(node, None)
                self._rtt.pop(node, None)
            if boot:
                self._boots[node] = boot
            r = float(stats.get("wan_push_rounds", 0) or 0)
            total_rounds += int(r)
            self._rounds.setdefault(node, _Window(self.window)).push(now, r)
            self._bytes.setdefault(node, _Window(self.window)).push(
                now, float(stats.get("wan_send_bytes", 0) or 0))
            rtt = stats.get("hb_rtt_s")
            if rtt is not None and not math.isnan(float(rtt)):
                self._rtt[node] = float(rtt)
        self._rounds_total = total_rounds
        return WanSignals(
            t=now,
            round_time_s=self._round_time(),
            goodput_bps=self._goodput(),
            wan_bytes_rate=self._per_codec_rates(server_stats),
            rtt_s=max(self._rtt.values()) if self._rtt else None,
            dominant_stage=self._dominant(report),
            straggler_party=self._straggler(report),
            rounds_total=total_rounds,
        )

    # ---- derived signals ----------------------------------------------------
    def _round_time(self) -> Optional[float]:
        """Per-party round time = Δt/Δrounds; the deployment's effective
        round time is the SLOWEST party's (the FSA round gates on it)."""
        worst = None
        for w in self._rounds.values():
            d = w.delta()
            if d is None:
                continue
            d_rounds, dt = d
            if d_rounds <= 0:
                continue  # no round completed in the window — no sample
            rt = dt / d_rounds
            worst = rt if worst is None else max(worst, rt)
        return worst

    def _goodput(self) -> Optional[float]:
        total = None
        for w in self._bytes.values():
            r = w.rate()
            if r is None:
                continue
            total = r if total is None else total + r
        return total

    @staticmethod
    def _per_codec_rates(server_stats: Dict[str, dict]) -> Dict[str, float]:
        """Instantaneous per-codec-tag byte ledger from the in-process
        metrics registry (best-effort: empty cross-process, where only
        the QUERY_STATS totals are visible)."""
        try:
            from geomx_tpu.utils.metrics import system_snapshot
        except Exception:  # pragma: no cover
            return {}
        out: Dict[str, float] = {}
        for k, v in system_snapshot().items():
            if ".wan_bytes_" in k:
                tag = k.rsplit(".wan_bytes_", 1)[1]
                out[tag] = out.get(tag, 0.0) + float(v)
        return out

    @staticmethod
    def _last_round(report: Optional[dict]) -> Optional[dict]:
        if not report:
            return None
        rounds = report.get("rounds") or ()
        return rounds[-1] if rounds else None

    def _dominant(self, report: Optional[dict]) -> Optional[str]:
        r = self._last_round(report)
        return r.get("dominant_stage") if r else None

    def _straggler(self, report: Optional[dict]) -> Optional[str]:
        r = self._last_round(report)
        if not r:
            return None
        st = (r.get("stages") or {}).get(r.get("dominant_stage") or "", {})
        return st.get("straggler_party")
