#!/usr/bin/env bash
# ESync acceptance: heterogeneous-worker straggler balancing
# (the reference's to-be-integrated mode, README.md:45).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python examples/cnn_esync.py --parties 2 --workers 2 --steps "${STEPS:-8}" "$@"
