from geomx_tpu.compression.codecs import (  # noqa: F401
    Codec, CodecError, Fp16Codec, TwoBitCodec, BscCodec, MpqSelector,
    BroadcastCompressor, make_push_codec, decompress_payload,
    DecoderBank, compression_allowed, KNOWN_PUSH_TAGS, WEIGHT_SAFE_CODECS,
)
