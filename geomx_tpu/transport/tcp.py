"""TCP fabric: real sockets for multi-process / multi-host deployment.

The reference's transport is ZeroMQ ROUTER/DEALER TCP plus raw UDP
(ref: 3rdparty/ps-lite/src/zmq_van.h:41-193); this fabric provides the
same role with plain sockets and the framework's binary message format
(Message.to_bytes / from_bytes — length-prefixed frames).  It implements
the InProcFabric interface (register → mailbox, deliver), so the Van and
everything above it is transport-agnostic.

Addressing is static: every node gets ``base_port + index`` within the
deterministic ``Topology.all_nodes()`` order on its host (127.0.0.1 for
pseudo-distributed runs, per-node hosts via GEOMX_NODE_HOSTS JSON for
multi-host).  The reference's dynamic ADD_NODE registration is replaced
by this static plan; elastic join/recovery rides the heartbeat layer.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from geomx_tpu.core.config import Config, NodeId, Topology
from geomx_tpu.transport.message import Message
from geomx_tpu.transport.van import FaultPolicy, _Mailbox


def default_address_plan(topology: Topology, base_port: int = 9200,
                         hosts: Optional[Dict[str, str]] = None
                         ) -> Dict[str, Tuple[str, int]]:
    """node-str → (host, port).  Hosts default to loopback (the reference's
    pseudo-distributed mode, ref: docs/source/pseudo-distributed-deployment.rst);
    ``hosts`` overrides per node for multi-host."""
    hosts = hosts or {}
    plan = {}
    for i, n in enumerate(topology.all_nodes()):
        s = str(n)
        plan[s] = (hosts.get(s, "127.0.0.1"), base_port + i)
    return plan


def plan_from_env(topology: Topology) -> Dict[str, Tuple[str, int]]:
    base = int(os.environ.get("GEOMX_BASE_PORT", "9200"))
    hosts = json.loads(os.environ.get("GEOMX_NODE_HOSTS", "{}"))
    return default_address_plan(topology, base, hosts)


class TcpFabric:
    """One per process. Only the local node(s) register; deliver() dials
    the static plan."""

    def __init__(self, plan: Dict[str, Tuple[str, int]],
                 fault: Optional[FaultPolicy] = None,
                 config: Optional[Config] = None):
        if fault is None:
            fault = FaultPolicy.from_config(config) if config else FaultPolicy()
        self.fault = fault
        self.plan = plan
        self._boxes: Dict[str, _Mailbox] = {}
        self._listeners = []
        self._conns: Dict[str, socket.socket] = {}
        # per-destination locks: one slow/unreachable peer must not stall
        # sends to every other peer (heartbeats would time out and trigger
        # false dead-node detection)
        self._conn_mus: Dict[str, threading.Lock] = {}
        self._registry_mu = threading.Lock()
        self._stop = False
        self.dropped = 0

    # ---- local side ---------------------------------------------------------
    def register(self, node: NodeId) -> _Mailbox:
        s = str(node)
        if s in self._boxes:
            return self._boxes[s]
        box = _Mailbox()
        self._boxes[s] = box
        host, port = self.plan[s]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(64)
        self._listeners.append(srv)
        threading.Thread(target=self._accept_loop, args=(srv, box),
                         name=f"tcp-accept-{s}", daemon=True).start()
        return box

    def _accept_loop(self, srv: socket.socket, box: _Mailbox):
        while not self._stop:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=self._recv_loop, args=(conn, box),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket, box: _Mailbox):
        try:
            while not self._stop:
                hdr = self._recv_exact(conn, 8)
                if hdr is None:
                    return
                (n,) = struct.unpack("<q", hdr)
                data = self._recv_exact(conn, n)
                if data is None:
                    return
                box.q.put(Message.from_bytes(data))
        finally:
            conn.close()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # ---- send side ----------------------------------------------------------
    def deliver(self, msg: Message) -> bool:
        if self.fault.should_drop(msg):
            self.dropped += 1
            return False
        dest = str(msg.recipient)
        box = self._boxes.get(dest)
        if box is not None:  # local shortcut (several roles per process)
            box.q.put(msg)
            return True
        if dest not in self.plan:
            raise KeyError(f"no mailbox for {msg.recipient}")
        data = msg.to_bytes()
        frame = struct.pack("<q", len(data)) + data
        with self._registry_mu:
            mu = self._conn_mus.setdefault(dest, threading.Lock())
        with mu:
            conn = self._conns.get(dest)
            if conn is None:
                conn = self._dial(dest)
            try:
                conn.sendall(frame)
            except OSError:
                # peer restarted: redial once
                conn.close()
                conn = self._dial(dest)
                conn.sendall(frame)
        return True

    def _dial(self, dest: str) -> socket.socket:
        host, port = self.plan[dest]
        conn = socket.create_connection((host, port), timeout=30)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns[dest] = conn
        return conn

    def shutdown(self):
        self._stop = True
        for srv in self._listeners:
            try:
                srv.close()
            except OSError:
                pass
        with self._registry_mu:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
