"""Churn orchestrator: seeded, scripted spot-instance churn.

Production geo-distributed fleets run on preemptible capacity where
departure is the NORMAL case (the TensorFlow paper, PAPERS.md, makes
tolerating routinely-preempted workers a first-class requirement).
This module drives that case on purpose: a :class:`ChurnPlan` (seeded
Poisson arrival/departure rates, notice-vs-kill mix, min-survivor
floors, per-phase schedules) is pre-sampled into a deterministic event
tape, and :class:`ChurnOrchestrator` executes it against a live
``Simulation`` through the SAME paths a real fleet uses:

- graceful departure → ``Simulation.notice_worker`` (the
  ``Control.PREEMPT_NOTICE`` drain: flush, leave, immediate fold) then
  the host reclaim (``kill_worker``);
- ungraceful departure → ``kill_worker`` alone (the PR 2 heartbeat
  eviction path recovers);
- arrival → ``Simulation.add_worker`` + the harness's ``spawn``
  callback (dynamic join);
- local-server preemption → ``kill_local_server`` + a scheduled
  ``restart_local_server`` (fold → warm boot → unfold);
- serve-replica preemption → ``kill_replica`` + a scheduled
  ``restart_replica`` (eviction → view prune → dense-resync rejoin —
  the serving-plane soak's churn axis, ISSUE 15);
- region outage → ``Simulation.partition_party`` + a scheduled
  ``heal_party`` (WAN uplink dark, processes alive — the
  quarantine-not-evict axis; scripted standalone fault tapes live in
  geomx_tpu/chaos/netfault.py).

Every injected event is stamped into the global scheduler's flight
recorder (``FlightEv.CHURN``) and counted in the registry family
``churn_{notices,graceful_leaves,ungraceful_kills,joins,replica_kills,
outages,stall_rounds}``
so a postmortem can attribute a stall to an injected fault vs an
organic one, and the health engine's ``churn_storm`` rule can page on
transition rate / survivor floor (obs/health.py).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from geomx_tpu.utils.metrics import system_counter, system_gauge


@dataclasses.dataclass(frozen=True)
class ChurnPhase:
    """One phase of the plan: independent Poisson processes for worker
    departures, worker joins, and local-server preemptions over
    ``duration_s`` seconds."""

    duration_s: float
    departure_rate: float = 0.0   # worker departures per second
    join_rate: float = 0.0        # worker joins per second
    notice_fraction: float = 1.0  # P(a departure gets a preempt notice)
    server_kill_rate: float = 0.0  # local-server preemptions per second
    server_restart_s: float = 2.0  # replacement delay after a server kill
    replica_kill_rate: float = 0.0  # serve-replica preemptions per
    #                                 second (the serving-plane soak's
    #                                 churn axis, ISSUE 15)
    replica_restart_s: float = 2.0  # replacement delay after a replica
    #                                 kill (fresh boot, empty store —
    #                                 first refresh resyncs dense)
    outage_rate: float = 0.0        # region (party WAN-uplink) outages
    #                                 per second — the link-level fault
    #                                 axis (partition, not crash): the
    #                                 party's processes stay up, its WAN
    #                                 links go dark, and the detectors
    #                                 must QUARANTINE instead of evict
    outage_duration_s: float = 5.0  # how long each outage lasts before
    #                                 the uplink heals


@dataclasses.dataclass
class ChurnPlan:
    """Seeded, scripted churn schedule.  ``schedule()`` pre-samples the
    whole event tape — two plans with the same seed and phases produce
    the SAME tape, so a flaky soak reproduces."""

    phases: Tuple[ChurnPhase, ...]
    seed: int = 0
    min_workers_per_party: int = 1  # departure floor (survivors per party)
    max_workers_per_party: int = 4  # join ceiling per party
    min_servers_live: int = 1       # floor on simultaneously-live parties
    min_replicas_live: int = 1      # floor on simultaneously-live serve
    #                                 replicas (a kill that would breach
    #                                 it is skipped, like the worker floor)

    def schedule(self) -> List[Tuple[float, str, ChurnPhase]]:
        """The deterministic event tape: sorted ``(t, kind, phase)``
        triples with ``kind`` in {"depart", "join", "server_kill"}.
        Target picks happen at execution time (they depend on who is
        alive) from a second stream seeded off the same seed."""
        rng = random.Random(self.seed)
        tape: List[Tuple[float, str, ChurnPhase]] = []
        t0 = 0.0
        for ph in self.phases:
            for kind, rate in (("depart", ph.departure_rate),
                               ("join", ph.join_rate),
                               ("server_kill", ph.server_kill_rate),
                               ("replica_kill", ph.replica_kill_rate),
                               ("outage", ph.outage_rate)):
                if rate <= 0:
                    continue
                t = t0
                while True:
                    t += rng.expovariate(rate)
                    if t >= t0 + ph.duration_s:
                        break
                    tape.append((t, kind, ph))
            t0 += ph.duration_s
        tape.sort(key=lambda e: e[0])
        return tape

    @property
    def duration_s(self) -> float:
        return sum(ph.duration_s for ph in self.phases)


class ChurnOrchestrator:
    """Executes a :class:`ChurnPlan` against a live ``Simulation``.

    ``spawn(kv)`` is the harness hook invoked for every joined worker
    (start its training thread); without one, joiners register with the
    party server but never push (legal — their bootstrap pulls serve
    from completed rounds).  ``start()``/``stop()``/``join()`` manage
    the driver thread; ``run()`` executes inline.
    """

    def __init__(self, sim, plan: ChurnPlan,
                 spawn: Optional[Callable] = None,
                 stall_window_s: Optional[float] = None,
                 protect=()):
        self.sim = sim
        self.plan = plan
        self.spawn = spawn
        # nodes never picked for departure (e.g. a soak's loss-parity
        # observer; a real plan would pin on-demand capacity the same way)
        self.protect = {str(n) for n in protect}
        cfg = sim.config
        assert cfg.enable_preempt or all(
            ph.notice_fraction == 0 for ph in plan.phases), \
            "graceful notices need Config.enable_preempt"
        self.node = str(sim.topology.global_scheduler())
        # stall attribution: no global key-round progress for longer
        # than this window counts one churn_stall_rounds (default: the
        # eviction detector's worst honest stall — heartbeat timeout
        # plus a sweep — so only stalls the recovery machinery FAILED
        # to clear are flagged)
        self.stall_window_s = (
            stall_window_s if stall_window_s is not None
            else max(2.0 * cfg.heartbeat_timeout_s, 2.0))
        self._rng = random.Random(plan.seed + 1)  # target-pick stream
        self._tape = plan.schedule()
        self._mu = threading.Lock()
        # live bookkeeping: party -> {rank: kv}; server liveness
        self._alive: Dict[int, Dict[int, object]] = {}
        for p in range(sim.topology.num_parties):
            self._alive[p] = {w.rank: sim.workers[str(w)]
                              for w in sim.topology.workers(p)}
        self._server_live = {p: True
                             for p in range(sim.topology.num_parties)}
        self._replica_live = {r: True
                              for r in range(sim.topology.num_replicas)}
        self._restarts: List[Tuple[float, int]] = []  # (t, party)
        self._replica_restarts: List[Tuple[float, int]] = []  # (t, rank)
        self._outage_heals: List[Tuple[float, int]] = []  # (t, party)
        self._partitioned: Dict[int, bool] = {
            p: False for p in range(sim.topology.num_parties)}
        self.noticed: set = set()      # nodes that got a graceful notice
        self.killed: set = set()       # nodes killed ungracefully
        self.drain_latencies: List[float] = []
        self.events: List[dict] = []   # executed tape (postmortem aid)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._c_notices = system_counter(f"{self.node}.churn_notices")
        self._c_leaves = system_counter(
            f"{self.node}.churn_graceful_leaves")
        self._c_kills = system_counter(
            f"{self.node}.churn_ungraceful_kills")
        self._c_joins = system_counter(f"{self.node}.churn_joins")
        self._c_replica_kills = system_counter(
            f"{self.node}.churn_replica_kills")
        self._c_outages = system_counter(f"{self.node}.churn_outages")
        self._c_stalls = system_counter(
            f"{self.node}.churn_stall_rounds")
        self._g_survivors = system_gauge(f"{self.node}.churn_survivors")
        self._g_floor = system_gauge(
            f"{self.node}.churn_min_survivors")
        self._g_floor.set(plan.min_workers_per_party
                          * sim.topology.num_parties)
        self._update_survivors()

    # ---- lifecycle ----------------------------------------------------------
    def start(self) -> "ChurnOrchestrator":
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"churn-orchestrator-{self.node}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def join(self, timeout: Optional[float] = None):
        if self._thread is not None:
            self._thread.join(timeout)

    def stats(self) -> dict:
        with self._mu:
            return {
                "notices": self._c_notices.value,
                "graceful_leaves": self._c_leaves.value,
                "ungraceful_kills": self._c_kills.value,
                "joins": self._c_joins.value,
                "replica_kills": self._c_replica_kills.value,
                "outages": self._c_outages.value,
                "stall_rounds": self._c_stalls.value,
                "transitions": len(self.events),
                "survivors": self._survivor_count(),
                "drain_latency_s": sorted(self.drain_latencies),
            }

    # ---- execution ----------------------------------------------------------
    def run(self):
        """Execute the tape in real time (plus any scheduled server
        restarts), sampling the stall watchdog between events.  Tape
        times are relative to this call; restart deadlines are absolute
        monotonic stamps."""
        t_start = time.monotonic()
        i = 0
        last_progress = (self._progress(), time.monotonic())
        stalled_since: Optional[float] = None
        while not self._stop.is_set():
            now = time.monotonic()
            for r in [r for r in self._restarts if r[0] <= now]:
                self._restarts.remove(r)
                self._do_server_restart(r[1])
            for r in [r for r in self._replica_restarts if r[0] <= now]:
                self._replica_restarts.remove(r)
                self._do_replica_restart(r[1])
            for r in [r for r in self._outage_heals if r[0] <= now]:
                self._outage_heals.remove(r)
                self._do_outage_heal(r[1])
            deadlines = [r[0] for r in self._restarts]
            deadlines += [r[0] for r in self._replica_restarts]
            deadlines += [r[0] for r in self._outage_heals]
            if i < len(self._tape):
                deadlines.append(t_start + self._tape[i][0])
            if not deadlines:
                break
            wait = min(deadlines) - now
            if wait > 0:
                # stall watchdog rides the waits (<= 4 samples/s)
                if self._stop.wait(min(wait, 0.25)):
                    break
                last_progress, stalled_since = self._watch_stall(
                    last_progress, stalled_since)
                continue
            if (i < len(self._tape)
                    and t_start + self._tape[i][0] <= now):
                _, kind, ph = self._tape[i]
                i += 1
                try:
                    self._execute(kind, ph)
                except Exception:
                    import logging

                    logging.getLogger(__name__).exception(
                        "churn: injected %s failed", kind)
                self._update_survivors()

    def _watch_stall(self, last, stalled_since):
        prog, t_prog = last
        cur = self._progress()
        now = time.monotonic()
        if cur > prog:
            return (cur, now), None
        if now - t_prog > self.stall_window_s and stalled_since is None:
            self._c_stalls.inc()
            self._stamp("churn_stall_round", None,
                        note_extra=int((now - t_prog) * 1e3))
            return (cur, t_prog), now
        return (cur, t_prog), stalled_since

    def _progress(self) -> int:
        """Global-tier round progress (stall watchdog signal)."""
        total = 0
        for gs in getattr(self.sim, "global_servers", []):
            total += int(getattr(gs, "key_rounds", 0))
        return total

    def _survivor_count(self) -> int:
        return sum(len(v) for v in self._alive.values())

    def _update_survivors(self):
        self._g_survivors.set(self._survivor_count())

    def _stamp(self, note: str, target, note_extra: int = 0):
        po = self.sim.offices.get(self.node)
        fl = getattr(po, "flight", None) if po is not None else None
        if fl is not None:
            from geomx_tpu.obs.flight import FlightEv

            fl.record(FlightEv.CHURN, a=note_extra,
                      peer=None if target is None else str(target),
                      note=note)
        self.events.append({"t": time.monotonic(), "kind": note,
                            "target": None if target is None
                            else str(target)})

    # ---- the injected events ------------------------------------------------
    def _pick_departure(self):
        with self._mu:
            cands = {}
            for p, ws in self._alive.items():
                if (len(ws) <= self.plan.min_workers_per_party
                        or not self._server_live.get(p)):
                    continue
                ranks = [r for r in sorted(ws)
                         if f"worker:{r}@p{p}" not in self.protect]
                if ranks:
                    cands[p] = ranks
            if not cands:
                return None, None
            p = self._rng.choice(sorted(cands))
            return p, self._rng.choice(cands[p])

    def _execute(self, kind: str, ph: ChurnPhase):
        if kind == "depart":
            p, rank = self._pick_departure()
            if p is None:
                return  # survivor floor: the departure is skipped
            node_s = f"worker:{rank}@p{p}"
            graceful = self._rng.random() < ph.notice_fraction
            if graceful:
                self._c_notices.inc()
                self.noticed.add(node_s)
                self._stamp("churn_notice", node_s)
                reply = self.sim.notice_worker(
                    p, rank, timeout=self.sim.config.preempt_drain_s + 5)
                if reply and reply.get("ok"):
                    self._c_leaves.inc()
                    self.drain_latencies.append(
                        float(reply["latency_s"]))
                    self._stamp("churn_graceful_leave", node_s)
            else:
                self._c_kills.inc()
                self.killed.add(node_s)
                self._stamp("churn_kill", node_s)
            # the host reclaim (for a drained worker this is the
            # preemption landing AFTER the graceful leave — the
            # eviction monitor must stay quiet; for an ungraceful one
            # it IS the fault)
            try:
                self.sim.kill_worker(p, rank)
            except KeyError:
                pass  # already gone
            with self._mu:
                self._alive[p].pop(rank, None)
        elif kind == "join":
            with self._mu:
                parties = [p for p, ws in self._alive.items()
                           if len(ws) < self.plan.max_workers_per_party
                           and self._server_live.get(p)]
            if not parties:
                return
            p = self._rng.choice(parties)
            kv = self.sim.add_worker(p)
            self._c_joins.inc()
            with self._mu:
                self._alive[p][kv.po.node.rank] = kv
            self._stamp("churn_join", kv.po.node)
            if self.spawn is not None:
                self.spawn(kv)
        elif kind == "server_kill":
            with self._mu:
                live = [p for p, up in self._server_live.items() if up]
                if len(live) <= self.plan.min_servers_live:
                    return
                p = self._rng.choice(live)
                self._server_live[p] = False
            self._c_kills.inc()
            self._stamp("churn_server_kill", f"server:0@p{p}")
            self.sim.kill_local_server(p)
            self._restarts.append(
                (time.monotonic() + ph.server_restart_s, p))
        elif kind == "replica_kill":
            with self._mu:
                live = [r for r, up in self._replica_live.items()
                        if up and f"replica:{r}" not in self.protect]
                if len([r for r, up in self._replica_live.items()
                        if up]) <= self.plan.min_replicas_live \
                        or not live:
                    return  # replica floor: the kill is skipped
                r = self._rng.choice(sorted(live))
                self._replica_live[r] = False
            self._c_replica_kills.inc()
            self.killed.add(f"replica:{r}")
            self._stamp("churn_replica_kill", f"replica:{r}")
            self.sim.kill_replica(r)
            self._replica_restarts.append(
                (time.monotonic() + ph.replica_restart_s, r))
        elif kind == "outage":
            # region outage: the party's WAN uplink dies, every process
            # behind it keeps running — the quarantine-not-evict axis.
            # Only parties whose server is UP and not already dark
            # qualify (an outage of a dead server tests nothing).
            with self._mu:
                cands = [p for p, up in self._server_live.items()
                         if up and not self._partitioned[p]]
                if not cands:
                    return
                p = self._rng.choice(sorted(cands))
                self._partitioned[p] = True
            self._c_outages.inc()
            self._stamp("churn_outage", f"server:0@p{p}")
            self.sim.partition_party(p)
            self._outage_heals.append(
                (time.monotonic() + ph.outage_duration_s, p))

    def _do_outage_heal(self, party: int):
        self.sim.heal_party(party)
        with self._mu:
            self._partitioned[party] = False
        self._stamp("churn_outage_heal", f"server:0@p{party}")
        print(f"churn: healed outage of party {party}", flush=True)

    def _do_replica_restart(self, rank: int):
        self.sim.restart_replica(rank)
        with self._mu:
            self._replica_live[rank] = True
        self._stamp("churn_replica_restart", f"replica:{rank}")
        print(f"churn: restarted replica:{rank}", flush=True)

    def _do_server_restart(self, party: int):
        self.sim.restart_local_server(party)
        with self._mu:
            self._server_live[party] = True
        self._stamp("churn_server_restart", f"server:0@p{party}")
        print(f"churn: restarted server:0@p{party}", flush=True)
