"""Shared model-factory plumbing: every family returns the same
(model, params, grad_fn) contract so training loops, examples, and the
kvstore integration swap models freely."""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


def group_norm(features: int, dtype):
    """GroupNorm with groups derived from the channel count — hard-coding
    8 crashes opaquely for widths not divisible by 8."""
    return nn.GroupNorm(num_groups=math.gcd(8, features), dtype=dtype)


def make_grad_fn(model):
    """Jitted ``grad_fn(params, x, y) -> (loss, acc, grads)`` with
    log-softmax NLL + accuracy — the one loss definition all families use."""

    def loss_fn(params, x, y):
        logits = model.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
        acc = jnp.mean(jnp.argmax(logits, -1) == y)
        return loss, acc

    @jax.jit
    def grad_fn(params, x, y):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x, y)
        return loss, acc, grads

    return grad_fn
