"""Replica autoscaler: telemetry-driven elastic serve capacity.

The serving tier's capacity was static (PR 8: ``--replicas K`` forever).
:class:`ReplicaAutoscaler` closes the loop on the global scheduler, the
same shape as the PR 4 ``WanPolicyEngine``: sample the telemetry
plane's per-replica series (``serve_qps`` / shed rate / ``serve_p99_ms``
/ staleness), decide with **deadband + patience + cooldown** hysteresis,
and actuate through the machinery the tier already has:

- **scale down** is reversible retirement: ``Ctrl.SERVE_SCALE
  {active: False}`` tells the replica to pause its refresh loop and
  shed reads with the explicit RETRY_AFTER signal (the balancer routes
  away within one view refresh), then the shard holders get
  ``Control.EVICT {subscriber_prune}`` — the PR 8 eviction actuation —
  so the retired copy's tracked pull views stop pinning a full model;
- **scale up** prefers reactivating a retired-but-live replica
  (``SERVE_SCALE {active: True}``: its next refresh resyncs DENSE,
  exactly the eviction→rejoin heal), and otherwise asks the harness's
  ``spawn`` callback to start replica rank K (a real deployment maps
  this to its process manager; ``Simulation`` maps it to
  ``restart_replica``) — the :class:`~geomx_tpu.serve.monitor.
  ReplicaMonitor` then observes the heartbeats exactly as it would any
  operator-started replica.

Hysteresis discipline: scale-up needs ``serve_scale_patience``
consecutive overloaded sweeps, scale-down twice that (shrinking is the
risky direction), and any action freezes decisions for
``serve_scale_cooldown_s``.  A desired direction that REVERSES the last
action inside its cooldown is counted (``autoscale_flaps`` — the
``replica_flap`` health rule pages on it) but never executed, so the
actuated sequence can never flap faster than the cooldown.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from geomx_tpu.core.config import Config, Role
from geomx_tpu.kvstore.common import Ctrl
from geomx_tpu.ps import Postoffice
from geomx_tpu.ps.kv_app import _App
from geomx_tpu.trace.recorder import get_tracer
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.utils.metrics import system_counter, system_gauge

# customer id for the autoscaler's command endpoint on the scheduler's
# postoffice (the adaptive-WAN controller owns 96; responses route by
# exact (app, customer), so they never collide)
_SCALE_CUSTOMER = 97


class _CmdEndpoint(_App):
    """Command-channel-only app: sends Ctrl.* requests, collects
    replies.  Never sees data traffic."""

    def _process(self, msg: Message):
        if not msg.push and not msg.pull:
            self._handle_command(msg)

    def rpc(self, recipient, head, body=None, timeout: float = 3.0,
            domain: Domain = Domain.GLOBAL) -> Optional[dict]:
        ts = self.send_cmd(recipient, head, body=body, domain=domain,
                           wait=False)
        try:
            self.customer.wait(ts, timeout=timeout)
        except TimeoutError:
            return None
        reply = self.cmd_response(ts)
        return reply if isinstance(reply, dict) else {}


class ReplicaAutoscaler:
    """One per deployment, on the global scheduler's postoffice.
    ``serve_scale_interval_s <= 0`` runs no sweep thread — tests (and
    the bench soak) drive :meth:`tick` deterministically."""

    def __init__(self, postoffice: Postoffice,
                 config: Optional[Config] = None, collector=None,
                 spawn: Optional[Callable[[int], None]] = None,
                 retire_cb: Optional[Callable[[int], None]] = None):
        assert postoffice.node.role is Role.GLOBAL_SCHEDULER, \
            "the replica autoscaler runs on the global scheduler"
        from geomx_tpu.kvstore.replication import ShardTargets

        self.po = postoffice
        self.config = config or postoffice.config
        self.collector = collector
        self.spawn = spawn          # start replica rank K (cold)
        self.retire_cb = retire_cb  # optional host reclaim after retire
        self.topology = postoffice.topology
        cfg = self.config
        self.min_replicas = int(cfg.serve_min_replicas)
        self.max_replicas = int(cfg.serve_max_replicas
                                or self.topology.num_replicas)
        self.max_replicas = min(self.max_replicas,
                                self.topology.num_replicas)
        self.cooldown_s = float(cfg.serve_scale_cooldown_s)
        self.patience = max(1, int(cfg.serve_scale_patience))
        self.target_qps = float(cfg.serve_target_qps)
        self.p99_ms = float(cfg.serve_scale_p99_ms)
        self.bound_s = float(cfg.serve_staleness_s)
        # rate reads look back a bounded window (not the whole ring):
        # a shed burst from minutes ago must not read as CURRENT
        # overload for as long as the ring remembers it
        self.lookback_s = max(5.0, 3.0 * float(cfg.serve_scale_interval_s))
        self._shards = ShardTargets(postoffice)
        self._cmd = _CmdEndpoint(0, _SCALE_CUSTOMER, postoffice)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._evict_replies: Dict[str, dict] = {}
        postoffice.add_control_hook(self._on_control)
        self._retired: set = set()   # ranks we scaled down (reversible)
        self._over = 0
        self._under = 0
        self._last_action = -float("inf")
        self._last_dir = 0
        self._flap_marked = False
        self.decisions: List[dict] = []  # audit trail
        self.flaps = 0
        n = str(postoffice.node)
        self._c_ups = system_counter(f"{n}.autoscale_ups")
        self._c_downs = system_counter(f"{n}.autoscale_downs")
        self._c_flaps = system_counter(f"{n}.autoscale_flaps")
        self._g_desired = system_gauge(f"{n}.serve_desired_replicas")
        self._g_active = system_gauge(f"{n}.serve_active_replicas")
        self._tr = get_tracer(n)
        self._stop = threading.Event()
        self._thread = None
        iv = float(cfg.serve_scale_interval_s)
        if iv > 0:
            self._thread = threading.Thread(
                target=self._run, args=(iv,), daemon=True,
                name=f"replica-autoscaler-{postoffice.node}")
            self._thread.start()

    def _run(self, interval: float):
        while not self._stop.wait(interval):
            try:
                self.tick()
            except Exception:  # a sweep error must not kill the loop
                import logging

                logging.getLogger(__name__).exception(
                    "%s: autoscaler sweep failed", self.po.node)

    # ---- membership view -----------------------------------------------------
    def _on_control(self, msg: Message) -> bool:
        """Token-matched EVICT replies for the subscriber-prune RPC
        (observe-only: the recovery/replica monitors on this node see
        their own tokens)."""
        if msg.control is Control.EVICT and not msg.request:
            b = msg.body if isinstance(msg.body, dict) else {}
            token = b.get("token")
            if isinstance(token, str) and token.startswith("autoscale#"):
                with self._cv:
                    self._evict_replies[token] = b
                    while len(self._evict_replies) > 256:
                        self._evict_replies.pop(
                            next(iter(self._evict_replies)))
                    self._cv.notify_all()
                return True
        return False

    def live_ranks(self) -> List[int]:
        """Replica ranks currently alive: heartbeat freshness when
        heartbeats run, else collector visibility, else the whole
        plan (nothing to judge by)."""
        topo = self.topology
        ranks = list(range(topo.num_replicas))
        if self.config.heartbeat_interval_s > 0:
            info, epoch = self.po.heartbeat_info()
            now = time.monotonic()
            out = []
            for r in ranks:
                s = str(topo.replica(r))
                t, _boot = info.get(s, (None, 0))
                age = now - (t if t is not None else epoch)
                if age <= self.config.heartbeat_timeout_s:
                    out.append(r)
            return out
        if self.collector is not None:
            seen = [r for r in ranks
                    if self.collector.latest(str(topo.replica(r)))
                    is not None]
            if seen:
                return seen
        return ranks

    def active_ranks(self) -> List[int]:
        return [r for r in self.live_ranks() if r not in self._retired]

    # ---- signals -------------------------------------------------------------
    def _signals(self, active: List[int]) -> dict:
        out = {"qps": None, "shed_rate": None, "p99_ms": None,
               "staleness_worst_s": None}
        if self.collector is None or not active:
            return out
        qps = shed = 0.0
        saw_rate = False
        p99: Optional[float] = None
        stale: Optional[float] = None
        for r in active:
            node = str(self.topology.replica(r))
            v = self.collector.rate(node, "serve_pulls",
                                    lookback_s=self.lookback_s)
            if v is not None:
                qps += max(0.0, v)
                saw_rate = True
            v = self.collector.rate(node, "serve_sheds",
                                    lookback_s=self.lookback_s)
            if v is not None:
                shed += max(0.0, v)
                saw_rate = True
            st = self.collector.latest_stats(node) or {}
            v = st.get("serve_p99_ms")
            if isinstance(v, (int, float)):
                p99 = max(p99 or 0.0, float(v))
            v = st.get("staleness_s")
            if isinstance(v, (int, float)):
                stale = max(stale or 0.0, float(v))
        if saw_rate:
            out["qps"] = qps
            out["shed_rate"] = shed
        out["p99_ms"] = p99
        out["staleness_worst_s"] = stale
        return out

    def _direction(self, sig: dict, n_active: int) -> int:
        """+1 = overloaded (grow), -1 = idle (shrink), 0 = in band."""
        shed = sig.get("shed_rate")
        if shed is not None and shed > 0.0:
            return +1
        p99 = sig.get("p99_ms")
        if self.p99_ms > 0 and isinstance(p99, (int, float)) \
                and p99 > self.p99_ms:
            return +1
        stale = sig.get("staleness_worst_s")
        if isinstance(stale, (int, float)) and stale > self.bound_s:
            return +1
        qps = sig.get("qps")
        if self.target_qps > 0 and qps is not None and n_active > 0:
            if qps / n_active > self.target_qps:
                return +1
            # shrink only when the load would STILL sit comfortably
            # under target after losing one replica (the deadband: no
            # thrash at the boundary)
            if qps / max(n_active - 1, 1) < 0.5 * self.target_qps:
                return -1
        return 0

    # ---- decision loop -------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One sweep: sample -> hysteresis -> at most one scaling
        action.  Returns the decision record (also appended to
        ``decisions``) or None."""
        now = time.monotonic() if now is None else now
        live = self.live_ranks()
        active = [r for r in live if r not in self._retired]
        n = len(active)
        self._g_active.set(float(n))
        self._g_desired.set(float(n))
        sig = self._signals(active)
        want = self._direction(sig, n)
        if want > 0:
            self._over += 1
            self._under = 0
        elif want < 0:
            self._under += 1
            self._over = 0
        else:
            self._over = self._under = 0
        if now - self._last_action < self.cooldown_s:
            # cooling down: keep counting, never act — and count an
            # attempted direction REVERSAL (the flap the health rule
            # pages on) exactly once per cooldown window
            if (want != 0 and self._last_dir != 0
                    and want != self._last_dir
                    and not self._flap_marked):
                self._flap_marked = True
                self.flaps += 1
                self._c_flaps.inc()
            return None
        if self._over >= self.patience and n < self.max_replicas:
            return self._act(+1, live, active, sig, now)
        # shrinking needs twice the patience: the risky direction is
        # the one that gives capacity back
        if self._under >= 2 * self.patience and n > self.min_replicas:
            return self._act(-1, live, active, sig, now)
        return None

    def _act(self, direction: int, live: List[int], active: List[int],
             sig: dict, now: float) -> Optional[dict]:
        if direction > 0:
            rank, how = self._scale_up(live, active)
        else:
            rank, how = self._scale_down(active)
        if rank is None:
            return None
        self._over = self._under = 0
        self._last_action = now
        self._last_dir = direction
        self._flap_marked = False
        (self._c_ups if direction > 0 else self._c_downs).inc()
        n_after = len(active) + direction
        self._g_desired.set(float(n_after))
        rec = {
            "action": "scale_up" if direction > 0 else "scale_down",
            "replica": rank, "how": how, "active_after": n_after,
            "t_mono": now, "signals": dict(sig),
        }
        self.decisions.append(rec)
        del self.decisions[:-256]
        self._tr.instant("autoscale.decision", action=rec["action"],
                         replica=rank, active=n_after)
        print(f"{self.po.node}: autoscale {rec['action']} replica:"
              f"{rank} via {how} (active={n_after}, "
              f"qps={sig.get('qps')}, shed={sig.get('shed_rate')}, "
              f"p99={sig.get('p99_ms')})", flush=True)
        return rec

    # ---- actuation -----------------------------------------------------------
    def _scale_up(self, live: List[int], active: List[int]):
        # prefer reactivating a retired-but-live replica: one
        # SERVE_SCALE round trip and a dense resync, no cold start
        for r in sorted(self._retired):
            if r in live:
                reply = self._cmd.rpc(self.topology.replica(r),
                                      Ctrl.SERVE_SCALE,
                                      body={"active": True})
                if reply is not None and reply.get("ok"):
                    self._retired.discard(r)
                    return r, "reactivate"
        if self.spawn is not None:
            for r in range(self.topology.num_replicas):
                if r not in live:
                    self._retired.discard(r)
                    try:
                        self.spawn(r)
                    except Exception:
                        import logging

                        logging.getLogger(__name__).exception(
                            "%s: replica spawn(%d) failed",
                            self.po.node, r)
                        return None, ""
                    return r, "spawn"
        return None, ""

    def _scale_down(self, active: List[int]):
        if not active:
            return None, ""
        r = max(active)  # keep the low ranks stable
        reply = self._cmd.rpc(self.topology.replica(r), Ctrl.SERVE_SCALE,
                              body={"active": False})
        if reply is None or not reply.get("ok"):
            return None, ""  # unreachable: the monitor's eviction path
            #                  owns a genuinely dead replica
        self._retired.add(r)
        self._prune_views(r)
        if self.retire_cb is not None:
            try:
                self.retire_cb(r)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "%s: retire_cb(%d) failed", self.po.node, r)
        return r, "retire"

    def _prune_views(self, rank: int):
        """Free the retired replica's tracked pull views at every shard
        holder — the same ``EVICT {subscriber_prune}`` actuation the
        ReplicaMonitor fires for a dead replica, so a retired copy
        stops pinning one full model per shard."""
        replica_s = str(self.topology.replica(rank))
        for gs in self._shards.global_servers():
            token = f"autoscale#{uuid.uuid4().hex[:8]}"
            try:
                self.po.van.send(Message(
                    recipient=gs, control=Control.EVICT,
                    domain=Domain.GLOBAL, request=True,
                    body={"action": "subscriber_prune",
                          "node": replica_s, "token": token}))
            except (KeyError, OSError):
                continue  # shard mid-failover; the monitor's eviction
                #           path re-prunes if the replica later dies
            with self._cv:
                self._cv.wait_for(lambda: token in self._evict_replies,
                                  timeout=2.0)
                self._evict_replies.pop(token, None)

    # ---- observability -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "active_replicas": len(self.active_ranks()),
            "live_replicas": len(self.live_ranks()),
            "retired": sorted(self._retired),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "scale_ups": self._c_ups.value,
            "scale_downs": self._c_downs.value,
            "flaps": self.flaps,
            "decisions": len(self.decisions),
        }

    def stop(self):
        self._stop.set()
        self._cmd.stop()
