// Native record-file scanner for the data subsystem.
//
// The reference's data path is native too (ref: src/io/ — dmlc record-IO
// readers + iterators, 6.4k LoC C++).  The wire format here mirrors
// dmlc-core's recordio (ref: 3rdparty/dmlc-core/include/dmlc/recordio.h):
// each record is [u32 magic | u32 lrec | payload | pad-to-4], where the
// low 29 bits of lrec are the payload length.  Writing is cold-path
// Python; this scanner is the hot path that builds the random-access
// index over a (possibly multi-GB) record file in one pass.

#include <cstdint>
#include <cstring>

extern "C" {

static const uint32_t kGeoRecMagic = 0xced7230a;

// Scan `buf` and emit (offset, length) pairs of record payloads.
// Returns the record count, or -(1 + byte_offset) on a corrupt record
// boundary so the caller can report where the file went bad.
int64_t geo_recordio_index(const uint8_t* buf, int64_t size,
                           int64_t max_records, int64_t* offsets,
                           int64_t* lengths) {
  int64_t pos = 0;
  int64_t n = 0;
  while (pos + 8 <= size && n < max_records) {
    uint32_t magic, lrec;
    std::memcpy(&magic, buf + pos, 4);
    std::memcpy(&lrec, buf + pos + 4, 4);
    if (magic != kGeoRecMagic) return -(1 + pos);
    const int64_t len = static_cast<int64_t>(lrec & ((1u << 29) - 1));
    if (pos + 8 + len > size) return -(1 + pos);
    offsets[n] = pos + 8;
    lengths[n] = len;
    ++n;
    pos += 8 + ((len + 3) & ~int64_t(3));  // payload padded to 4 bytes
  }
  if (pos != size && n < max_records) return -(1 + pos);
  return n;
}

}  // extern "C"
