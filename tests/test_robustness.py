"""Targeted regression nets for behaviors the parity map claims but no
test exercised directly: the server's pull-queue split (slow pushes must
not starve pulls, ref: customer.h:91-101), TCP peer-restart recovery,
and checkpointing under concurrent training."""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.ps import KVPairs, KVServer, KVWorker, Postoffice
from geomx_tpu.ps.postoffice import split_range
from geomx_tpu.transport import InProcFabric, Message, Van


def test_pull_queue_split_avoids_push_starvation():
    """A handler stuck processing a push must not delay pull serving —
    pulls ride their own queue/thread (ref: customer.h:91-101)."""
    topo = Topology(num_parties=1, workers_per_party=1)
    fabric = InProcFabric()
    cfg = Config(topology=topo)
    offices = {str(n): Postoffice(n, topo, fabric, cfg) for n in topo.all_nodes()}
    for po in offices.values():
        po.start()
    push_block = threading.Event()
    served = []

    def handle(msg, kvs, server):
        if msg.push:
            push_block.wait(5)  # simulate a slow aggregation
            server.response(msg)
        else:
            served.append(time.monotonic())
            server.response(msg, KVPairs(
                kvs.keys, np.zeros(4, np.float32), np.array([4])))

    sn = topo.server(0)
    server = KVServer(0, 0, offices[str(sn)], handle, split_pull_queue=True)
    w = topo.workers(0)[0]
    kw = KVWorker(0, 1, offices[str(w)], [sn], split_range(1))
    kw.zpush(KVPairs(np.array([1]), np.ones(4, np.float32), np.array([4])))
    t0 = time.monotonic()
    kw.zpull([1], wait=True)  # must be served while the push blocks
    assert time.monotonic() - t0 < 2.0, "pull starved behind blocked push"
    push_block.set()
    kw.stop(); server.stop()
    for po in offices.values():
        po.stop()
    fabric.shutdown()


@pytest.mark.slow
def test_tcp_peer_restart_recovery_via_resend():
    """A receiver that restarts (new listener on the same port) keeps
    receiving.  TCP gives no delivery guarantee across a crash — the first
    post-crash send can vanish into a half-closed connection — so recovery
    is resend (retransmit) + redial (reconnect), layered exactly like the
    reference (ref: resender.h + zmq reconnect)."""
    from geomx_tpu.transport.tcp import TcpFabric, default_address_plan
    from tests.test_tcp import free_base_port

    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=free_base_port())
    a, b = topo.workers(0)[0], topo.server(0)
    rcfg = Config(topology=topo, resend_timeout_ms=100)

    fab_a = TcpFabric(plan)
    van_a = Van(a, fab_a, config=rcfg)
    van_a.start(lambda m: None)

    got = []

    def start_receiver():
        fab = TcpFabric(plan)
        van = Van(b, fab, config=rcfg)
        van.start(lambda m: got.append(m.timestamp))
        return fab, van

    fab_b, van_b = start_receiver()
    van_a.send(Message(recipient=b, timestamp=1,
                       vals=np.ones(2, np.float32)))
    deadline = time.monotonic() + 5
    while 1 not in got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert 1 in got

    # restart the receiver (old sockets die, same port re-bound)
    van_b.stop(); fab_b.shutdown()
    time.sleep(0.2)
    fab_b, van_b = start_receiver()
    van_a.send(Message(recipient=b, timestamp=2,
                       vals=np.ones(2, np.float32)))
    deadline = time.monotonic() + 15
    while 2 not in got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert 2 in got, "resend+redial after peer restart failed"
    van_a.stop(); fab_a.shutdown()
    van_b.stop(); fab_b.shutdown()


def test_checkpoint_during_concurrent_training(tmp_path):
    """Saving a checkpoint mid-training must not deadlock or corrupt the
    run (serialization happens outside the server lock)."""
    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=1)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(20_000, np.float32))
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        stop = threading.Event()
        errs = []

        def trainer(w):
            try:
                while not stop.is_set():
                    w.push(0, np.ones(20_000, np.float32))
                    w.pull_sync(0)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=trainer, args=(w,)) for w in ws]
        for t in threads:
            t.start()
        time.sleep(0.2)
        for _ in range(3):
            paths = ws[0].save_server_checkpoints(str(tmp_path))
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        # the checkpoint is loadable and holds the full tensor
        from geomx_tpu.kvstore.checkpoint import load_server_state

        store, _, _ = load_server_state(paths[0])
        assert sum(len(v) for v in store.values()) == 20_000
    finally:
        sim.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_drops_joins_leaves_compression():
    """Everything at once, long horizon: 2-party BSC-compressed training
    under 15% message drop (resend recovering), with a worker JOINING
    one party mid-run, another LEAVING, and a third KILLED ungracefully
    (no leave — the heartbeat eviction must fold it out and fence its
    zombie) — 52 steps end-to-end, every surviving worker finishes
    finite and the party replicas agree at the end.  The reference's
    equivalents are PS_DROP_MSG + the keepalive launcher; none of its
    modes survive membership churn on top."""
    import threading

    import jax

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker
    from geomx_tpu.transport.van import FaultPolicy

    sim = Simulation(
        Config(topology=Topology(num_parties=2, workers_per_party=2),
               resend_timeout_ms=150, request_retry_s=2.0,
               heartbeat_interval_s=0.1, heartbeat_timeout_s=1.0),
        fault=FaultPolicy(drop_rate=0.15, seed=11))
    try:
        x, y = synthetic_classification(n=512, shape=(8, 8, 1), seed=3)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        # compression is configured PER PARTY SERVER (every party's
        # rank-0 worker must call it) — configuring only party 0 would
        # leave half the "compressed" topology running dense
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression(
                {"type": "bsc", "ratio": 0.1})
        hist = {}
        errs = []

        def train(kv, widx, nw, steps, leave_after=None):
            try:
                it = ShardedIterator(x, y, 16, widx, nw, seed=4)
                h = run_worker(kv, params, grad_fn, it, steps,
                               barrier_init=False)
                if leave_after is not None:
                    kv.wait_all()
                    kv.leave_party()
                hist[widx] = h
            except Exception as e:  # noqa: BLE001 — assert below
                errs.append((widx, repr(e)))

        # phase 1: static plan trains 20 steps; party-0 worker 1 will
        # leave at the end of its run
        ths = [threading.Thread(target=train, args=(w, i, 4, 20),
                                kwargs=dict(leave_after=20 if i == 1
                                            else None))
               for i, w in enumerate(ws)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=300)
        assert not errs, errs
        assert len(hist) == 4, "a worker hung in phase 1"

        # phase 2: a NEW worker joins party 1 and the remaining three
        # train 20 more steps under the same drop rate
        w4 = sim.add_worker(1)
        survivors = [ws[0]] + ws[2:] + [w4]
        hist.clear()
        ths = [threading.Thread(target=train, args=(w, i, 4, 20))
               for i, w in enumerate(survivors)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=300)
        assert not errs, errs
        assert len(hist) == 4, "a worker hung post-churn"
        for h in hist.values():
            assert np.isfinite([loss for loss, _ in h]).all()

        # FSA invariant survives the churn: both party stores agree
        s0, s1 = sim.local_servers[0].store, sim.local_servers[1].store
        for k in s0:
            np.testing.assert_allclose(s0[k], s1[k], rtol=1e-4,
                                       atol=1e-5)

        # phase 3: an UNGRACEFUL kill — worker:0@p1 dies without a
        # leave message; the remaining three stall at most one heartbeat
        # timeout before the eviction folds it out, then train 12 more
        # steps under the same drop rate
        sim.kill_worker(1, 0)
        survivors3 = [ws[0], ws[3], w4]
        hist.clear()
        ths = [threading.Thread(target=train, args=(w, i, 3, 12))
               for i, w in enumerate(survivors3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=300)
        assert not errs, errs
        assert len(hist) == 3, "a survivor hung after the ungraceful kill"
        for h in hist.values():
            assert np.isfinite([loss for loss, _ in h]).all()
        assert sim.local_servers[1].evicted_workers == 1
        assert sim.eviction_monitors[1].evictions == 1

        # the zombie resumes and pushes its stale round — fenced, told
        # to rejoin; the survivor-set training above stays untouched
        ws[2].po.start()
        ws[2].push(0, np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="evicted"):
            ws[2].wait_all()
        assert sim.local_servers[1].eviction_fenced_pushes >= 1

        # convergence on the survivor set: the party stores still agree
        for k in s0:
            np.testing.assert_allclose(
                sim.local_servers[0].store[k],
                sim.local_servers[1].store[k], rtol=1e-4, atol=1e-5)
    finally:
        sim.shutdown()
