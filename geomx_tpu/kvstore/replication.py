"""Global-tier hot-standby replication and automatic failover.

The reference leaves global-tier recovery as an explicit TODO
(ref: van.cc:224); this subsystem closes it with the classic
parameter-server fault-tolerance shape (PAPERS.md: "TensorFlow: A system
for large-scale machine learning" — PS state replication + automatic
recovery):

- ``Replicator`` (runs inside a primary :class:`GlobalServer`): after
  every ``Config.replicate_every`` optimizer updates, snapshot the
  server state (weights + optimizer + sync/compression meta + the
  replay-dedup done-window) and stream it to the shard's hot standby as
  one ``Cmd.REPLICATE`` push — the ``kvstore/checkpoint.py`` slab format
  over the wire instead of disk.  Ships are async (a serialize must not
  stall the merge path) and self-coalescing (a ship in flight defers the
  next snapshot instead of queueing).
- ``GlobalFailoverMonitor`` (runs on the global scheduler): watches the
  postoffice heartbeat/dead-node table; when a primary global server
  misses heartbeats past the timeout it bumps the shard's **term**,
  promotes the standby (``Control.PROMOTE``), and broadcasts
  ``Control.NEW_PRIMARY`` so every local server retargets its WAN
  endpoint and replays un-ACKed requests (``KVWorker.retarget``).
  Replays are exactly-once: the standby was seeded with the primary's
  replay-dedup window, so a request the dead primary applied *and*
  replicated is re-acked, not re-applied; the van boot nonce keeps a
  replayed client distinguishable from a replaced one.
- **Term fencing**: each promotion increments the shard's term.  A
  zombie ex-primary that comes back keeps its stale term; its
  replication pushes are rejected by the promoted standby
  (``fenced_rejects`` counter) and the rejection — or a late
  ``NEW_PRIMARY`` rebroadcast — flips it into a fenced state where it
  refuses data pushes instead of split-braining the store.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

import numpy as np

from geomx_tpu.core.config import NodeId, Role
from geomx_tpu.kvstore.common import APP_PS, Cmd
from geomx_tpu.ps import KVPairs, KVWorker, Postoffice
from geomx_tpu.ps.postoffice import split_range
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.utils.metrics import system_counter, system_gauge

# customer id of the replication endpoint on a primary global server
# (0 = the KVServer; local servers use 1 for their up-link worker)
REPL_CUSTOMER_ID = 7
# customer id of a draining holder's handoff ship endpoint (key-range
# reassignment; distinct from REPL_CUSTOMER_ID — a primary may be
# replicating to its standby AND draining at once)
HANDOFF_CUSTOMER_ID = 8


class ShardTargets:
    """Failover-aware view of *who currently serves each global shard*.

    The static plan says shard ``k`` is ``global_server:k``, but after a
    promotion (PR 1) or a live key-range reassignment the current holder
    differs.  Every component on a postoffice that must ADDRESS the
    global tier by shard — the recovery monitor's party folds, the
    adaptive-WAN controller's policy broadcasts, operator tooling —
    shares this tracker instead of each re-implementing NEW_PRIMARY
    bookkeeping.  The hook observes only (returns False), so every other
    NEW_PRIMARY consumer on the node still fires."""

    def __init__(self, postoffice: Postoffice):
        self.po = postoffice
        self._mu = threading.Lock()
        self._replaced: dict = {}  # old node str -> new node str
        postoffice.add_control_hook(self._on_new_primary)

    def _on_new_primary(self, msg: Message) -> bool:
        if msg.control is Control.NEW_PRIMARY and not msg.request:
            b = msg.body if isinstance(msg.body, dict) else {}
            if b.get("old") and b.get("new") and b["old"] != b["new"]:
                with self._mu:
                    self._replaced[str(b["old"])] = str(b["new"])
        return False  # observe-only

    def record(self, old, new) -> None:
        """Local fast path for components on the SAME postoffice as the
        failover monitor (its own broadcast loops back eventually, but
        the mapping must be current the moment promote() returns)."""
        old, new = str(old), str(new)
        if old != new:
            with self._mu:
                self._replaced[old] = new

    def resolve(self, node) -> NodeId:
        s = str(node)
        with self._mu:
            for _ in range(8):  # chained failovers resolve transitively
                nxt = self._replaced.get(s)
                if nxt is None:
                    break
                s = nxt
        return NodeId.parse(s)

    def global_servers(self):
        """Current holder of every shard's key range, deduplicated (a
        drain can merge two ranges onto one server) in shard order."""
        out, seen = [], set()
        for n in self.po.topology.global_servers():
            cur = self.resolve(n)
            if str(cur) not in seen:
                seen.add(str(cur))
                out.append(cur)
        return out


class Replicator:
    """Primary-side state streamer toward the shard's hot standby."""

    def __init__(self, gserver, standby: NodeId):
        self.gs = gserver
        self.standby = standby
        self.every = max(1, int(gserver.config.replicate_every))
        self.kw = KVWorker(
            APP_PS, REPL_CUSTOMER_ID, gserver.po,
            targets=[standby], key_ranges=split_range(1),
            domain=Domain.GLOBAL,
        )
        self.seq = 0          # last shipped snapshot number
        self.acked_seq = 0    # last standby-confirmed snapshot
        self.stopped = False  # fenced by a newer primary, or stop()ed
        self._since = 0
        self._busy = False
        self._pending = False
        self._lag = system_gauge(f"{gserver.po.node}.replication_lag_s")
        # per-SHARD twin of the per-node gauge: shard rank k is this
        # node's rank whether it is the plan primary (global_server:k)
        # or its promoted standby (standby_global:k) — bench's shards
        # sweep and the chaos soaks read the shard-keyed series so a
        # failover doesn't break the metric's continuity
        self._shard_lag = system_gauge(
            f"global_shard{gserver.po.node.rank}.replication_lag_s")
        # baseline ship shortly after startup: a primary that dies before
        # its first completed round must still leave the standby with the
        # key set (and a restarted zombie announces itself to the fence)
        threading.Thread(target=self._baseline, daemon=True,
                         name=f"repl-baseline-{gserver.po.node}").start()

    def _baseline(self):
        time.sleep(0.5)  # let the van/fabric finish starting
        with self.gs._mu:
            if self.seq == 0 and not self._busy:
                self.mark_locked(force=True)

    # ---- primary-side hooks -------------------------------------------------
    def mark_locked(self, n_updates: int = 0, force: bool = False):
        """Record updates; snapshot+ship when the cadence is due.  The
        caller holds the GlobalServer's ``_mu`` — the snapshot copies
        happen here (consistent state), serialization and the wire ship
        on a daemon thread (never under the lock)."""
        if self.stopped:
            return
        self._since += n_updates
        if not force and self._since < self.every:
            return
        self._since = 0
        if self._busy:
            # a ship is in flight with an older snapshot — coalesce: ship
            # once more when it completes rather than queueing every round
            self._pending = True
            return
        self._busy = True
        self._spawn_ship_locked()

    def _spawn_ship_locked(self):
        gs = self.gs
        # the optimizer-stage snapshot hook: a device-resident
        # trajectory (kvstore/jax_backend.py DeviceOptimizer) is
        # exported to the numpy pickle format here, so the standby can
        # restore it on either engine; store.items() likewise
        # materializes device-resident weights (a replication ship IS a
        # snapshot event in the zero-D2H steady-state contract)
        store_snap = {k: v.copy() for k, v in gs.store.items()}
        opt_snap = gs._export_opt_locked()
        meta = {
            "sync_mode": gs.sync_mode,
            "compression": dict(gs.compression),
            "recent_done": gs._recent.export_done(),
            "optimizer_configured": gs._optimizer_configured,
        }
        self.seq += 1
        seq, term = self.seq, gs.term
        t_snap = time.monotonic()

        def ship():
            from geomx_tpu.kvstore import checkpoint as ckpt

            blob = np.frombuffer(
                ckpt.dumps_server_state(store_snap, {"optimizer": opt_snap},
                                        meta), dtype=np.uint8)

            def done():
                errs = []
                with self.kw._mu:
                    if self.kw.errors:
                        errs, self.kw.errors[:] = list(self.kw.errors), []
                if any("fenced" in e for e in errs):
                    # a newer primary holds the shard: stop streaming and
                    # flip the owning server into the fenced state so its
                    # data path refuses pushes too (split-brain guard)
                    self.stopped = True
                    self.gs._fence("replication rejected by newer primary")
                else:
                    self.acked_seq = max(self.acked_seq, seq)
                    lag = time.monotonic() - t_snap
                    self._lag.set(lag)
                    self._shard_lag.set(lag)
                with self.gs._mu:
                    self._busy = False
                    if self._pending and not self.stopped:
                        self._pending = False
                        self._busy = True
                        self._spawn_ship_locked()

            try:
                self.kw.zpush(
                    KVPairs(np.array([0], dtype=np.int64), blob,
                            np.array([len(blob)], dtype=np.int64)),
                    cmd=Cmd.REPLICATE,
                    body={"term": term, "seq": seq},
                    on_complete=done, donated=True)
            except Exception:  # never take the server down over replication
                import logging

                logging.getLogger(__name__).exception(
                    "%s: replication ship failed", gs.po.node)
                with self.gs._mu:
                    self._busy = False

        threading.Thread(target=ship, daemon=True,
                         name=f"repl-ship-{gs.po.node}").start()

    def stop(self):
        self.stopped = True
        self.kw.stop()


class GlobalFailoverMonitor:
    """Failure detector + promotion coordinator on the global scheduler.

    Promotion sequence per shard rank ``k`` (requires heartbeats on —
    ``Config.heartbeat_interval_s > 0``):

    1. primary ``global_server:k`` misses heartbeats past
       ``heartbeat_timeout_s`` → the dead-node table names it;
    2. term[k] += 1; ``Control.PROMOTE {term}`` to ``standby_global:k``
       (retried until acknowledged);
    3. ``Control.NEW_PRIMARY {rank, old, new, term}`` broadcast to every
       local server / worker / master — local servers retarget their WAN
       worker and immediately replay un-ACKed requests;
    4. the broadcast repeats while the old primary stays dead, so a
       zombie that restarts later still learns it was deposed and fences
       itself.
    """

    def __init__(self, postoffice: Postoffice,
                 check_interval_s: Optional[float] = None):
        assert postoffice.node.role is Role.GLOBAL_SCHEDULER
        self.po = postoffice
        topo = postoffice.topology
        self.topology = topo
        self._terms = {r: 0 for r in range(topo.num_global_servers)}
        # current holder of each shard's key range (promotion and
        # key-range reassignment both move it); the shared ShardTargets
        # view on this postoffice serves every other component
        self._holders = {r: NodeId(Role.GLOBAL_SERVER, r)
                         for r in range(topo.num_global_servers)}
        self.shard_targets = ShardTargets(postoffice)
        self._promoted: set = set()
        self.reassignments = 0  # completed live key-range handoffs
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._replies: dict = {}  # token -> body
        self.failover_events = 0
        self._counter = system_counter(f"{postoffice.node}.failover_events")
        self._stop = threading.Event()
        self._interval = (check_interval_s if check_interval_s is not None
                          else max(postoffice.config.heartbeat_interval_s,
                                   0.1))
        postoffice.add_control_hook(self._on_control)
        # timer-wheel entry on a reactor fabric, sleep-loop thread
        # otherwise (transport/reactor.py) — same sweep cadence
        from geomx_tpu.transport.reactor import Periodic

        self._ticker = Periodic(
            self._interval, self._tick,
            name=f"failover-monitor-{postoffice.node}",
            reactor=getattr(postoffice.van.fabric, "reactor", None))

    # ---- detection ----------------------------------------------------------
    def _tick(self):
        if self._stop.is_set():
            return
        try:
            dead = set(self.po.dead_nodes())
        except Exception:
            return
        for rank in range(self.topology.num_standby_globals):
            primary = NodeId(Role.GLOBAL_SERVER, rank)
            if rank in self._promoted:
                if str(primary) in dead:
                    # keep fencing: a zombie restarting at any later
                    # point must hear who owns the shard now
                    self._broadcast_new_primary(
                        rank, old=primary, repeats=1)
                continue
            if str(primary) in dead:
                self.promote(rank)

    # ---- promotion ----------------------------------------------------------
    def promote(self, rank: int, reason: str = "heartbeat timeout") -> bool:
        """Promote ``standby_global:rank``.  Also the operator-forced
        entry point (runbook: docs/deployment.md) — callable directly
        with the primary still alive, e.g. for planned maintenance.
        Per-shard: shard ``rank``'s term moves alone; every other
        shard's primary, standby chain and term are untouched."""
        standby = self.topology.standby_for(rank)
        if standby is None or rank in self._promoted:
            return False
        old = self._holders[rank]
        term = self._terms[rank] + 1
        if not self._rpc_promote(standby, term, rank):
            import logging

            logging.getLogger(__name__).warning(
                "%s: standby %s did not acknowledge promotion (term %d)",
                self.po.node, standby, term)
            return False
        self._record_move(rank, old, standby, term)
        self.failover_events += 1
        self._counter.inc()
        system_counter(f"global_shard{rank}.promotions").inc()
        from geomx_tpu.trace.recorder import get_tracer

        # failover lands on the merged trace timeline as a control event
        get_tracer(str(self.po.node)).instant(
            "failover.promoted", rank=rank, term=term, reason=reason)
        if self.po.flight is not None:
            from geomx_tpu.obs.flight import FlightEv

            self.po.flight.record(FlightEv.PROMOTE, a=term, b=rank,
                                  peer=standby, note="promote")
        print(f"{self.po.node}: promoted {standby} to primary of shard "
              f"{rank} (term={term}, {reason})", flush=True)
        self._broadcast_new_primary(rank, old=old, repeats=3)
        return True

    def shard_table(self) -> dict:
        """Operator/console view of the shard map: rank ->
        {holder, term, promoted} (the cluster-state service merges this
        with heartbeat freshness and per-shard registry counters)."""
        with self._mu:
            return {r: {"holder": str(self._holders[r]),
                        "term": int(self._terms[r]),
                        "promoted": r in self._promoted}
                    for r in self._holders}

    def _record_move(self, rank: int, old: NodeId, new: NodeId, term: int):
        """Shared bookkeeping for a shard's key range changing hands
        (promotion or reassignment): term, holder, shared resolver, and
        the per-shard registry gauges next to the PR 1 per-node ones."""
        self._terms[rank] = term
        self._holders[rank] = new
        self._promoted.add(rank)
        self.shard_targets.record(old, new)
        system_gauge(f"global_shard{rank}.term").set(term)

    # ---- live key-range reassignment (shard drain) --------------------------
    def reassign(self, rank: int, target: Optional[NodeId] = None,
                 reason: str = "operator reassignment") -> bool:
        """Move shard ``rank``'s key range onto ``target`` — the shard's
        standby by default, or ANY live global server (drain: the old
        holder retires and the target serves both ranges).  Epoch-fenced
        by the shard's term exactly like failover, but exercised with
        the old holder still alive:

        1. term[rank] += 1;
        2. ``Control.HANDOFF {term, target}`` to the current holder —
           it quiesces, ships its final state snapshot (store +
           optimizer + replay-dedup window) straight to the target as a
           ``Cmd.REPLICATE {handoff}`` push, then fences itself and
           silently drops any straggling data requests (to the data
           plane it is now "dead", so the failover replay path applies);
        3. ``Control.NEW_PRIMARY`` broadcast — every local server
           retargets the range and replays its un-ACKed requests at the
           target; the replicated dedup window keeps that exactly-once.
        """
        with self._mu:
            old = self._holders.get(rank)
        if old is None:
            return False
        if target is None:
            target = self.topology.standby_for(rank)
        if target is None or str(target) == str(old):
            return False
        term = self._terms[rank] + 1
        reply = self._rpc(old, Control.HANDOFF,
                          {"term": term, "rank": rank,
                           "target": str(target)},
                          attempts=8, per_try_s=5.0)
        if reply is None or not reply.get("ok"):
            import logging

            logging.getLogger(__name__).warning(
                "%s: shard %d handoff %s -> %s failed (%s)",
                self.po.node, rank, old, target, reply)
            return False
        self._record_move(rank, old, target, term)
        self.reassignments += 1
        system_counter(f"global_shard{rank}.reassignments").inc()
        from geomx_tpu.trace.recorder import get_tracer

        get_tracer(str(self.po.node)).instant(
            "reassign.moved", rank=rank, term=term, old=str(old),
            new=str(target), reason=reason)
        if self.po.flight is not None:
            from geomx_tpu.obs.flight import FlightEv

            self.po.flight.record(FlightEv.HANDOFF, a=term, b=rank,
                                  peer=target, note="reassign")
        print(f"{self.po.node}: reassigned shard {rank} key range "
              f"{old} -> {target} (term={term}, "
              f"{reply.get('keys', 0)} keys, {reason})", flush=True)
        self._broadcast_new_primary(rank, old=old, repeats=3)
        return True

    def _rpc(self, target: NodeId, control: Control, body: dict,
             attempts: int = 5, per_try_s: float = 2.0) -> Optional[dict]:
        """Token-matched retried control RPC (the eviction monitors'
        helper, local to this monitor's reply table)."""
        token = f"{self.po.node}#{uuid.uuid4().hex[:8]}"
        body = dict(body, token=token)
        for _ in range(attempts):
            if self._stop.is_set():
                return None
            try:
                self.po.van.send(Message(
                    recipient=target, control=control,
                    domain=Domain.GLOBAL, request=True, body=dict(body)))
            except (KeyError, OSError):
                pass  # peer not dialable yet — retry
            with self._cv:
                if self._cv.wait_for(lambda: token in self._replies,
                                     timeout=per_try_s):
                    return self._replies.pop(token)
        return None

    def _rpc_promote(self, standby: NodeId, term: int, rank: int,
                     attempts: int = 5, per_try_s: float = 2.0) -> bool:
        token = f"{self.po.node}#{uuid.uuid4().hex[:8]}"
        for _ in range(attempts):
            try:
                self.po.van.send(Message(
                    recipient=standby, control=Control.PROMOTE,
                    domain=Domain.GLOBAL, request=True,
                    body={"term": term, "rank": rank, "token": token}))
            except (KeyError, OSError):
                pass  # standby not dialable yet — retry
            with self._cv:
                if self._cv.wait_for(lambda: token in self._replies,
                                     timeout=per_try_s):
                    return bool(self._replies.pop(token).get("ok"))
        return False

    def _on_control(self, msg: Message) -> bool:
        if (msg.control in (Control.PROMOTE, Control.HANDOFF)
                and not msg.request):
            body = msg.body if isinstance(msg.body, dict) else {}
            with self._cv:
                self._replies[body.get("token")] = body
                self._cv.notify_all()
            return True
        return False

    def _broadcast_new_primary(self, rank: int,
                               old: Optional[NodeId] = None,
                               repeats: int = 1):
        topo = self.topology
        primary = NodeId(Role.GLOBAL_SERVER, rank)
        if old is None:
            old = primary
        body = {"rank": rank, "old": str(old),
                "new": str(self._holders[rank]),
                "term": self._terms[rank]}
        targets = list(topo.servers()) + list(topo.all_workers())
        # serve replicas subscribe to every shard's key range: they must
        # retarget their refresh pulls exactly like the local servers'
        # up-links (geomx_tpu/serve)
        targets += list(topo.replicas())
        mw = topo.master_worker()
        if mw is not None:
            targets.append(mw)
        targets.append(old)    # the zombie / drained-holder fence
        if str(old) != str(primary):
            targets.append(primary)  # a plan-primary zombie too
        # the NEW holder too: a reassignment target that is a standby
        # adopts the promotion from this broadcast (the failover path
        # sends it a direct PROMOTE first; the reassign path relies on
        # the new==me branch of _on_new_primary)
        targets.append(self._holders[rank])
        # self-delivery: components on THIS scheduler's postoffice (the
        # adaptive-WAN controller, ShardTargets consumers) track holders
        # through the same control hook as everyone else — without it a
        # locally-originated broadcast is the one they never hear
        targets.append(self.po.node)
        for i in range(repeats):
            if i:
                time.sleep(0.3)
            for n in targets:
                try:
                    self.po.van.send(Message(
                        recipient=n, control=Control.NEW_PRIMARY,
                        domain=Domain.GLOBAL, request=False,
                        body=dict(body)))
                except (KeyError, OSError):
                    pass  # down peers hear a later rebroadcast

    def stop(self):
        self._stop.set()
        self._ticker.stop()
