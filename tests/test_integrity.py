"""End-to-end data-integrity plane (ISSUE 17).

Four layers, each pinned here:

- *wire*: checksum-stamped frames (``GEOMX_INTEGRITY_WIRE``) — flag
  off is bit-for-bit the legacy encoding, stamped frames detect every
  single-bit flip as :class:`WireCorruption`, and the in-proc fabric's
  corruption tap proves detect → NACK → resend keeps training
  byte-identical to an uncorrupted run;
- *gradient hygiene*: the server-side finiteness screen zeroes poisoned
  pushes, answers with a typed error, and QUARANTINES (never evicts)
  a repeat offender;
- *durable state*: checkpoint blobs carry a format stamp + whole-blob
  and per-slab CRCs; restore falls back through N generations; a
  corrupt replication snapshot is rejected without the word "fenced"
  (the Replicator reads fence-flavored replies as deposition);
- *codecs*: every WAN codec (bsc / fp16 / 2bit / mpq) survives a
  seeded fuzz of truncations and bit flips — typed ``CodecError`` or a
  right-shaped tensor, never a crash or a silently wrong shape.

The real-TCP operator tour is ``scripts/run_integrity_demo.sh``; the
cost/coverage numbers come from ``bench.py --child integrity``.
"""

import os
import random
import struct
import time

import numpy as np
import pytest

from geomx_tpu.compression.codecs import (BscCodec, CodecError, Fp16Codec,
                                          MpqSelector, TwoBitCodec,
                                          decompress_payload, pack_rows,
                                          pack_sparse, scatter_sparse,
                                          unpack_rows, unpack_sparse)
from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore import checkpoint as ckpt
from geomx_tpu.transport import message as message_mod
from geomx_tpu.transport.message import Message, WireCorruption


def _msg(elems=256, seed=3):
    rng = np.random.default_rng(seed)
    return Message(
        sender=NodeId(Role.SERVER, 0, 0),
        recipient=NodeId(Role.GLOBAL_SERVER, 0, None),
        request=True, push=True, timestamp=11, msg_sig=77,
        keys=np.array([4], np.int64),
        vals=rng.standard_normal(elems).astype(np.float32),
        lens=np.array([elems], np.int64))


# ---------------------------------------------------------------------------
# wire integrity
# ---------------------------------------------------------------------------

def test_flag_off_is_bit_for_bit_legacy(monkeypatch):
    """The whole plane is opt-in: with the flag off the encoder output
    is byte-identical to the legacy frame — no marker, no CRC block —
    so a mixed-version rollout can upgrade either side first."""
    m = _msg()
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", False)
    off = bytes(m.to_bytes())
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", True)
    on = bytes(m.to_bytes())
    # the stamp is exactly the 8-byte CRC block; the marker byte flips
    # inside the (same-size) header
    assert len(on) - len(off) == 8
    assert off[4 + Message._INTEGRITY_BYTE] == 0
    assert on[4 + Message._INTEGRITY_BYTE] == 1
    # both decode to the same message
    for raw in (off, on):
        back = Message.from_bytes(raw)
        np.testing.assert_array_equal(back.vals, m.vals)
        assert back.msg_sig == m.msg_sig
    # and a stamped frame re-encoded with the flag off is the legacy
    # bytes again (decoder state never leaks into the encoder)
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", False)
    assert bytes(Message.from_bytes(on).to_bytes()) == off


def test_stamped_frame_detects_every_bit_flip(monkeypatch):
    """Random single-bit-flip sweep: every flip in a stamped frame must
    raise a typed error or fail framing — zero silently-wrong
    deliveries.  (The larger randomized sweep runs in
    ``bench.py --child integrity``.)"""
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", True)
    m = _msg(64)
    raw = bytearray(m.to_bytes())
    ref = m.vals.tobytes()
    rng = np.random.default_rng(5)
    silent = 0
    for pos in rng.choice(len(raw) * 8, size=400, replace=False):
        byte, bit = int(pos) // 8, int(pos) % 8
        raw[byte] ^= 1 << bit
        try:
            out = Message.from_bytes(bytes(raw))
            if out.vals is None or out.vals.tobytes() != ref \
                    or out.msg_sig != m.msg_sig:
                silent += 1
        except Exception:
            pass  # detected (WireCorruption or a framing ValueError)
        finally:
            raw[byte] ^= 1 << bit
    assert silent == 0


def test_wire_corruption_carries_sender_identity(monkeypatch):
    """A payload-CRC mismatch still has a VERIFIED meta span, so the
    error names the sender — that identity is what the receiving
    fabric's NACK path needs to trigger the immediate resend."""
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", True)
    m = _msg(64)
    raw = bytearray(m.to_bytes())
    raw[-3] ^= 0x10  # damage payload bytes, far from header + meta
    with pytest.raises(WireCorruption) as ei:
        Message.from_bytes(bytes(raw))
    assert ei.value.sender == str(m.sender)
    assert ei.value.msg_sig == m.msg_sig


def test_legacy_frame_delivers_flip_silently(monkeypatch):
    """The behavior the stamp exists to close, pinned so the soak's
    with/without comparison stays honest: an unstamped frame with a
    payload flip decodes fine and returns WRONG numbers."""
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", False)
    m = _msg(64)
    raw = bytearray(m.to_bytes())
    off = raw.find(m.vals.tobytes())
    assert off > 0
    raw[off + 5] ^= 0x10
    out = Message.from_bytes(bytes(raw))
    assert out.vals.tobytes() != m.vals.tobytes()


def _tiny_cfg(**kw):
    kw.setdefault("topology", Topology(num_parties=2, workers_per_party=1))
    kw.setdefault("enable_flight", False)
    kw.setdefault("lightweight", True)
    kw.setdefault("resend_timeout_ms", 200)
    return Config(**kw)


def _init_model(sim, elems):
    ws = sim.all_workers()
    for w in ws:
        w.init(0, np.zeros(elems, np.float32))
    ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
    return ws


def _push_rounds(ws, rounds, elems):
    g = np.ones(elems, np.float32)
    for _ in range(rounds):
        for w in ws:
            w.push(0, g)
        for w in ws:
            w.wait_all()
    return ws[0].pull_sync(0)


def test_corrupt_link_detect_nack_resend_parity(monkeypatch):
    """The tentpole soak in miniature: a seeded bit-flip tap corrupts a
    WAN uplink; with stamps on, EVERY damaged frame is detected (none
    dropped as framing noise, none silently delivered), the NACK resend
    path re-delivers, and the final model is byte-identical to an
    uncorrupted run's."""
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", True)
    elems, rounds = 2048, 6
    sim = Simulation(_tiny_cfg())
    try:
        clean = _push_rounds(_init_model(sim, elems), rounds, elems)
    finally:
        sim.shutdown()
    sim = Simulation(_tiny_cfg())
    try:
        ws = _init_model(sim, elems)  # bring-up on a healthy fabric
        src = str(sim.local_servers[0].po.node)
        dst = str(sim.global_servers[0].po.node)
        sim.corrupt_link(src, dst, rate=0.3, mode="bitflip", seed=23)
        final = _push_rounds(ws, rounds, elems)
        fab = sim.fabric
        assert fab.corrupt_injected > 0, "tap never fired — dead soak"
        assert fab.corrupt_detected == fab.corrupt_injected
        assert fab.corrupt_delivered == 0
        assert fab.corrupt_dropped == 0
        np.testing.assert_array_equal(final, clean)
    finally:
        sim.shutdown()


def test_unstamped_corrupt_link_is_not_detected(monkeypatch):
    """Control experiment: with stamps OFF the same tap yields zero
    detections — every damaged frame is either silently delivered or
    dropped as framing noise.  The ledger's distinction is what makes
    the soak's detected == injected assertion meaningful."""
    monkeypatch.setattr(message_mod, "WIRE_INTEGRITY", False)
    sim = Simulation(_tiny_cfg())
    try:
        ws = _init_model(sim, 64)
        src = str(sim.local_servers[0].po.node)
        dst = str(sim.global_servers[0].po.node)
        sim.corrupt_link(src, dst, rate=1.0, mode="bitflip", seed=29)
        for w in ws:
            w.push(0, np.ones(64, np.float32))
        fab = sim.fabric
        deadline = time.monotonic() + 10.0
        while fab.corrupt_injected == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        sim.heal_corrupt(src, dst)
        assert fab.corrupt_injected > 0
        assert fab.corrupt_detected == 0  # nothing to detect them with
        assert fab.corrupt_delivered + fab.corrupt_dropped \
            == fab.corrupt_injected
        for w in ws:
            w.wait_all()  # the healed link serves the resends
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# gradient hygiene: poison screen + quarantine
# ---------------------------------------------------------------------------

def test_poison_screen_quarantines_not_evicts():
    """A worker pushing NaN gradients strikes out after
    ``poison_quarantine_n`` rejects and is QUARANTINED — reversibly
    folded out via the PR-16 machinery, never evicted — while the
    healthy worker's training math stays exactly right."""
    cfg = _tiny_cfg(
        topology=Topology(num_parties=1, workers_per_party=2),
        integrity_push_screen=True, poison_quarantine_n=2)
    sim = Simulation(cfg)
    try:
        w_ok, w_bad = _init_model(sim, 128)
        ls = sim.local_servers[0]
        bad = np.full(128, np.nan, np.float32)
        for _strike in (1, 2):
            # both members contribute before either waits: the typed
            # error rides the sync round's ack
            w_bad.push(0, bad)
            w_ok.push(0, np.ones(128, np.float32))
            with pytest.raises(RuntimeError, match="poisoned push"):
                w_bad.wait_all()
            w_ok.wait_all()
        assert ls.integrity_poison_rejects == 2
        assert ls.poison_quarantines == 1
        bad_s = str(w_bad.po.node)
        assert bad_s in ls._quarantined_members
        assert bad_s not in ls._members
        assert bad_s not in ls._evicted, "quarantine escalated to EVICT"
        # the healthy worker trains on alone (quarantine shrank the
        # round quorum) and zero poison ever reached the merge
        w_ok.push(0, np.ones(128, np.float32))
        w_ok.wait_all()
        final = w_ok.pull_sync(0)
        assert np.isfinite(final).all()
        assert final.min() < 0  # sgd actually applied clean gradients
        st = ls.stats()
        assert st["integrity_poison_rejects"] == 2
        assert st["poison_quarantines"] == 1
        assert st["quarantined_workers"] == 1
    finally:
        sim.shutdown()


def test_magnitude_screen_rejects_blowup():
    """poison_mag_max > 0 extends the screen beyond NaN/Inf: a finite
    but exploded gradient is rejected the same way — and with
    ``poison_quarantine_n=0`` the strike never escalates."""
    cfg = _tiny_cfg(
        topology=Topology(num_parties=1, workers_per_party=1),
        integrity_push_screen=True, poison_quarantine_n=0,
        poison_mag_max=1e3)
    sim = Simulation(cfg)
    try:
        (w,) = _init_model(sim, 32)
        w.push(0, np.full(32, 1e6, np.float32))
        with pytest.raises(RuntimeError, match="poisoned push"):
            w.wait_all()
        ls = sim.local_servers[0]
        assert ls.integrity_poison_rejects == 1
        assert ls.poison_quarantines == 0  # n=0 disables the escalation
        assert str(w.po.node) in ls._members
        w.push(0, np.ones(32, np.float32))
        w.wait_all()  # a clean push after the reject still merges
        assert np.isfinite(w.pull_sync(0)).all()
    finally:
        sim.shutdown()


def test_integrity_plane_off_by_default():
    cfg = Config()
    assert cfg.integrity_push_screen is False
    if "GEOMX_INTEGRITY_WIRE" not in os.environ:
        assert message_mod.WIRE_INTEGRITY is False


# ---------------------------------------------------------------------------
# verified durable state
# ---------------------------------------------------------------------------

def _state(seed=0):
    rng = np.random.default_rng(seed)
    store = {0: rng.standard_normal(64).astype(np.float32),
             3: rng.standard_normal(16).astype(np.float32)}
    return store, {"optimizer": {"type": "sgd", "lr": 0.1}}, {"boot": seed}


def test_checkpoint_stamped_roundtrip_and_legacy():
    store, opt, meta = _state()
    for integrity in (False, True):
        blob = ckpt.dumps_server_state(store, opt, meta,
                                       integrity=integrity)
        assert blob.startswith(b"GXCK") is integrity
        s2, o2, m2 = ckpt.loads_server_state(blob)
        assert o2 == opt and m2 == meta
        for k in store:
            np.testing.assert_array_equal(s2[k], store[k])


def test_checkpoint_corruption_detected_and_typed():
    store, opt, meta = _state()
    blob = ckpt.dumps_server_state(store, opt, meta, integrity=True)
    # whole-blob flip
    dam = bytearray(blob)
    dam[len(dam) // 2] ^= 0x40
    with pytest.raises(ckpt.CheckpointCorruption):
        ckpt.loads_server_state(bytes(dam))
    # truncation — mid-blob and mid-header
    with pytest.raises(ckpt.CheckpointCorruption):
        ckpt.loads_server_state(blob[:len(blob) // 2])
    with pytest.raises(ckpt.CheckpointCorruption):
        ckpt.loads_server_state(blob[:7])
    # unknown format version
    ver = bytearray(blob)
    ver[4:6] = struct.pack("<H", 99)
    with pytest.raises(ckpt.CheckpointCorruption, match="version"):
        ckpt.loads_server_state(bytes(ver))


def test_generation_rotation_and_fallback(tmp_path):
    """Three saves under keep=3 retain three generations; rotting the
    newest makes the restore scan fall back to the previous one."""
    path = str(tmp_path / "ck.npz")
    for gen in range(3):
        ckpt.rotate_generations(path, keep=3)
        store, opt, meta = _state(seed=gen)
        ckpt.save_server_state(path, store, opt, meta, integrity=True)
    assert ckpt.restore_candidates(path) == [path, f"{path}.1",
                                             f"{path}.2"]
    # newest verifies → wins
    _, _, m = ckpt.load_server_state(path)
    assert m["boot"] == 2
    # rot the newest: the fallback scan lands on generation 1
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x01
    open(path, "wb").write(bytes(raw))
    got = None
    for cand in ckpt.restore_candidates(path):
        try:
            got = ckpt.load_server_state(cand)
            break
        except ckpt.CheckpointCorruption:
            continue
    assert got is not None and got[2]["boot"] == 1


def test_server_load_checkpoint_falls_back(tmp_path):
    """The live GlobalServer restore path: newest generation rotted on
    disk → the previous one is installed, the reject is counted, and
    serving continues from verified state."""
    sim = Simulation(_tiny_cfg(
        topology=Topology(num_parties=1, workers_per_party=1)))
    try:
        gs = sim.global_servers[0]
        path = str(tmp_path / "gs.npz")
        good_store, opt, meta = _state(seed=7)
        ckpt.save_server_state(path, good_store, opt, meta,
                               integrity=True)
        ckpt.rotate_generations(path, keep=2)
        ckpt.save_server_state(path, *_state(seed=8), integrity=True)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x08
        open(path, "wb").write(bytes(raw))
        gs.load_checkpoint(path)
        assert gs.integrity_ckpt_rejects == 1
        np.testing.assert_array_equal(
            np.asarray(gs.store[0]), good_store[0])
    finally:
        sim.shutdown()


def test_corrupt_replication_snapshot_reply_never_says_fenced():
    """A rotted REPLICATE frame must be rejected WITHOUT fence-flavored
    wording — the primary's Replicator reads 'fenced' replies as a
    deposition signal, and one bad frame must not depose a healthy
    primary."""
    sim = Simulation(_tiny_cfg(
        topology=Topology(num_parties=1, workers_per_party=1)))
    try:
        gs = sim.global_servers[0]
        probe = Message(sender=NodeId(Role.GLOBAL_SERVER, 1, None),
                        recipient=gs.po.node, request=True)
        with gs._mu:
            err = gs._reject_corrupt_snapshot_locked(
                ckpt.CheckpointCorruption("blob CRC mismatch"), probe)
        assert "fenced" not in err["error"]
        assert gs.integrity_ckpt_rejects == 1
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# codec fuzz: typed errors, right shapes, no crashes
# ---------------------------------------------------------------------------

def _fuzz_decode(decode, orig_len):
    """Decode a (possibly damaged) payload: the ONLY acceptable
    outcomes are a typed CodecError or a right-shaped float32 tensor.
    Anything else — struct.error, IndexError, a short array — is the
    bug class this suite exists to catch."""
    try:
        out = decode()
    except CodecError:
        return "typed-reject"
    out = np.asarray(out)
    assert out.shape == (orig_len,), f"wrong shape {out.shape}"
    assert out.dtype == np.float32
    return "decoded"


@pytest.mark.parametrize("codec_name", ["bsc", "fp16", "2bit", "mpq"])
def test_codec_fuzz_roundtrip_truncate_bitflip(codec_name):
    rng = np.random.default_rng(abs(hash(codec_name)) % (2 ** 32))
    n = 4096
    grad = rng.standard_normal(n).astype(np.float32) * 2.0
    codec = {"bsc": lambda: BscCodec(ratio=0.05),
             "fp16": Fp16Codec,
             "2bit": TwoBitCodec,
             "mpq": lambda: MpqSelector(size_bound=n // 2)}[codec_name]()
    if codec_name == "mpq":
        codec = codec.select(n)  # n >= size_bound → the bsc member
    payload = np.asarray(codec.compress(1, grad))
    tag = codec.name

    # 1. clean roundtrip: deterministic decode with the right shape
    out1 = codec.decompress(1, payload, n)
    out2 = codec.decompress(1, payload.copy(), n)
    assert out1.shape == (n,) and out1.dtype == np.float32
    np.testing.assert_array_equal(out1, out2)

    raw = payload.tobytes()
    item = payload.dtype.itemsize

    def decode_bytes(b):
        arr = (np.frombuffer(b, dtype=payload.dtype)
               if len(b) % item == 0
               else np.frombuffer(b, dtype=np.uint8))
        return decompress_payload(tag, 1, arr, n)

    # 2. truncations: every cut point is a typed reject or right-shaped
    rejects = 0
    for cut in rng.choice(max(1, len(raw) - 1), size=64, replace=False):
        rejects += _fuzz_decode(
            lambda: decode_bytes(raw[:int(cut)]), n) == "typed-reject"
    assert rejects > 0, "no truncation was ever rejected"

    # 3. seeded bit flips: never crash, never mis-shape
    for _ in range(128):
        dam = bytearray(raw)
        pos = int(rng.integers(len(dam) * 8))
        dam[pos // 8] ^= 1 << (pos % 8)
        _fuzz_decode(lambda: decode_bytes(bytes(dam)), n)


def test_sparse_index_bounds_are_fenced():
    """A flipped int32 scatter index turns negative or huge; numpy
    fancy indexing would silently WRAP the negative ones into valid
    slots.  The sparse decoders refuse out-of-range ids instead."""
    vals = np.array([1.0, 2.0], np.float32)
    for idx in ([-3, 0], [0, 10 ** 6]):
        payload = pack_sparse(vals, np.array(idx, np.int64))
        with pytest.raises(CodecError, match="index"):
            scatter_sparse(payload, 16, key=5)
    # row-sparse geometry gates
    rows = np.ones((2, 4), np.float32)
    packed = pack_rows(np.array([0, 1], np.int64), rows)
    ids, back = unpack_rows(packed, 4)
    np.testing.assert_array_equal(back, rows)
    np.testing.assert_array_equal(ids, [0, 1])
    with pytest.raises(CodecError):
        unpack_rows(packed[:-1], 4)  # ragged payload
    with pytest.raises(CodecError):
        unpack_rows(packed, 0)  # nonsensical geometry


def test_unpack_sparse_rejects_odd_and_unknown_tag():
    with pytest.raises(CodecError):
        unpack_sparse(np.ones(3, np.float32))
    with pytest.raises(CodecError, match="unknown"):
        decompress_payload("zstd9", 1, np.ones(4, np.float32), 4)


# ---------------------------------------------------------------------------
# chaos plumbing + atomic_write
# ---------------------------------------------------------------------------

def test_netfault_corrupt_phase_validation_and_seed():
    from geomx_tpu.chaos.netfault import NetFaultPhase, _corrupt_seed

    ph = NetFaultPhase(at_s=1.0, duration_s=2.0, kind="corrupt",
                       src="server:0@p0", dst="global_server:0",
                       rate=0.5, corrupt_mode="truncate")
    # the per-link tape seed is stable and link-distinct
    assert _corrupt_seed(7, ph) == _corrupt_seed(7, ph)
    ph2 = NetFaultPhase(at_s=1.0, duration_s=2.0, kind="corrupt",
                        src="server:0@p1", dst="global_server:0")
    assert _corrupt_seed(7, ph) != _corrupt_seed(7, ph2)
    with pytest.raises(ValueError):
        NetFaultPhase(at_s=0, duration_s=1, kind="corrupt",
                      src="a", dst="b", rate=0.0)
    with pytest.raises(ValueError):
        NetFaultPhase(at_s=0, duration_s=1, kind="corrupt",
                      src="a", dst="b", corrupt_mode="scramble")
    with pytest.raises(ValueError):
        NetFaultPhase(at_s=0, duration_s=1, kind="corrupt", dst="b")


def test_corrupt_bytes_deterministic_per_seed():
    from geomx_tpu.transport.van import corrupt_bytes

    blob = bytes(range(256)) * 8
    a = corrupt_bytes(blob, random.Random(13), "bitflip")
    b = corrupt_bytes(blob, random.Random(13), "bitflip")
    assert a == b and a != blob and len(a) == len(blob)
    t = corrupt_bytes(blob, random.Random(13), "truncate")
    assert len(t) < len(blob)


def test_atomic_write_durable_and_no_droppings(tmp_path):
    from geomx_tpu.utils.io import atomic_write

    p = tmp_path / "slab.bin"
    with atomic_write(str(p)) as f:
        f.write(b"x" * 1024)
    assert p.read_bytes() == b"x" * 1024
    leftovers = [q for q in tmp_path.iterdir() if q.name != "slab.bin"]
    assert not leftovers, f"tmp droppings: {leftovers}"


# ---------------------------------------------------------------------------
# health rule
# ---------------------------------------------------------------------------

def test_health_rule_data_corruption_pages_and_recovers():
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=1),
        enable_obs=True, obs_interval_s=0.0,  # manual tick
        obs_window=8, obs_corruption_events=5,
        enable_flight=False, lightweight=True))
    try:
        mc, eng = sim.metrics_collector, sim.health
        node = "server:0@p9"  # synthetic foreign node

        def sample(t, wire, poison, quar, who=node):
            mc.ingest({"node": who, "boot": 1, "t_mono": float(t),
                       "metrics": {},
                       "stats": {"integrity_wire_rejects": wire,
                                 "integrity_poison_rejects": poison,
                                 "poison_quarantines": quar}})

        for i in range(3):
            sample(i, wire=i * 4, poison=i, quar=0)
        recs = eng.tick(now=5.0)
        fired = [r for r in recs if r["rule"] == "data_corruption"
                 and r["subject"] == node]
        assert fired and fired[0]["state"] == "firing"
        assert fired[0]["severity"] == "warn"  # no quarantine involved
        # flat counters → window deltas decay to zero → recovery (the
        # obs_window=8 ring ages the reject burst out)
        for i in range(3, 12):
            sample(i, wire=8, poison=2, quar=0)
        recs = eng.tick(now=20.0)
        rec = [r for r in recs if r["rule"] == "data_corruption"
               and r["subject"] == node]
        assert rec and rec[0]["state"] == "recovered"
        # a burst that includes a quarantine pages at critical severity
        node2 = "server:0@p8"
        for i in range(2):
            sample(i, wire=0, poison=i * 6, quar=i, who=node2)
        recs = eng.tick(now=25.0)
        crit = [r for r in recs if r["rule"] == "data_corruption"
                and r["subject"] == node2]
        assert crit and crit[0]["state"] == "firing"
        assert crit[0]["severity"] == "critical"
    finally:
        sim.shutdown()
