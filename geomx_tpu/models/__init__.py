from geomx_tpu.models.cnn import CNN, create_cnn_state  # noqa: F401
from geomx_tpu.models.resnet import ResNet, create_resnet_state  # noqa: F401
