"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

Absent from the reference (SURVEY.md §2.3 — no PP anywhere); a TPU-design
addition.  A stack of identical blocks is sharded layer-wise over the
``pp`` mesh axis (each device owns ``L / pp`` consecutive blocks).  The
batch splits into M microbatches; activations flow rank→rank+1 via
``lax.ppermute`` each tick, so at steady state all stages compute
concurrently.  The whole schedule is a ``lax.scan`` (M + pp − 1 ticks)
inside ``shard_map`` — fully differentiable, so one jit compiles the
complete pipelined train step.

Bubble fraction is the usual (pp−1)/(M+pp−1); pick M ≥ 4·pp in practice.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    mesh: Mesh,
    block_fn: Callable,
    stacked_params,
    x_mb: jax.Array,
    axis: str = "pp",
    dp_axis: Optional[str] = None,
):
    """Run microbatches through the pipelined block stack.

    - ``block_fn(params_one_block, x) -> x`` applies ONE block.
    - ``stacked_params``: pytree whose leaves have a leading layer dim L,
      sharded ``P(axis)`` (L must divide by the pp axis size).
    - ``x_mb``: [M, mb, ...] microbatches, replicated across ``axis``.
    - ``dp_axis``: optional mesh axis sharding the microbatch dim (index
      1) — pp×dp composition: each dp shard runs its own pipeline over
      its slice of every microbatch; the pp collectives (ppermute
      relays, final psum) stay within a dp coordinate, and the gradient
      AllReduce over dp is inserted by shard_map's transpose as usual.

    Returns [M, mb, ...] outputs, replicated over ``axis`` (sharded over
    ``dp_axis`` if given).
    """
    pp = mesh.shape[axis]

    def stage(params_local, x):
        def apply_local(h):
            h, _ = lax.scan(lambda c, p: (block_fn(p, c), None),
                            h, params_local)
            return h

        my = lax.axis_index(axis)
        M = x.shape[0]
        steps = M + pp - 1
        zero_mb = jnp.zeros_like(x[0])
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            prev_act, out_buf = carry
            # rank 0 feeds microbatch t (garbage past M never lands in a
            # valid output slot); other ranks consume the relayed act
            x_t = lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), axis=0,
                                           keepdims=False)
            inp = jnp.where(my == 0, x_t, prev_act)
            h = apply_local(inp)
            # last rank writes finished microbatch t-(pp-1)
            out_idx = t - (pp - 1)
            write = jnp.logical_and(my == pp - 1, out_idx >= 0)
            safe_idx = jnp.clip(out_idx, 0, M - 1)
            cur = lax.dynamic_index_in_dim(out_buf, safe_idx, 0,
                                           keepdims=False)
            new = jnp.where(write, h, cur)
            out_buf = lax.dynamic_update_index_in_dim(out_buf, new,
                                                      safe_idx, 0)
            # relay my activation to the next stage
            nxt = lax.ppermute(h, axis, fwd_perm)
            return (nxt, out_buf), None

        out0 = jnp.zeros_like(x)
        (_, out), _ = lax.scan(tick, (zero_mb, out0), jnp.arange(steps))
        # only the last rank holds real outputs; psum broadcasts them
        # (all other ranks contribute zeros)
        mask = jnp.where(my == pp - 1, 1.0, 0.0).astype(out.dtype)
        return lax.psum(out * mask, axis)

    x_spec = P(*([None, dp_axis] + [None] * (x_mb.ndim - 2))
               if dp_axis else [None] * x_mb.ndim)
    return shard_map(
        stage, mesh=mesh,
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x_mb)


def mlp_block(params, x):
    """Reference block for tests/dry runs: pre-norm MLP residual block."""
    w1, w2 = params["w1"], params["w2"]
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    h = x * lax.rsqrt(var + 1e-6)
    return x + jax.nn.gelu(h @ w1) @ w2


def init_mlp_stack(rng, n_layers: int, d: int, f: int):
    k1, k2 = jax.random.split(rng)
    scale1 = 1.0 / jnp.sqrt(d)
    scale2 = 1.0 / jnp.sqrt(f)
    return {
        "w1": jax.random.normal(k1, (n_layers, d, f), jnp.float32) * scale1,
        "w2": jax.random.normal(k2, (n_layers, f, d), jnp.float32) * scale2,
    }


def sequential_apply(stacked_params, x_mb, block_fn=mlp_block):
    """Single-device reference: same math, no pipeline."""
    def apply_one(x):
        h, _ = lax.scan(lambda c, p: (block_fn(p, c), None), x, stacked_params)
        return h

    return jax.vmap(apply_one)(x_mb)


# --------------------------------------------------------------------------
# flagship transformer over pp(+dp) — VERDICT r2 item 5
# --------------------------------------------------------------------------

def stack_layers(layers):
    """Stack a list of identical-structure layer pytrees along a new
    leading dim (the pp shard dim).  Homogeneous (non-MoE) layers only."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def init_pp_transformer(cfg, rng):
    """Flagship params in pipeline layout: ``layers`` stacked [L, ...]
    (shard ``P("pp")``), embedding/head UNTIED (same reasoning as
    ``make_staged``: one tensor must not live in two stages when each
    stage's grads are pushed to the kvstore independently)."""
    from geomx_tpu.models.transformer import init_params

    assert cfg.moe_every == 0, "pp flagship pipelines homogeneous layers"
    params = init_params(cfg, rng)
    import numpy as np
    head = jax.random.normal(
        jax.random.fold_in(rng, 7), (cfg.d_model, cfg.vocab),
        jnp.float32) / np.sqrt(cfg.d_model)
    return {
        "embed": params["embed"],
        "pos": params["pos"],
        "layers": stack_layers(params["layers"]),
        "ln_f": params["ln_f"],
        "head": head,
    }


def pp_param_specs(pp_params, axis: str = "pp"):
    """PartitionSpecs mirroring an ``init_pp_transformer`` tree: layer
    stack sharded over pp (leading dim), everything else replicated."""
    return {
        "embed": P(None, None),
        "pos": P(None, None),
        "layers": jax.tree_util.tree_map(
            lambda leaf: P(*([axis] + [None] * (leaf.ndim - 1))),
            pp_params["layers"]),
        "ln_f": P(None),
        "head": P(None, None),
    }


def make_pp_apply(cfg, mesh: Mesh, n_microbatches: int,
                  axis: str = "pp", dp_axis: Optional[str] = None):
    """Pipelined flagship forward: embed (replicated over pp) → GPipe
    schedule over the stacked transformer layers → ln_f + untied head.
    One jit compiles the whole thing; grads flow through the schedule
    (the scan is differentiable), so ``value_and_grad`` of the returned
    apply is the full pipelined train step."""
    from geomx_tpu.models.transformer import (
        _layer_forward, _rms_norm, _single_device_attention)

    # same guard as init_pp_transformer: block() routes every layer
    # through _layer_forward(idx=0), which silently applies dense FFN
    # (and drops the aux loss) for a MoE config
    assert cfg.moe_every == 0, "pp flagship pipelines homogeneous layers"

    def block(layer, x):
        return _layer_forward(
            cfg, 0, layer, x,
            lambda q, k, v: _single_device_attention(cfg, q, k, v))[0]

    def apply(pp_params, tokens):
        B, T = tokens.shape
        M = n_microbatches
        assert B % M == 0, (B, M)
        cd = cfg.compute_dtype
        x = pp_params["embed"][tokens].astype(cd)
        x = x + pp_params["pos"][:T][None].astype(cd)
        x_mb = x.reshape(M, B // M, T, cfg.d_model)
        out = pipeline_apply(mesh, block, pp_params["layers"], x_mb,
                             axis=axis, dp_axis=dp_axis)
        x = out.reshape(B, T, cfg.d_model)
        x = _rms_norm(x, pp_params["ln_f"])
        logits = jnp.einsum("btd,dv->btv", x, pp_params["head"].astype(cd))
        return logits.astype(jnp.float32)

    return apply
