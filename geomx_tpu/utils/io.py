"""Shared filesystem helpers."""

from __future__ import annotations

import contextlib
import os
import tempfile


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb"):
    """Write-then-rename: the file at ``path`` is either the previous
    version or the complete new one, never a torn write.  Creates parent
    directories.  Used by every on-disk artifact (checkpoints, param
    saves, record datasets).

    Durability: the temp file is fsync'd BEFORE the rename and the
    parent directory AFTER — rename alone only orders the metadata, so
    a power loss shortly after ``os.replace`` could surface the new
    name pointing at unwritten blocks (or no entry at all)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
