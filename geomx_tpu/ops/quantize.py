"""On-TPU codec kernels (pallas).

The C++ codecs (geomx_tpu/native) run on the server hosts; these pallas
kernels are the *worker-side* equivalents so gradients can be compressed
on-chip before the device→host handoff at the slice edge — the payload
crossing PCIe/DCN is then already 16x smaller (cf. the EQuARX idea of
quantizing inside the collective; PAPERS.md).

Layout note: the on-chip packer uses a **strided** 2-bit layout
(byte ``i`` holds codes for elements ``i, i+n/4, i+2n/4, i+3n/4``) —
packing along the lane dimension would need cross-lane shuffles, packing
across rows is a pure elementwise shift-or.  ``dequantize_2bit_tpu``
mirrors it; the host codecs keep their own (consecutive) layout, so the
two formats are distinguished by the ``compr`` tags "2bit" (host) and
"2bit-tpu" (this kernel).

All kernels operate on flat float32 arrays padded to a multiple of
4*1024; shapes inside the kernel are (rows, 1024) blocks aligned to the
(8, 128) float32 tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANES = 1024  # 8 sublanes x 128 lanes worth of elements per row


def _pad_to(x: jax.Array, mult: int) -> jax.Array:
    pad = (-x.shape[0]) % mult
    if pad:
        x = jnp.pad(x, (0, pad))
    return x


def _quant_kernel(g_ref, r_ref, thr_ref, packed_ref, newr_ref):
    thr = thr_ref[0, 0]
    r = r_ref[:] + g_ref[:]
    pos = r > thr
    neg = r < -thr
    # avoid small-int→float casts (unsupported on TPU pallas): pure selects
    q = jnp.where(pos, 1, jnp.where(neg, 2, 0))  # int32: 0 / 1 / 2
    newr_ref[:] = r - jnp.where(pos, thr, 0.0) + jnp.where(neg, thr, 0.0)
    # strided pack: rows are the quarter-strides
    quarter = q.shape[0] // 4
    packed = (q[0 * quarter:1 * quarter]
              | (q[1 * quarter:2 * quarter] << 2)
              | (q[2 * quarter:3 * quarter] << 4)
              | (q[3 * quarter:4 * quarter] << 6))
    packed_ref[:] = packed.astype(jnp.uint8)


# rows per grid step: 128 input rows → 32 packed uint8 rows (the uint8
# min sublane tile is 32); keeps each step's VMEM footprint ~2.5 MB
_QROWS = 128


@functools.partial(jax.jit, static_argnames=("interpret",))
def _quantize_padded(g2d, r2d, thr, interpret=False):
    from jax.experimental import pallas as pl

    rows = g2d.shape[0]
    grid = (rows // _QROWS,)
    return pl.pallas_call(
        _quant_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_QROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((_QROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((_QROWS // 4, LANES), lambda i: (i, 0)),
            pl.BlockSpec((_QROWS, LANES), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((rows // 4, LANES), jnp.uint8),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ),
        interpret=interpret,
    )(g2d, r2d, thr)


def quantize_2bit_tpu(grad: jax.Array, residual: jax.Array,
                      threshold: float = 0.5, interpret: bool = False):
    """Residual-feedback 2-bit quantization on-chip.

    Returns (packed uint8 [ceil(n/4*LANES)*LANES...], new_residual [n]).
    ``interpret=True`` runs the kernel in pallas interpret mode (CPU tests).
    """
    n = grad.shape[0]
    g = _pad_to(grad.astype(jnp.float32), _QROWS * LANES)
    r = _pad_to(residual.astype(jnp.float32), _QROWS * LANES)
    rows = g.shape[0] // LANES
    thr = jnp.full((1, 1), threshold, jnp.float32)
    packed, newr = _quantize_padded(
        g.reshape(rows, LANES), r.reshape(rows, LANES), thr,
        interpret=interpret)
    return packed.reshape(-1), newr.reshape(-1)[:n]


def _dequant_kernel(packed_ref, thr_ref, out_ref):
    thr = thr_ref[0, 0]
    b = packed_ref[:].astype(jnp.int32)
    quarter = out_ref.shape[0] // 4

    def decode(q):
        return jnp.where(q == 1, thr, jnp.where(q == 2, -thr, 0.0))

    out_ref[0 * quarter:1 * quarter] = decode(b & 3)
    out_ref[1 * quarter:2 * quarter] = decode((b >> 2) & 3)
    out_ref[2 * quarter:3 * quarter] = decode((b >> 4) & 3)
    out_ref[3 * quarter:4 * quarter] = decode((b >> 6) & 3)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dequantize_padded(p2d, thr, interpret=False):
    from jax.experimental import pallas as pl

    rows = p2d.shape[0] * 4
    grid = (p2d.shape[0] // (_QROWS // 4),)
    return pl.pallas_call(
        _dequant_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_QROWS // 4, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_QROWS, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        interpret=interpret,
    )(p2d, thr)


def dequantize_2bit_tpu(packed: jax.Array, n: int, threshold: float = 0.5,
                        interpret: bool = False) -> jax.Array:
    prows = packed.shape[0] // LANES
    thr = jnp.full((1, 1), threshold, jnp.float32)
    out = _dequantize_padded(packed.reshape(prows, LANES), thr,
                             interpret=interpret)
    return out.reshape(-1)[:n]


def _dgc_kernel(v_ref, u_ref, g_ref, m_ref, vout_ref, uout_ref):
    m = m_ref[0, 0]
    v = m * v_ref[:] + g_ref[:]
    vout_ref[:] = v
    uout_ref[:] = u_ref[:] + v


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dgc_padded(v2d, u2d, g2d, m, interpret=False):
    from jax.experimental import pallas as pl

    rows = v2d.shape[0]
    grid = (rows // _QROWS,)
    spec = pl.BlockSpec((_QROWS, LANES), lambda i: (i, 0))
    return pl.pallas_call(
        _dgc_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=(spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ),
        interpret=interpret,
    )(v2d, u2d, g2d, m)


def dgc_update_tpu(velocity: jax.Array, accum: jax.Array, grad: jax.Array,
                   momentum: float = 0.9, interpret: bool = False):
    """Fused DGC momentum-correction update (v = m·v + g; u += v) on-chip
    (the BSC inner loop, ref: gradient_compression.cc:191-269)."""
    n = grad.shape[0]
    v = _pad_to(velocity.astype(jnp.float32), _QROWS * LANES)
    u = _pad_to(accum.astype(jnp.float32), _QROWS * LANES)
    g = _pad_to(grad.astype(jnp.float32), _QROWS * LANES)
    rows = v.shape[0] // LANES
    m = jnp.full((1, 1), momentum, jnp.float32)
    vo, uo = _dgc_padded(v.reshape(rows, LANES), u.reshape(rows, LANES),
                         g.reshape(rows, LANES), m, interpret=interpret)
    return vo.reshape(-1)[:n], uo.reshape(-1)[:n]
