#!/usr/bin/env bash
# Acceptance config: bisparse_compression (mirrors the reference scripts/cpu/run_bisparse_compression.sh)
exec "$(dirname "$0")/run_cluster.sh" --compression bsc
