"""HiPS kvstore integration tests over the in-proc simulation.

Models the reference acceptance style: correctness = workers converge on
identical, correctly-updated weights through the two-tier hierarchy
(ref: examples/cnn.py accuracy-curve-as-oracle, SURVEY.md §4)."""

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.transport.van import FaultPolicy


def make_sim(parties=2, workers=2, gservers=1, **cfg_kw):
    cfg = Config(
        topology=Topology(num_parties=parties, workers_per_party=workers,
                          num_global_servers=gservers),
        **cfg_kw,
    )
    return Simulation(cfg)


def run_steps(sim, tensors, steps, lr=0.1):
    """Each worker pushes grad = ones; with plain SGD every param element
    should decrease by lr * steps (grads averaged across all workers)."""
    workers = sim.all_workers()
    for w in workers:
        for tid, shape in tensors.items():
            w.init(tid, np.zeros(shape, np.float32))
    workers[0].set_optimizer({"type": "sgd", "lr": lr})
    pulled = {}
    for step in range(steps):
        for w in workers:
            for tid, shape in tensors.items():
                w.push(tid, np.ones(shape, np.float32), priority=-tid)
        for w in workers:
            for tid in tensors:
                w.pull(tid, lambda t, arr, w=w: pulled.__setitem__((id(w), t), arr))
        for w in workers:
            w.wait_all()
    return pulled


def test_fsa_two_tier_sgd():
    """FSA: 2 parties × 2 workers; global SGD applies the averaged grad."""
    sim = make_sim(parties=2, workers=2)
    try:
        tensors = {0: (4, 3), 1: (8,)}
        steps = 3
        pulled = run_steps(sim, tensors, steps, lr=0.1)
        for (wid, tid), arr in pulled.items():
            # each step: party avg = 1; global avg over 2 parties... each
            # local server pushes sum/num_workers? No: local pushes the SUM
            # of its workers' grads; global divides by num_global_workers.
            # sum=2 per party, global grad = (2+2)/2 = 2?? See note in test.
            pass
        # compute expected from the implemented semantics:
        # local merged = sum over party workers = 2 * ones
        # global grad = sum over parties / num_parties = 2 * ones
        # w -= lr * grad each step
        expected = -0.1 * 2 * steps
        for (wid, tid), arr in pulled.items():
            np.testing.assert_allclose(arr, expected, rtol=1e-5)
    finally:
        sim.shutdown()


def test_fsa_gradient_averaging_normalized():
    """Workers pre-divide by num_all_workers (the reference examples push
    grad/num_workers, ref examples/cnn_hfa.py) → effective mean grad."""
    sim = make_sim(parties=2, workers=2)
    try:
        tensors = {0: (6,)}
        workers = sim.all_workers()
        for w in workers:
            w.init(0, np.zeros(6, np.float32))
        workers[0].set_optimizer({"type": "sgd", "lr": 1.0})
        n = workers[0].num_all_workers
        for w in workers:
            w.push(0, np.full(6, 4.0 / n, np.float32))
        got = {}
        for w in workers:
            got[id(w)] = w.pull_sync(0)
        # mean grad = 4/4 * sum(4 workers)/2(parties)... implemented
        # semantics: local sum = 2*(4/4)=2, global avg over 2 parties = 2
        for arr in got.values():
            np.testing.assert_allclose(arr, -2.0, rtol=1e-5)
    finally:
        sim.shutdown()


def test_multigps_sharding():
    """Big tensors shard across 2 global servers; both hold disjoint state."""
    sim = make_sim(parties=1, workers=2, gservers=2, bigarray_bound=8)
    try:
        tensors = {0: (32,), 1: (3,)}  # 0 is "big" → split across both
        pulled = run_steps(sim, tensors, steps=2, lr=0.1)
        for (wid, tid), arr in pulled.items():
            np.testing.assert_allclose(arr, -0.1 * 2 * 2, rtol=1e-5)
        # both global servers actually own keys
        assert all(len(gs.store) > 0 for gs in sim.global_servers)
        big_keys_0 = set(sim.global_servers[0].store)
        big_keys_1 = set(sim.global_servers[1].store)
        assert big_keys_0.isdisjoint(big_keys_1)
    finally:
        sim.shutdown()


def test_mixed_sync_async_global():
    """MixedSync: async global tier still converges on this determinstic
    workload (updates applied per-party-push instead of per-round)."""
    sim = make_sim(parties=2, workers=1, sync_global_mode=False)
    try:
        workers = sim.all_workers()
        for w in workers:
            w.init(0, np.zeros(4, np.float32))
        workers[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in workers:
            w.push(0, np.ones(4, np.float32))
        for w in workers:
            w.wait_all()
        # async tier: a party's replica refreshes only on its own push-up
        # rounds, so after a single push it may legitimately hold a stale
        # intermediate (-0.1).  Real async workers keep stepping — push
        # zero-gradients (no-op updates) to refresh until both original
        # updates are visible.
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            for w in workers:
                w.push(0, np.zeros(4, np.float32))
            for w in workers:
                w.wait_all()
            arrs = [w.pull_sync(0) for w in workers]
            if all(np.allclose(a, -0.2, rtol=1e-5) for a in arrs):
                break
            time.sleep(0.02)
        for arr in arrs:
            np.testing.assert_allclose(arr, -0.2, rtol=1e-5)
    finally:
        sim.shutdown()


def test_dcasgd_on_async_tier():
    sim = make_sim(parties=2, workers=1, sync_global_mode=False)
    try:
        workers = sim.all_workers()
        for w in workers:
            w.init(0, np.zeros(4, np.float32))
        workers[0].set_optimizer({"type": "dcasgd", "lr": 0.1, "lamda": 0.04})
        for step in range(3):
            for w in workers:
                w.push(0, np.ones(4, np.float32))
            for w in workers:
                w.wait_all()
        arrs = [w.pull_sync(0) for w in workers]
        for arr in arrs:
            assert np.all(arr < 0)  # moved downhill
    finally:
        sim.shutdown()


def test_wan_byte_accounting_and_stats():
    sim = make_sim(parties=2, workers=1)
    try:
        w = sim.all_workers()[0]
        for wk in sim.all_workers():
            wk.init(0, np.zeros(1000, np.float32))
        for wk in sim.all_workers():
            wk.push(0, np.ones(1000, np.float32))
            wk.wait_all()
        _ = [wk.pull_sync(0) for wk in sim.all_workers()]
        stats = sim.wan_bytes()
        # 2 local servers each pushed 1000 floats up and pulled 1000 back
        assert stats["wan_send_bytes"] > 2 * 4000
        per_server = w.server_stats()
        assert per_server["wan_send_bytes"] > 0
    finally:
        sim.shutdown()


def test_row_sparse_push_pull():
    """Embedding path: only active rows cross the wire; inactive rows
    never change (ref: row-sparse kvstore_dist.h:628-702)."""
    sim = make_sim(parties=2, workers=1)
    try:
        ws = sim.all_workers()
        R, C = 50, 8
        init = np.zeros((R, C), np.float32)
        for w in ws:
            w.init(0, init)
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        # party 0 touches rows {3, 7}, party 1 rows {7, 20}
        ws[0].push_row_sparse(0, [3, 7], np.ones((2, C), np.float32))
        ws[1].push_row_sparse(0, [7, 20], np.ones((2, C), np.float32))
        got = {}
        for i, w in enumerate(ws):
            w.pull_row_sparse(0, [3, 7, 20, 40],
                              lambda t, rows, i=i: got.__setitem__(i, rows))
        for w in ws:
            w.wait_all()
        for i in range(2):
            rows = got[i]
            # global grad = sum over parties / num_parties; lr 1.0
            np.testing.assert_allclose(rows[0], -0.5)   # row 3: one party
            np.testing.assert_allclose(rows[1], -1.0)   # row 7: both
            np.testing.assert_allclose(rows[2], -0.5)   # row 20: one party
            np.testing.assert_allclose(rows[3], 0.0)    # row 40: untouched
        # the wire carried sparse rows, not the full table
        # (2 rows * 8 cols * 4B + ids ≈ 72B vs 1600B dense)
    finally:
        sim.shutdown()


def test_pull_right_after_init_is_served():
    """A pull issued before any push must answer with the init value
    (regression: parked pulls were only drained by push rounds)."""
    sim = make_sim(parties=1, workers=2)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.full(8, 7.0, np.float32))
        got = ws[1].pull_sync(0)
        np.testing.assert_allclose(got, 7.0)
    finally:
        sim.shutdown()


def test_async_local_mode_no_deadlock():
    """sync_mode=False forwards pushes immediately; pulls never park."""
    sim = make_sim(parties=1, workers=2, sync_mode=False,
                   sync_global_mode=False)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in ws:
            w.push(0, np.ones(4, np.float32))
        for w in ws:
            w.wait_all()
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if np.allclose(ws[0].pull_sync(0), -0.2, rtol=1e-5):
                break
            time.sleep(0.05)
        np.testing.assert_allclose(ws[0].pull_sync(0), -0.2, rtol=1e-5)
    finally:
        sim.shutdown()


def test_unknown_compression_rejected():
    sim = make_sim(parties=1, workers=1)
    try:
        w = sim.all_workers()[0]
        with pytest.raises(ValueError):
            w.set_gradient_compression({"type": "definitely-not-a-codec"})
    finally:
        sim.shutdown()


def test_hfa_with_bsc_pull_stays_dense_and_synced():
    """HFA K2 pulls must come back dense even under bsc compression —
    a sparse delta against the adopted party-mean would desync replicas."""
    sim = make_sim(parties=2, workers=1, use_hfa=True, hfa_k2=1)
    try:
        ws = sim.all_workers()
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression({"type": "bsc", "ratio": 0.01})
        for w in ws:
            w.init(0, np.zeros(1000, np.float32))
        # HFA pushes are party-mean WEIGHTS; party p pushes p+1
        for p, w in enumerate(ws):
            w.push(0, np.full(1000, float(p + 1), np.float32))
        outs = [w.pull_sync(0) for w in ws]
        # global: 0 + ((1-0)+(2-0))/2 = 1.5, everywhere, exactly
        for out in outs:
            np.testing.assert_allclose(out, 1.5, rtol=1e-6)
        np.testing.assert_allclose(sim.local_servers[0].store[list(sim.local_servers[0].store)[0]], 1.5)
    finally:
        sim.shutdown()


def test_hfa_gating_reduces_wan_traffic():
    """HFA with k2=2: only every 2nd local round crosses the WAN
    (ref: kvstore_dist_server.h:1324-1343 K2 gate)."""
    sim_plain = make_sim(parties=1, workers=2)
    sim_hfa = make_sim(parties=1, workers=2, use_hfa=True, hfa_k2=2)
    try:
        for sim in (sim_plain, sim_hfa):
            ws = sim.all_workers()
            for w in ws:
                w.init(0, np.zeros(256, np.float32))
            for step in range(4):
                for w in ws:
                    w.push(0, np.ones(256, np.float32))
                for w in ws:
                    w.wait_all()
                for w in ws:
                    w.pull_sync(0)
        plain = sim_plain.wan_bytes()["wan_send_bytes"]
        hfa = sim_hfa.wan_bytes()["wan_send_bytes"]
        assert hfa < plain * 0.75, (plain, hfa)
    finally:
        sim_plain.shutdown()
        sim_hfa.shutdown()


def test_multikey_pull_across_separate_inits():
    """A multi-key pull parked before INIT must be served once the LAST
    key arrives, even when the keys are INITed in separate messages
    (advisor r1: the message used to stay orphaned under the first
    missing key's parked list and hang forever)."""
    import numpy as np

    from geomx_tpu.ps.kv_app import KVPairs
    from geomx_tpu.transport.message import Message

    sim = make_sim(parties=1, workers=1)
    try:
        gs = sim.global_servers[0]
        served = []
        gs._respond_pull = lambda req: served.append(req)  # capture, no wire

        keys = np.array([5, 9], dtype=np.int64)
        msg = Message(keys=keys, pull=True, request=True)
        gs._pull(msg, KVPairs(keys, np.zeros(0, np.float32),
                              np.array([0, 0], dtype=np.int64)))
        assert served == []
        with gs._mu:
            gs.store[5] = np.zeros(4, np.float32)
            # the sharded server returns still-blocked pulls; callers
            # re-park them under a key that is missing NOW (the same
            # no-orphaning invariant, split so the re-park can take the
            # blocking key's stripe outside this one)
            for m in gs._serve_parked_pulls_locked(5):
                gs._park_pull(m)
        assert served == []  # key 9 still missing; must now be parked on 9
        with gs._mu:
            assert any(m is msg for m in gs._keys[9].parked_pulls)
            gs.store[9] = np.zeros(4, np.float32)
            for m in gs._serve_parked_pulls_locked(9):
                gs._park_pull(m)
        assert served == [msg]
    finally:
        sim.shutdown()


def test_replay_dedup_keyed_on_incarnation():
    """A replacement node whose Customer timestamps restart at 0 must not
    have fresh requests misclassified as replays of its predecessor's
    (advisor r1: dedup key had no boot/incarnation nonce)."""
    from geomx_tpu.kvstore.common import RecentRequests
    from geomx_tpu.transport.message import Message

    rr = RecentRequests()
    old = Message(sender=None, app_id=0, customer_id=0, timestamp=0, boot=111)
    new = Message(sender=None, app_id=0, customer_id=0, timestamp=0, boot=222)
    assert rr.check(old) == "new"
    rr.mark_done(old)
    assert rr.check(new) == "new"       # NOT "done": different incarnation
    assert rr.check(old) == "done"      # the true replay still dedups


def test_boot_nonce_survives_wire_roundtrip():
    from geomx_tpu.transport.message import Message

    m = Message(app_id=1, customer_id=2, timestamp=3, boot=0xABCDEF)
    m2 = Message.from_bytes(m.to_bytes())
    assert m2.boot == 0xABCDEF


def test_master_worker_drives_configuration():
    """Central-worker deployment (ref: DMLC_ENABLE_CENTRAL_WORKER,
    postoffice.cc:32-33): the MASTER configures the optimizer and WAN
    compression; plain workers only train.  FSA invariant holds."""
    from geomx_tpu.core.config import Role

    cfg = Config(topology=Topology(num_parties=2, workers_per_party=1,
                                   central_worker=True))
    assert any(n.role is Role.MASTER_WORKER
               for n in cfg.topology.all_nodes())
    sim = Simulation(cfg)
    try:
        assert sim.master is not None
        sim.master.set_optimizer({"type": "sgd", "lr": 0.1})
        sim.master.set_gradient_compression({"type": "fp16"})
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        for _ in range(2):
            for w in ws:
                w.push(0, np.ones(64, np.float32))
            for w in ws:
                w.wait_all()
        outs = [w.pull_sync(0) for w in ws]
        # sgd lr=0.1, grad mean = 1 per round, 2 rounds -> -0.2
        for out in outs:
            np.testing.assert_allclose(out, -0.2, rtol=1e-3)
        stats = sim.master.query_stats()
        assert stats.get("optimizer_configured")
    finally:
        sim.shutdown()


def test_global_same_sender_round_fence():
    """BSP same-sender fence on the global sync merge: a party's
    round-N+1 push arriving while round N is still open (WAN pushes
    pipeline; a slow peer encode widens the window) must DEFER to the
    next round — merging it would close round N from one party's two
    pushes and serve that party a close its peers never reached."""
    from geomx_tpu.kvstore.common import Cmd
    from geomx_tpu.ps.kv_app import KVPairs
    from geomx_tpu.transport.message import Message

    sim = make_sim(parties=2, workers=1)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(8, np.float32))
        for w in ws:
            w.wait_all()
        gs = sim.global_servers[0]
        gs.server.response = lambda *a, **k: None  # merge only, no wire
        key = int(next(iter(gs.store)))

        def push(sender, ts):
            m = Message(sender=sender, recipient=gs.po.node, push=True,
                        request=True, timestamp=ts, cmd=Cmd.DEFAULT,
                        keys=np.array([key], np.int64),
                        vals=np.ones(8, np.float32),
                        lens=np.array([8], np.int64))
            gs._push_sync(m, KVPairs(m.keys, m.vals, m.lens))

        base_rounds = gs.key_rounds
        push("server:0@p0", 101)
        push("server:0@p0", 102)  # same sender, round still open
        assert gs._shards.drain(10)
        st = gs._keys[key]
        assert st.count == 1, "second same-sender push merged into " \
                              "the open round"
        assert len(st.deferred) == 1
        assert gs.key_rounds == base_rounds  # round 1 still open
        push("server:0@p1", 101)  # peer's push closes round 1
        assert gs._shards.drain(10)
        # the deferred push replayed into round 2: open, count 1
        assert gs.key_rounds == base_rounds + 1
        assert st.count == 1 and not st.deferred
        assert "server:0@p0" in st.contributors
        push("server:0@p1", 102)  # closes round 2
        assert gs._shards.drain(10)
        assert gs.key_rounds == base_rounds + 2
        assert st.count == 0 and not st.contributors
        # weight-version stamp: one bump per close, coherent snapshot
        _, wv = gs._weight_wv(key)
        assert wv == (gs.term << 48) + st.ver and st.ver >= 2
    finally:
        sim.shutdown()


def test_pull_down_drops_stale_weight_version():
    """Receiver half of the ordering guard: pull-down responses are
    flushed with no stripes held and CAN reorder in flight; a response
    stamped strictly older than the last applied weight version must
    be dropped (applying it would roll the replica back a round)."""
    from geomx_tpu.ps.kv_app import KVPairs

    sim = make_sim(parties=1, workers=1)
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.push(0, np.ones(8, np.float32))
        w.wait_all()
        w.pull_sync(0)
        ls = sim.local_servers[0]
        key = int(next(iter(ls.store)))
        fresh = np.full(8, -2.0, np.float32)
        stale = np.full(8, -1.0, np.float32)
        skips = ls.stale_pull_skips
        ls._on_pull_down(KVPairs(np.array([key], np.int64), fresh,
                                 np.array([8], np.int64), wv={key: 7}))
        np.testing.assert_array_equal(ls.store[key], fresh)
        # the late round-N response (older stamp) must NOT roll back
        ls._on_pull_down(KVPairs(np.array([key], np.int64), stale,
                                 np.array([8], np.int64), wv={key: 6}))
        np.testing.assert_array_equal(ls.store[key], fresh)
        assert ls.stale_pull_skips == skips + 1
        # an equal stamp is the same weights — re-applying is fine
        ls._on_pull_down(KVPairs(np.array([key], np.int64), fresh.copy(),
                                 np.array([8], np.int64), wv={key: 7}))
        np.testing.assert_array_equal(ls.store[key], fresh)
    finally:
        sim.shutdown()


def test_merged_round_parks_member_pulls_until_complete():
    """advisor r5: during a PARTIAL TS-merged round (some push carried
    num_merge>1, so count > distinct senders) an established member's
    pull must PARK until the round completes — its own contribution is
    already inside the open accumulator, and serving it the previous
    round's weights would silently diverge party replicas.  A
    bootstrapping joiner (no push history) is still served stale — the
    deadlock-free answer (advisor r4) — since the round genuinely
    waits on its first push."""
    import threading
    import time

    sim = make_sim(parties=1, workers=3)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(8, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        # round 1: plain pushes — establishes every worker's push history
        for w in ws:
            w.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(ws[0].pull_sync(0), -3.0)
        for w in ws:
            w.wait_all()
        # round 2, degraded merge shape: w0 pushes a partial pre-merge
        # carrying its own + w1's contributions (num_merge=2)
        ws[0].push(0, 2 * np.ones(8, np.float32), num_merge=2)
        # pushes are async: the merged contribution must be IN the open
        # accumulator before w1's pull arrives, or the server rightly
        # serves the pull from the (count==0) completed round
        srv = sim.local_servers[0]

        def merged_landed():
            with srv._mu:
                return any(st.count >= 2 for st in srv._keys.values())

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not merged_landed():
            time.sleep(0.01)
        assert merged_landed()
        got = {}
        done = threading.Event()

        def on_pull(t, v):
            got["w1"] = np.array(v)
            done.set()

        ws[1].pull(0, on_pull)
        time.sleep(0.4)
        assert not done.is_set(), (
            "member pull served STALE mid-merged-round (replica "
            f"divergence): got {got.get('w1')}")
        # a fresh joiner's bootstrap pull mid-merge is served stale (the
        # last completed round) — parking it would deadlock its own join
        wj = sim.add_worker(0)
        wj.init(0, np.zeros(8, np.float32))
        np.testing.assert_allclose(wj.pull_sync(0), -3.0)
        # w2 + the joiner complete the round (target rose to 4 on join)
        ws[2].push(0, np.ones(8, np.float32))
        wj.push(0, np.ones(8, np.float32))
        assert done.wait(timeout=30), "parked pull never served"
        # accum = 2 (merged) + 1 + 1 = 4 → weights -3 - 4 = -7
        np.testing.assert_allclose(got["w1"], -7.0)
        for w in ws + [wj]:
            w.wait_all()
    finally:
        sim.shutdown()


def test_partial_merge_parks_member_with_no_push_history():
    """ADVICE r5 (round 5): under the TS push overlay, non-elected
    workers NEVER push directly, so a push-history test would serve
    their pulls from the previous round for every partial-merge window
    — replicas silently diverging one round apart.  A known party
    member with NO push history must PARK during a TS-merged partial
    round (its contribution rode the merge tree; the round completes
    without its direct push by construction), while an out-of-plan
    joiner's BOOTSTRAP pull (nothing pushed yet) is still served from
    the last completed round — the advisor-r4 deadlock-free answer."""
    import threading
    import time

    sim = make_sim(parties=1, workers=3)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(8, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        srv = sim.local_servers[0]
        # degraded/partial TS-merged push straight away: w0 relays its
        # own + w1's contributions (num_merge=2); w2 has NEVER pushed
        ws[0].push(0, 2 * np.ones(8, np.float32), num_merge=2)

        def merged_landed():
            with srv._mu:
                return any(st.count >= 2 for st in srv._keys.values())

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not merged_landed():
            time.sleep(0.01)
        assert merged_landed()
        got = {}
        done = threading.Event()

        def on_pull(t, v):
            got["w2"] = np.array(v)
            done.set()

        # w2: plan member, zero push history on this key (the TS
        # non-elected shape) — must park, NOT read round-0 weights
        ws[2].pull(0, on_pull)
        time.sleep(0.4)
        assert not done.is_set(), (
            "never-pushed member pull served STALE mid-merged-round "
            f"(replica divergence): got {got.get('w2')}")
        # an out-of-plan joiner mid-merge still bootstraps serve-stale
        wj = sim.add_worker(0)
        wj.init(0, np.zeros(8, np.float32))
        np.testing.assert_allclose(wj.pull_sync(0), 0.0)
        # w2's first push + the joiner's complete the round (target 4)
        ws[2].push(0, np.ones(8, np.float32))
        wj.push(0, np.ones(8, np.float32))
        assert done.wait(timeout=30), "parked pull never served"
        # accum = 2 (merged) + 1 + 1 = 4 → weights 0 - 4 = -4
        np.testing.assert_allclose(got["w2"], -4.0)
        for w in ws + [wj]:
            w.wait_all()
    finally:
        sim.shutdown()
