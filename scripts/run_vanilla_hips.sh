#!/usr/bin/env bash
# Acceptance config: vanilla_hips (mirrors the reference scripts/cpu/run_vanilla_hips.sh)
exec "$(dirname "$0")/run_cluster.sh" 
