from geomx_tpu.optim.server_opt import (  # noqa: F401
    ServerOptimizer, Sgd, Adam, DCASGD, make_optimizer,
)
