"""TSEngine: adaptive overlay scheduling for model dissemination.

Reimplements the reference's TSEngine pull direction (ref: van.cc:1312-1458
ProcessAskPullCommand, kv_app.h:1040-1224 AutoPullUpdate relay,
kvstore_dist_server.h:1368-1384 DefaultAutoPull): instead of every worker
pulling from the server (star topology), the server sends the updated
model to ONE node chosen by the scheduler; each receiver relays it onward
to the next scheduler-chosen node, forming a dissemination chain/tree
tuned by *observed throughput* — senders report the throughput of their
last transfer, the scheduler keeps a matrix ``A[from][to]`` and picks the
next receiver greedily with probability ``min(known_fraction,
MAX_GREED_RATE_TS)``, else uniformly (ε-exploration, ref: van.cc:1312-1386).

Scope: both tiers are wired into the kvstore — intra-party
(enable_intra_ts: party server → workers over the LAN) and inter-party
(enable_inter_ts: global servers → local servers over the WAN, replacing
the FSA pull-down with overlay dissemination).  Round tokens are strings
("node:counter") so concurrent initiators (MultiGPS global servers)
never collide in the scheduler's served-set.

Control plane: Control.ASK_PULL / Control.REPLY / Control.AUTOPULL_REPLY
messages through Postoffice control hooks (ref: new control cmds
message.h:135-136).
"""

from __future__ import annotations

import queue
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from geomx_tpu.core.config import Config, NodeId
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.transport.reactor import Periodic, resolve_reactor_workers

# Lightweight mode runs dissemination jobs on the shared reactor pool,
# and a job PARKS its worker across scheduler/ack round-trips (bounded
# by ts_ask_timeout_s).  Cap how many may park at once to half the pool:
# relays beyond the cap simply stay queued until a slot frees, so the
# reply/ack handler channels can always find a worker — without the cap,
# enough concurrent relays would occupy every worker and stall the very
# replies they are waiting on until timeout.
_DISSEM_SLOTS = threading.BoundedSemaphore(
    max(2, resolve_reactor_workers() // 2))


class TsScheduler:
    """Runs on a scheduler node; answers ASK_PULL with the next receiver.

    Round state: a dissemination round (one model broadcast) is identified
    by ``iter``; each member is served at most once per round (the busy
    vector B1 of the reference, ref: van.h:198-204).
    """

    def __init__(self, postoffice: Postoffice, members: Sequence[NodeId],
                 greed_rate: float = 0.9, seed: int = 0):
        self.po = postoffice
        self.members = [str(m) for m in members]
        self.greed = greed_rate
        self.A: Dict[str, Dict[str, float]] = {}  # A[from][to] = throughput
        # true LRU (recency = last ask touching the round), not
        # insertion order: a long-running round kept alive by asks must
        # not be evicted just because it STARTED first
        self._served: "OrderedDict[str, set]" = OrderedDict()
        self._done: set = set()
        self._done_rounds: list = []
        self._mu = threading.Lock()
        self._rng = random.Random(seed)
        self._member_seq = -1   # last applied membership broadcast stamp
        postoffice.add_control_hook(self._on_control)
        postoffice.add_control_hook(self._on_membership)

    def _on_membership(self, msg: Message) -> bool:
        """Dynamic join/leave: the party server broadcasts the live
        member list (seq-stamped); the overlay's dissemination targets
        must track it — a joiner the scheduler doesn't know never
        receives a relay, a leaver it still knows wedges every round's
        chain on a dead hop (VERDICT r4 item 6: the reference's
        ADD_NODE is uniform, van.cc:41-112)."""
        body = msg.body if isinstance(msg.body, dict) else {}
        if (msg.control is not Control.ADD_NODE or msg.request
                or body.get("event") != "membership"
                or "members" not in body):
            return False
        from geomx_tpu.transport.van import apply_member_addrs

        # the scheduler must be able to DIAL a dynamic joiner (ask
        # replies, and choosing it as a relay target presumes peers can)
        apply_member_addrs(self.po.van.fabric, body.get("addrs"),
                           str(self.po.node))
        seq = body.get("seq")
        with self._mu:
            if seq is not None and seq > self._member_seq:
                self._member_seq = seq
                self.members = [str(m) for m in body["members"]]
            elif seq is None:
                self.members = [str(m) for m in body["members"]]
        # NOT exclusive: hooks stop at the first True, and the push
        # scheduler on this same postoffice consumes the broadcast too
        return False

    def _on_control(self, msg: Message) -> bool:
        if msg.control is not Control.ASK_PULL:
            return False
        body = msg.body or {}
        it = str(body.get("iter", ""))
        sender = str(msg.sender)
        # learn the reported throughput of the asker's last transfer
        last, thr = body.get("last"), body.get("throughput")
        if last is not None and thr is not None:
            self.A.setdefault(sender, {})[last] = float(thr)
        with self._mu:
            if it in self._done:
                # round already fully served — a late relayer's ask must
                # NOT recreate the served-set and re-serve stale data
                receiver = None
            else:
                if it not in self._served and len(self._served) > 1000:
                    # rounds abandoned mid-flight (relay timeout, dead
                    # member) never reach the no-candidates branch — bound
                    # the map by evicting the least-recently-asked round
                    self._served.popitem(last=False)
                served = self._served.setdefault(it, set())
                self._served.move_to_end(it)  # refresh recency
                candidates = [m for m in self.members
                              if m not in served and m != sender]
                if not candidates:
                    receiver = None
                    self._served.pop(it, None)
                    self._done.add(it)
                    self._done_rounds.append(it)
                    if len(self._done_rounds) > 1000:
                        old = self._done_rounds.pop(0)
                        self._done.discard(old)
                        self._served.pop(old, None)
                else:
                    receiver = self._choose(sender, candidates)
                    served.add(receiver)
        self.po.van.send(msg.reply_to(
            control=Control.REPLY, body={"receiver": receiver, "iter": it}))
        return True

    def _choose(self, sender: str, candidates: List[str]) -> str:
        known = self.A.get(sender, {})
        known_frac = len([c for c in candidates if c in known]) / len(candidates)
        if known and self._rng.random() < min(known_frac, self.greed):
            best = max(candidates, key=lambda c: known.get(c, 0.0))
            if known.get(best, 0.0) > 0.0:
                return best
        return self._rng.choice(candidates)


class TsClient:
    """Ask-the-scheduler helper + relay bookkeeping for one node
    (ref: GetReceiver blocking ask van.cc:1474-1504)."""

    def __init__(self, postoffice: Postoffice, scheduler: NodeId,
                 domain: Domain = Domain.LOCAL):
        import queue as _queue

        self.po = postoffice
        self.scheduler = scheduler
        self.domain = domain
        import collections

        self._cv = threading.Condition()
        self._replies: Dict[int, Optional[str]] = {}
        self._acks: set = set()
        self._ack_order: "collections.deque" = collections.deque()
        self._seq = 0
        postoffice.add_control_hook(self._on_control)
        # dissemination must never run on a customer/handler dispatch
        # lane: the ask/send loop blocks on round-trips, and blocking a
        # handler deadlocks when two nodes relay to each other
        # concurrently.  Lightweight mode folds the job queue onto the
        # reactor timer wheel (a Periodic tick drains it on the worker
        # pool, slot-capped by _DISSEM_SLOTS); the threaded transport
        # keeps the dedicated per-node drain thread.
        self._dq: "_queue.Queue" = _queue.Queue()
        self._dissem_thread = None
        self._dissem_task = None
        fabric = getattr(postoffice.van, "fabric", None)
        reactor = getattr(fabric, "reactor", None)
        if getattr(fabric, "lightweight", False) and reactor is not None:
            self._dissem_task = Periodic(
                0.005, self._drain_dissem,
                name=f"ts-dissem-{postoffice.node}", reactor=reactor)
        else:
            self._dissem_thread = threading.Thread(
                target=self._dissem_loop, daemon=True,
                name=f"ts-dissem-{postoffice.node}")
            self._dissem_thread.start()

    def disseminate_async(self, keys, vals, lens, it: str, cmd: int):
        """Queue a relay round: ask the scheduler for receivers and send
        until the round is fully served (ref: AutoPullUpdate loop
        kv_app.h:1181-1224). Returns immediately."""
        self._dq.put((keys, vals, lens, it, cmd))

    def _dissem_loop(self):
        while True:
            job = self._dq.get()
            if job is None:
                return
            self._run_dissem(job)

    def _drain_dissem(self):
        """One timer-wheel tick: run queued dissemination rounds on this
        pool worker, as long as a park slot is free.  A job left queued
        by slot exhaustion is retried next tick — relays are latency-
        tolerant (the overlay already pipelines hops)."""
        while True:
            if not _DISSEM_SLOTS.acquire(blocking=False):
                return  # pool protection: stay queued, retry next tick
            try:
                try:
                    job = self._dq.get_nowait()
                except queue.Empty:
                    return
                if job is None:
                    continue  # stop() sentinel
                self._run_dissem(job)
            finally:
                _DISSEM_SLOTS.release()

    def _run_dissem(self, job):
        keys, vals, lens, it, cmd = job
        last, thr = None, None
        try:
            while True:
                recv = self.ask_receiver(it, last, thr)
                if recv is None:
                    break
                thr = self.send_model(recv, keys, vals, lens, it, cmd)
                last = str(recv)
        except TimeoutError:  # pragma: no cover - surfaced in logs
            import logging

            logging.getLogger(__name__).warning(
                "%s: TS dissemination round %s aborted", self.po.node, it)

    def stop(self):
        if self._dissem_task is not None:
            self._dissem_task.stop()
            self._dissem_task = None
        self._dq.put(None)

    def _on_control(self, msg: Message) -> bool:
        """A node can host several TsClients (intra + inter overlays):
        scheduler REPLYs are consumed only by the client of that
        scheduler; AUTOPULL_REPLY acks are recorded but NOT consumed so
        every client sees them (the ack key includes the round token,
        which only the initiating client waits on)."""
        if msg.control is Control.REPLY and isinstance(msg.body, dict) \
                and "receiver" in msg.body:
            if msg.sender != self.scheduler:
                return False
            with self._cv:
                self._replies[msg.timestamp] = msg.body["receiver"]
                self._cv.notify_all()
            return True
        if msg.control is Control.AUTOPULL_REPLY:
            # delivery confirmation from a relay receiver
            # (ref: WaitForFinish van.cc:1142-1165)
            key = (str(msg.sender), str(msg.body["iter"]))
            with self._cv:
                self._acks.add(key)
                self._ack_order.append(key)
                # evict oldest unmatched (foreign) acks only — a blanket
                # clear() could wipe an ack a live send_model is awaiting
                while len(self._ack_order) > 10_000:
                    self._acks.discard(self._ack_order.popleft())
                self._cv.notify_all()
            return False
        return False

    def send_model(self, recipient: NodeId, keys, vals, lens, it: str,
                   cmd: int, app_id: int = 0,
                   timeout: Optional[float] = None) -> float:
        """Send a model relay message; block for the receiver's
        AUTOPULL_REPLY; return the observed throughput (bytes/sec)."""
        ack_key = (str(recipient), it)
        with self._cv:
            self._acks.discard(ack_key)
        msg = Message(
            recipient=recipient, domain=self.domain, app_id=app_id,
            customer_id=0, timestamp=-1, request=True, push=True, cmd=cmd,
            keys=keys, vals=vals, lens=lens, body={"iter": it},
        )
        if timeout is None:
            timeout = self.po.config.ts_ask_timeout_s
        nbytes = msg.nbytes
        t0 = time.monotonic()
        self.po.van.send(msg)
        with self._cv:
            ok = self._cv.wait_for(lambda: ack_key in self._acks,
                                   timeout=timeout)
            if not ok:
                raise TimeoutError(f"{self.po.node}: TS relay to "
                                   f"{recipient} unacked")
            self._acks.discard(ack_key)
        elapsed = max(time.monotonic() - t0, 1e-9)
        return nbytes / elapsed

    def send_reply(self, to: NodeId, it: str):
        self.po.van.send(Message(
            recipient=to, control=Control.AUTOPULL_REPLY,
            domain=self.domain, body={"iter": it},
        ))

    def ask_receiver(self, it: str, last: Optional[str] = None,
                     throughput: Optional[float] = None,
                     timeout: Optional[float] = None) -> Optional[NodeId]:
        """Blocking: who should I send the round-``it`` model to next?"""
        if timeout is None:
            timeout = self.po.config.ts_ask_timeout_s
        with self._cv:
            self._seq += 1
            seq = self._seq
        self.po.van.send(Message(
            recipient=self.scheduler, control=Control.ASK_PULL,
            domain=self.domain, timestamp=seq,
            body={"iter": it, "last": last, "throughput": throughput},
        ))
        with self._cv:
            ok = self._cv.wait_for(lambda: seq in self._replies, timeout=timeout)
            if not ok:
                raise TimeoutError(f"{self.po.node}: TS ask_receiver timed out")
            r = self._replies.pop(seq)
        return NodeId.parse(r) if r else None
