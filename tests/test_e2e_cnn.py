"""End-to-end acceptance: CNN trains through the full HiPS stack.

The reference's correctness oracle is "accuracy climbs like vanilla"
(ref: SURVEY.md §4 convergence-as-oracle).  2 parties × 2 workers, FSA,
server-side Adam; loss must drop and all workers must hold identical
weights after each round."""

import threading

import jax
import numpy as np

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.data import ShardedIterator, synthetic_classification
from geomx_tpu.kvstore import Simulation
from geomx_tpu.models import create_cnn_state
from geomx_tpu.training import flatten_params, run_worker


def test_cnn_trains_through_hips():
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=2))
    sim = Simulation(cfg)
    try:
        x, y = synthetic_classification(n=512, shape=(12, 12, 1), seed=1)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 12, 12, 1))

        histories = {}
        lock = threading.Lock()

        def worker_main(party, rank, widx):
            kv = sim.worker(party, rank)
            if widx == 0:
                kv.set_optimizer({"type": "adam", "lr": 0.01})
            kv.barrier()
            it = ShardedIterator(x, y, 16, widx, 4, seed=2)
            hist = run_worker(kv, params, grad_fn, it, steps=8)
            with lock:
                histories[widx] = hist

        threads = []
        for widx, (p, r) in enumerate([(0, 0), (0, 1), (1, 0), (1, 1)]):
            t = threading.Thread(target=worker_main, args=(p, r, widx))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=180)
        assert len(histories) == 4, "a worker thread died or hung"

        first = [h[0][0] for h in histories.values()]
        last = [h[-1][0] for h in histories.values()]
        assert np.mean(last) < np.mean(first), (first, last)

        # FSA invariant: every party's local server ends with identical stores
        s0 = sim.local_servers[0].store
        s1 = sim.local_servers[1].store
        assert set(s0) == set(s1)
        for k in s0:
            np.testing.assert_allclose(s0[k], s1[k], rtol=1e-5, atol=1e-6)

        # WAN traffic flowed through tier 2
        assert sim.wan_bytes()["wan_send_bytes"] > 0
    finally:
        sim.shutdown()
