"""Scheduler tests: P3 priority propagation, DGT transport, TSEngine overlay."""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation


def make_sim(parties=1, workers=2, **cfg_kw):
    cfg = Config(
        topology=Topology(num_parties=parties, workers_per_party=workers),
        **cfg_kw,
    )
    return Simulation(cfg)


# ---------------- P3 ----------------------------------------------------------

def test_p3_push_pull_trains_and_slices():
    """P3 mode: big tensors slice into independent keyed requests; values
    return on the push response; result matches plain FSA."""
    sim = make_sim(parties=2, workers=1, enable_p3=True, p3_slice_elems=100)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(350, np.float32))  # → 4 slices of ≤100
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        got = {}
        for i, w in enumerate(ws):
            w.push_pull(0, np.ones(350, np.float32),
                        lambda t, arr, i=i: got.__setitem__(i, arr))
        for w in ws:
            w.wait_all()
        # avg grad over 2 parties = 1; lr 0.1 → -0.1 everywhere
        for i in range(2):
            np.testing.assert_allclose(got[i], -0.1, rtol=1e-5)
        # slicing actually happened: local server holds 4 keys
        assert len(sim.local_servers[0].store) == 4
    finally:
        sim.shutdown()


# ---------------- DGT ---------------------------------------------------------

def _mk_push_msg(vals, key=7):
    from geomx_tpu.core.config import NodeId, Role
    from geomx_tpu.transport.message import Domain, Message
    return Message(
        sender=NodeId(Role.SERVER, 0, 0), recipient=NodeId(Role.GLOBAL_SERVER, 0),
        domain=Domain.GLOBAL, app_id=0, customer_id=1, timestamp=5,
        request=True, push=True, cmd=0,
        keys=np.array([key], np.int64), vals=vals,
        lens=np.array([len(vals)], np.int64),
    )


def test_dgt_split_reassemble_lossless():
    from geomx_tpu.transport.dgt import DgtReassembler, DgtSender
    cfg = Config(enable_dgt=1, dgt_block_size=100, dgt_k=0.3,
                 dgt_udp_channels=3)
    snd = DgtSender(cfg)
    vals = np.random.default_rng(0).standard_normal(950).astype(np.float32)
    chunks = snd.split(_mk_push_msg(vals))
    assert len(chunks) == 10
    assert chunks[-1].seq == chunks[-1].seq_end and chunks[-1].channel == 0
    # top-30% contribution chunks ride channel 0
    assert sum(1 for c in chunks if c.channel == 0) >= 3
    rs = DgtReassembler()
    out = None
    for c in chunks:
        out = rs.accept(c) or out
    assert out is not None
    np.testing.assert_array_equal(out.vals, vals)
    np.testing.assert_array_equal(out.keys, [7])
    assert out.timestamp == 5 and out.push and out.request


def test_dgt_drops_zero_fill_unimportant_only():
    from geomx_tpu.transport.dgt import DgtReassembler, DgtSender
    cfg = Config(enable_dgt=1, dgt_block_size=100, dgt_k=0.2,
                 dgt_udp_channels=2)
    snd = DgtSender(cfg)
    vals = np.zeros(1000, np.float32)
    vals[:200] = 10.0   # two high-contribution blocks
    vals[200:] = 0.01   # low-contribution tail
    chunks = snd.split(_mk_push_msg(vals))
    rs = DgtReassembler()
    out = None
    for c in chunks:
        if c.channel >= 1:
            continue  # the "network" drops every lossy chunk
        out = rs.accept(c) or out
    assert out is not None
    np.testing.assert_array_equal(out.vals[:200], 10.0)  # important survived
    # the completion chunk (last block) is always reliable; everything
    # else in the low-contribution tail was dropped and zero-filled
    assert np.count_nonzero(out.vals[200:900]) == 0
    np.testing.assert_allclose(out.vals[900:], 0.01, rtol=1e-6)


def test_dgt_training_descends_under_loss():
    """enable_dgt=1 with 60% loss on lossy channels: flow completes and
    the model still moves downhill (important chunks always arrive)."""
    from geomx_tpu.transport.van import FaultPolicy
    cfg = Config(
        topology=Topology(num_parties=2, workers_per_party=1),
        enable_dgt=1, dgt_block_size=256, dgt_k=0.3, dgt_udp_channels=2,
    )
    sim = Simulation(cfg, fault=FaultPolicy(channel_drop_rate=0.6, seed=5))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4096, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        rng = np.random.default_rng(0)
        for _ in range(3):
            g = np.abs(rng.standard_normal(4096)).astype(np.float32)
            for w in ws:
                w.push(0, g)
            outs = [w.pull_sync(0) for w in ws]
        for out in outs:
            assert out.mean() < -0.01, out.mean()
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    finally:
        sim.shutdown()


# ---------------- TSEngine ----------------------------------------------------

def test_tsengine_overlay_delivers_updates():
    """Intra-TS: workers never pull from the server; the scheduler-driven
    relay chain delivers every round's model to every worker."""
    sim = make_sim(parties=1, workers=3, enable_intra_ts=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        got = {}
        for step in range(3):
            for w in ws:
                w.push(0, np.ones(64, np.float32))
            for i, w in enumerate(ws):
                w.pull(0, lambda t, arr, i=i: got.__setitem__(i, arr))
            for w in ws:
                w.wait_all()
        # grads: party sum = 3, /num_workers scale not applied here →
        # global grad 3 per step, lr .1 → -0.3/step × 3 steps
        for i in range(3):
            np.testing.assert_allclose(got[i], -0.9, rtol=1e-5)
        # the scheduler's throughput matrix learned something
        A = sim.ts_schedulers[0].A
        assert len(A) > 0
    finally:
        sim.shutdown()


def test_dgt_mode3_4bit_requant():
    """Mode 3: unimportant chunks travel 4-bit quantized on the reliable
    channel — ~8x less wire for the low-contribution mass, bounded error."""
    from geomx_tpu.transport.dgt import DgtReassembler, DgtSender, dequant4, quant4

    # unit: quant4 round-trip
    v = np.linspace(-2, 3, 101).astype(np.float32)
    p, lo, hi = quant4(v)
    np.testing.assert_allclose(dequant4(p, 101, lo, hi), v, atol=(hi - lo) / 15)

    cfg = Config(enable_dgt=3, dgt_block_size=100, dgt_k=0.2,
                 dgt_udp_channels=2)
    snd = DgtSender(cfg)
    vals = np.zeros(1000, np.float32)
    vals[:200] = 10.0
    vals[200:] = np.linspace(0.01, 0.02, 800).astype(np.float32)
    chunks = snd.split(_mk_push_msg(vals))
    assert all(c.channel == 0 for c in chunks)  # mode 3: all reliable
    quantized = [c for c in chunks
                 if isinstance(c.body, dict) and "_dgt4" in c.body]
    assert len(quantized) >= 5  # the unimportant tail
    assert all(c.vals.dtype == np.uint8 and len(c.vals) == 50
               for c in quantized)  # 100 f32 → 50 bytes
    rs = DgtReassembler()
    out = None
    for c in chunks:
        out = rs.accept(c) or out
    np.testing.assert_array_equal(out.vals[:200], 10.0)  # important exact
    np.testing.assert_allclose(out.vals[200:], vals[200:], atol=0.002)


def test_tsengine_push_merge_through_training():
    """enable_intra_ts end-to-end: gradients ride the worker-to-worker
    merge tree, ONE worker pushes per party round (num_merge counted),
    and the pull overlay delivers the update — result matches plain FSA."""
    sim = make_sim(parties=2, workers=3, enable_intra_ts=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        got = {}
        elected_counts = []

        def round_once():
            import threading as _t
            elected = []
            lock = _t.Lock()

            def wmain(i, w):
                was = w.ts_merge_push({0: np.ones(32, np.float32)})
                with lock:
                    if was:
                        elected.append(i)
                w.pull(0, lambda t, a, i=i: got.__setitem__(i, a))
                w.wait_all()

            ts = [_t.Thread(target=wmain, args=(i, w))
                  for i, w in enumerate(ws)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            elected_counts.append(len(elected))

        for _ in range(2):
            round_once()
        # one elected pusher per party per round
        assert all(c == 2 for c in elected_counts), elected_counts
        # party sum = 3 ones; global mean over parties = 3 → -0.3/step × 2
        for i in range(6):
            np.testing.assert_allclose(got[i], -0.6, rtol=1e-5)
    finally:
        sim.shutdown()


def test_tsengine_inter_party_overlay():
    """Inter-TS: the WAN pull-down is replaced by scheduler-driven
    dissemination from the global server to the local servers — results
    must match plain FSA exactly."""
    sim = make_sim(parties=3, workers=1, enable_inter_ts=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for step in range(3):
            for w in ws:
                w.push(0, np.ones(64, np.float32))
            outs = [w.pull_sync(0) for w in ws]
        # global grad per step = sum over 3 parties / 3 = 1 → -0.1/step
        for out in outs:
            np.testing.assert_allclose(out, -0.3, rtol=1e-5)
        # the global scheduler's throughput matrix learned links
        assert len(sim.ts_schedulers[-1].A) > 0
    finally:
        sim.shutdown()


def test_tsengine_inter_party_push_merge_exact():
    """Push-direction inter-TS: parties pair-merge over the WAN, one
    elected server pushes the merged set (counted num_global_workers
    contributions) — result must match plain FSA exactly
    (ref: global ASK_PUSH van.cc:1254-1310)."""
    sim = make_sim(parties=3, workers=1, enable_inter_ts=True,
                   enable_inter_ts_push=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(48, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for step in range(3):
            for w in ws:
                w.push(0, np.ones(48, np.float32))
            outs = [w.pull_sync(0) for w in ws]
        # party sum = 1 each; global mean over 3 parties = 1 → -0.1/step
        for out in outs:
            np.testing.assert_allclose(out, -0.3, rtol=1e-5)
        # the WAN carried ONE gradient push per round, not three: the
        # global servers' inbound push traffic is ~1/3 of the FSA case
    finally:
        sim.shutdown()


def test_tsengine_inter_push_multikey_batch_orders():
    """Per-key round tokens pair correctly even when parties complete
    keys in different batch orders (two tensors, interleaved pushes)."""
    sim = make_sim(parties=2, workers=1, enable_inter_ts=True,
                   enable_inter_ts_push=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(16, np.float32))
            w.init(1, np.zeros(8, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        # party 0 pushes tensor 0 then 1; party 1 pushes 1 then 0
        ws[0].push(0, np.ones(16, np.float32))
        ws[1].push(1, np.full(8, 2.0, np.float32))
        ws[0].push(1, np.full(8, 2.0, np.float32))
        ws[1].push(0, np.ones(16, np.float32))
        for w in ws:
            np.testing.assert_allclose(w.pull_sync(0), -1.0, rtol=1e-5)
            np.testing.assert_allclose(w.pull_sync(1), -2.0, rtol=1e-5)
    finally:
        sim.shutdown()


def test_tsengine_inter_party_under_async_tier():
    """Inter-TS + MixedSync (async global tier): rounds finish without a
    pull-down; rate-limited dissemination refreshes the local replicas
    (previously rejected; now supported via inter_ts_async_every)."""
    sim = make_sim(parties=2, workers=1, enable_inter_ts=True,
                   sync_global_mode=False, inter_ts_async_every=2)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for step in range(4):  # 4 party-rounds → 8 async pushes → ≥4 dissems
            for w in ws:
                w.push(0, np.ones(32, np.float32))
            for w in ws:
                w.pull_sync(0)
            for w in ws:
                w.wait_all()
        # dissemination is asynchronous — poll until the overlay delivered
        # an updated replica to the local servers
        deadline = time.monotonic() + 10
        vals = [0.0, 0.0]
        while time.monotonic() < deadline:
            vals = [float(w.pull_sync(0)[0]) for w in ws]
            if all(v < 0 for v in vals):
                break
            time.sleep(0.05)
        # async: every push applies individually (8 pushes × lr 0.1 × grad 1
        # = -0.8 at the global store); replicas must have caught up to a
        # negative (post-update) value by now
        assert all(v < 0 for v in vals), vals
    finally:
        sim.shutdown()


def test_tsengine_intra_plus_inter_combined():
    """Both overlays at once: worker pulls come from the intra relay,
    local-server weights come from the inter relay."""
    sim = make_sim(parties=2, workers=2, enable_intra_ts=True,
                   enable_inter_ts=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        got = {}
        for step in range(2):
            for w in ws:
                w.push(0, np.ones(32, np.float32))
            for i, w in enumerate(ws):
                w.pull(0, lambda t, a, i=i: got.__setitem__(i, a))
            for w in ws:
                w.wait_all()
        # party sum = 2, global mean over 2 parties = 2 → -0.2/step × 2
        for i in range(4):
            np.testing.assert_allclose(got[i], -0.4, rtol=1e-5)
    finally:
        sim.shutdown()


def test_tsengine_scheduler_greedy_prefers_fast_links():
    """With a fully-known throughput row, greed picks the argmax."""
    from geomx_tpu.sched.tsengine import TsScheduler

    class FakePO:
        class van:
            @staticmethod
            def send(msg):
                pass
        @staticmethod
        def add_control_hook(h):
            pass

    s = TsScheduler(FakePO, ["w0", "w1", "w2"], greed_rate=1.0, seed=0)
    s.A["server"] = {"w0": 1.0, "w1": 100.0, "w2": 2.0}
    picks = [s._choose("server", ["w0", "w1", "w2"]) for _ in range(10)]
    assert all(p == "w1" for p in picks)


def test_tsengine_push_direction_merge_tree():
    """3 workers merge their gradients worker-to-worker; exactly one is
    elected to push the fully-merged set (ref: ASK_PUSH pairing
    van.cc:1197-1252 + WorkersMerge kvstore_dist.h:91-173)."""
    from geomx_tpu.sched.ts_push import TsPushScheduler, TsPushWorker

    sim = make_sim(parties=1, workers=3)
    try:
        topo = sim.topology
        TsPushScheduler(sim.offices[str(topo.scheduler(0))], num_workers=3)
        results = {}
        lock = threading.Lock()

        def worker_main(rank):
            kv = sim.worker(0, rank)
            tsp = TsPushWorker(kv.po, topo.scheduler(0), kv.worker)
            grads = {0: np.full(16, float(rank + 1), np.float32),
                     1: np.full(4, 10.0 * (rank + 1), np.float32)}
            merged = tsp.merge_push(grads)
            with lock:
                results[rank] = merged

        threads = [threading.Thread(target=worker_main, args=(r,))
                   for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 3
        elected = [r for r, m in results.items() if m is not None]
        assert len(elected) == 1, results
        merged, num_merge = results[elected[0]]
        assert num_merge == 3
        # sum over workers: (1+2+3) and 10*(1+2+3)
        np.testing.assert_allclose(merged[0], 6.0)
        np.testing.assert_allclose(merged[1], 60.0)
    finally:
        sim.shutdown()


def test_concurrent_default_token_merge_rejected():
    """advisor r5: two concurrent default-token merge_push calls from
    ONE sender would silently cross-merge different rounds' gradients
    in the shared __worker_round__ bucket — the scheduler now refuses
    the second ask and the worker raises instead.  Per-key STRING
    tokens (the inter-party server path) stay concurrent-safe."""
    from geomx_tpu.sched.ts_push import TsPushScheduler, TsPushWorker

    sim = make_sim(parties=1, workers=2)
    try:
        topo = sim.topology
        TsPushScheduler(sim.offices[str(topo.scheduler(0))], num_workers=2)
        kv0, kv1 = sim.worker(0, 0), sim.worker(0, 1)
        tsp0 = TsPushWorker(kv0.po, topo.scheduler(0), kv0.worker)
        tsp1 = TsPushWorker(kv1.po, topo.scheduler(0), kv1.worker)
        res = {}

        def first():
            res["first"] = tsp0.merge_push({0: np.ones(8, np.float32)})

        t = threading.Thread(target=first)
        t.start()
        time.sleep(0.3)  # the first ask is parked awaiting a pair
        with pytest.raises(RuntimeError, match="concurrent"):
            tsp0.merge_push({0: np.ones(8, np.float32)})
        # the parked first ask is untouched by the rejection: worker 1
        # joins and the round completes normally
        res["second"] = tsp1.merge_push({0: np.ones(8, np.float32)})
        t.join(timeout=30)
        assert not t.is_alive()
        elected = [m for m in res.values() if m is not None]
        assert len(elected) == 1
        merged, num_merge = elected[0]
        assert num_merge == 2
        np.testing.assert_allclose(merged[0], 2.0)
    finally:
        sim.shutdown()


def test_p3_priority_queue_on_van():
    """enable_p3 switches worker vans to priority send queues."""
    sim = make_sim(parties=1, workers=1, enable_p3=True)
    try:
        w = sim.topology.workers(0)[0]
        assert sim.offices[str(w)].van.use_priority_queue
    finally:
        sim.shutdown()
