"""Key-sharded parallel server merge (PR 5 tentpole).

The servers' per-key state now lives behind N lock stripes with N
serial merge lanes (``kvstore.common.StripedRLock`` /
``ShardExecutor``); membership folds, fences and snapshots take the
all-stripes barrier.  These tests pin:

- the primitives' contracts (per-key FIFO, barrier atomicity, drain);
- merge DETERMINISM under 8 concurrent pushers over disjoint AND
  overlapping keys — sharded and single-lock accumulators bit-identical
  (integer-valued gradients make float accumulation order-independent);
- end-to-end training parity: a sharded deployment converges to exactly
  the single-lock deployment's weights;
- pull serving is not head-of-line blocked behind another key's merge
  (the split pull lane + stripe independence together).
"""

import threading
import time

import numpy as np

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.common import Cmd, ShardExecutor, StripedRLock
from geomx_tpu.ps.kv_app import KVPairs
from geomx_tpu.transport.message import Message


def test_striped_lock_barrier_excludes_stripe_holder():
    lk = StripedRLock(4)
    held = threading.Event()
    release = threading.Event()

    def holder():
        with lk.stripe(2):
            held.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    assert held.wait(2)
    entered = []

    def barrier():
        with lk:
            entered.append(True)

    b = threading.Thread(target=barrier)
    b.start()
    time.sleep(0.1)
    assert not entered, "all-stripes barrier entered past a held stripe"
    release.set()
    b.join(5); t.join(5)
    assert entered
    # re-entrancy: under the barrier, any stripe may be re-taken
    with lk:
        with lk.stripe(0), lk.stripe(3):
            pass


def test_shard_executor_keeps_per_key_fifo():
    ex = ShardExecutor(4)
    try:
        order = {k: [] for k in range(8)}
        for i in range(50):
            for k in range(8):
                ex.submit(k, lambda k=k, i=i: order[k].append(i))
        assert ex.drain(10)
        for k, seen in order.items():
            assert seen == list(range(50)), f"lane {k % 4} reordered key {k}"
    finally:
        ex.stop()


def _push_stress(shards: int, pushers: int = 8, pushes: int = 12,
                 elems: int = 2048):
    """Drive the LocalServer's push handler from ``pushers`` threads:
    each pusher hits its own key (disjoint) AND a shared key
    (overlapping).  Returns {key: accumulated sum} once the lanes
    drain.  Integer-valued gradients keep float accumulation exact, so
    the sums are bit-identical whatever the interleaving."""
    cfg = Config(topology=Topology(num_parties=1,
                                   workers_per_party=pushers),
                 server_shards=shards)
    sim = Simulation(cfg)
    try:
        ls = sim.local_servers[0]
        ls._workers_target = 1 << 30   # rounds must never complete here
        ls.server.response = lambda *a, **k: None  # merge only, no wire
        workers = sim.topology.workers(0)
        shared_key = 1000

        def pusher(i):
            for t in range(pushes):
                for k in (i, shared_key):
                    m = Message(sender=workers[i], recipient=ls.po.node,
                                push=True, request=True,
                                timestamp=t * 2 + (k == shared_key),
                                cmd=Cmd.DEFAULT,
                                keys=np.array([k], np.int64),
                                vals=np.full(elems, float(i + 1),
                                             np.float32),
                                lens=np.array([elems], np.int64))
                    ls._handle_push(m, KVPairs(m.keys, m.vals, m.lens))

        threads = [threading.Thread(target=pusher, args=(i,))
                   for i in range(pushers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert ls._shards.drain(20)
        out = {}
        with ls._mu:
            for k, st in ls._keys.items():
                assert st.accum is not None, f"key {k} lost its accum"
                out[int(k)] = st.accum.tobytes()
                # every pusher's every push must be counted
                expect = pushes * (pushers if k == 1000 else 1)
                assert st.count == expect, (k, st.count, expect)
        return out
    finally:
        sim.shutdown()


def test_sharded_merge_bit_identical_to_single_lock():
    single = _push_stress(shards=1)
    sharded = _push_stress(shards=8)
    assert single.keys() == sharded.keys()
    for k in single:
        assert single[k] == sharded[k], f"key {k} sum diverged"


def test_sharded_e2e_training_parity():
    """A sharded deployment must train to EXACTLY the single-lock
    deployment's weights (4 workers, multi-key model, integer-valued
    gradients pre-scaled by 1/4 stay exact in float32)."""

    def run(shards):
        cfg = Config(topology=Topology(num_parties=1,
                                       workers_per_party=4),
                     server_shards=shards)
        sim = Simulation(cfg)
        try:
            ws = sim.all_workers()
            ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
            for w in ws:
                for k in range(3):
                    w.init(k, np.zeros(256, np.float32))
            rng = np.random.default_rng(42)
            grads = rng.integers(-8, 8, size=(3, 4, 3, 256)) * 4.0
            for r in range(3):
                for i, w in enumerate(ws):
                    for k in range(3):
                        w.push(k, grads[r, i, k].astype(np.float32))
                for w in ws:
                    w.wait_all()
                for w in ws:
                    for k in range(3):
                        w.pull_sync(k)
            # tensor ids map to sharded ps-keys; snapshot the whole store
            return {int(k): np.array(v)
                    for k, v in sim.global_servers[0].store.items()}
        finally:
            sim.shutdown()

    w1 = run(1)
    w8 = run(8)
    assert w1.keys() == w8.keys() and len(w1) == 3
    for k in w1:
        assert np.array_equal(w1[k], w8[k]), f"key {k} weights diverged"


def test_pull_not_blocked_behind_other_keys_merge():
    """Head-of-line independence under sharding: while key B's merge
    lane is stuck, a pull of key A must still be served (split pull
    lane routes it around the push queue; stripes keep A's state free).
    This is the sharded half of the split_pull_queue guarantee — the
    single-lock half lives in test_robustness.py.  lightweight=False:
    lightweight mode runs merge lanes inline with server_shards forced
    to 1 — the sharded configuration under test doesn't exist there."""
    cfg = Config(topology=Topology(num_parties=1, workers_per_party=2),
                 server_shards=4)
    sim = Simulation(cfg, lightweight=False)
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
            w.init(1, np.zeros(64, np.float32))
        ls = sim.local_servers[0]
        block = threading.Event()
        from geomx_tpu.native import bindings as nb
        orig = nb.accumulate

        def slow_accumulate(acc, v, threads=0):
            block.wait(5)  # key B's merge wedged mid-accumulate
            orig(acc, v, threads)

        # wedge key 1's round: first push seeds the accum, second push
        # (the patched accumulate) blocks its lane
        ws[0].push(1, np.ones(64, np.float32))
        ws[0].wait_all()
        import geomx_tpu.kvstore.server as server_mod

        server_mod._native_accumulate = slow_accumulate
        try:
            ws[1].push(1, np.ones(64, np.float32))  # blocks on a lane
            t0 = time.monotonic()
            got = ws[1].pull_sync(0)  # DIFFERENT key: must not wait
            assert time.monotonic() - t0 < 2.0, (
                "pull starved behind another key's merge")
            assert got.shape == (64,)
        finally:
            block.set()
            for w in ws:
                w.wait_all()
            server_mod._native_accumulate = orig
    finally:
        sim.shutdown()


def test_deterministic_mode_forces_single_shard():
    from geomx_tpu.kvstore.common import resolve_server_shards

    cfg = Config(topology=Topology(), server_shards=8, deterministic=True)
    assert resolve_server_shards(cfg) == 1
    cfg2 = Config(topology=Topology(), server_shards=6)
    assert resolve_server_shards(cfg2) == 6
