"""Parallelism tests on the 8-device virtual CPU mesh: ring attention
matches dense attention exactly; the flagship transformer's full train
step compiles and runs under dp/sp/tp(+ep) shardings."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from geomx_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from geomx_tpu.models.transformer import (
    TransformerConfig, init_params, lm_loss, make_apply, param_specs,
)
from geomx_tpu.parallel import make_mesh, ring_attention
from geomx_tpu.parallel.ring_attention import dense_attention


def test_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh({"sp": 4})
    B, T, H, D = 2, 32, 2, 16  # global T = 32, 8 per device
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

    ref = dense_attention(q, k, v, causal=causal)

    spec = P(None, "sp", None, None)
    f = shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp", axis_size=4,
                                       causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_fast_mode_tracks_dense():
    """fast=True (bf16 MXU matmuls inside each ring block, fp32 online
    softmax) stays within bf16 tolerance of the fp32 reference."""
    mesh = make_mesh({"sp": 4})
    B, T, H, D = 2, 32, 2, 16
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
               for _ in range(3))

    ref = dense_attention(q, k, v, causal=True)
    spec = P(None, "sp", None, None)
    f = shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp", axis_size=4,
                                       causal=True, fast=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    from geomx_tpu.parallel import ulysses_attention

    mesh = make_mesh({"sp": 4})
    B, T, H, D = 2, 32, 4, 16  # H=4 divisible by sp=4
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

    ref = dense_attention(q, k, v, causal=causal)

    spec = P(None, "sp", None, None)
    f = shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp",
                                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    from geomx_tpu.parallel import ulysses_attention

    mesh = make_mesh({"sp": 4})
    spec = P(None, "sp", None, None)
    x = jnp.zeros((1, 8, 3, 4), jnp.float32)  # 3 heads, sp=4
    f = shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(x, x, x)


def test_transformer_sharded_train_step_ulysses_sp():
    """The flagship with sp_attn='ulysses': sharded train step compiles,
    runs, and the forward matches the dense path."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=64, sp_attn="ulysses")
    params = init_params(cfg, jax.random.PRNGKey(0))
    apply_fn = make_apply(cfg, mesh)
    specs = param_specs(cfg)
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(params, pshard)
    tokens = jax.device_put(
        jnp.asarray(np.random.default_rng(2).integers(0, 64, (4, 32)),
                    jnp.int32), NamedSharding(mesh, P("dp", "sp")))
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: lm_loss(apply_fn, p, tokens)))(params)
    assert np.isfinite(float(loss))
    dense_apply = make_apply(cfg)
    dense_logits = dense_apply(jax.device_get(params), np.asarray(tokens))
    shard_logits = jax.jit(apply_fn)(params, tokens)
    np.testing.assert_allclose(np.asarray(shard_logits),
                               np.asarray(dense_logits), rtol=3e-2,
                               atol=3e-2)


def test_transformer_dense_forward_and_loss():
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    apply_fn = make_apply(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    logits = jax.jit(apply_fn)(params, tokens)
    assert logits.shape == (2, 16, 64)
    loss = lm_loss(apply_fn, params, tokens)
    assert np.isfinite(float(loss)) and float(loss) < 10


def test_fast_attention_matches_dense():
    """fast_dense_attention (bf16 MXU matmuls, fp32 accum) tracks the
    fp32 reference within bf16 tolerance, including the causal mask."""
    from geomx_tpu.parallel.ring_attention import (
        dense_attention, fast_dense_attention)

    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 32, 4, 16)),
                           jnp.bfloat16) for _ in range(3))
    ref = dense_attention(q, k, v, causal=True)
    fast = fast_dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(fast, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_transformer_attn_impl_and_remat():
    """attn_impl='fast' (default) and 'dense' agree; remat=True changes
    memory strategy, not the math; unknown impl raises."""
    base = dict(vocab=64, d_model=32, n_heads=2, n_layers=2,
                d_ff=64, max_seq=64)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, (2, 16)), jnp.int32)
    params = init_params(TransformerConfig(**base), jax.random.PRNGKey(0))
    out = {}
    for impl, remat in (("fast", False), ("dense", False), ("fast", True)):
        cfg = TransformerConfig(**base, attn_impl=impl, remat=remat)
        out[(impl, remat)] = np.asarray(
            jax.jit(make_apply(cfg))(params, tokens))
    np.testing.assert_allclose(out[("fast", False)], out[("dense", False)],
                               rtol=5e-2, atol=5e-2)
    # remat must be bit-identical to non-remat (same ops, same order)
    np.testing.assert_array_equal(out[("fast", False)], out[("fast", True)])
    with pytest.raises(ValueError):
        make_apply(TransformerConfig(**base, attn_impl="nope"))(
            params, tokens)


def test_two_parties_each_a_slice_through_hips():
    """The headline mapping: 2 'data centers', each a 4-device mesh whose
    gradient aggregation is XLA psum over the slice; only the host edge
    pushes the merged gradient into the HiPS tier (workers_per_party=1)."""
    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation
    from geomx_tpu.parallel.dp import make_party_step, party_meshes
    from geomx_tpu.training import flatten_params, unflatten_params

    meshes = party_meshes(2)  # 4 CPU devices each
    assert all(m.shape["dp"] == 4 for m in meshes)

    # tiny MLP classifier
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((8, 4)) * 0.1, jnp.float32)
    b = jnp.zeros(4, jnp.float32)
    params = {"W": W, "b": b}

    def grad_fn(p, x, y):
        def loss_fn(p):
            logits = x @ p["W"] + p["b"]
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
            acc = jnp.mean(jnp.argmax(logits, -1) == y)
            return loss, acc

        (loss, acc), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        return loss, acc, g

    steps = [make_party_step(grad_fn, m) for m in meshes]

    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=1)))
    try:
        kvs = [sim.worker(p, 0) for p in range(2)]
        leaves, treedef = flatten_params(params)
        for kv in kvs:
            for tid, leaf in enumerate(leaves):
                kv.init(tid, leaf)
        kvs[0].set_optimizer({"type": "sgd", "lr": 0.5})

        x = rng.standard_normal((2, 16, 8)).astype(np.float32)
        y = rng.integers(0, 4, (2, 16)).astype(np.int32)
        losses = []
        cur = [params, params]
        for it in range(6):
            for p in range(2):
                loss, acc, grads = steps[p](cur[p], x[p], y[p])
                g_leaves, _ = jax.tree_util.tree_flatten(grads)
                for tid, g in enumerate(g_leaves):
                    kvs[p].push(tid, np.asarray(g))
            buf = {p: [None] * len(leaves) for p in range(2)}
            for p in range(2):
                for tid in range(len(leaves)):
                    kvs[p].pull(tid, lambda t, a, p=p: buf[p].__setitem__(t, a))
                kvs[p].wait_all()
            for p in range(2):
                cur[p] = unflatten_params(treedef, buf[p])
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # both parties hold identical weights (FSA invariant)
        for l0, l1 in zip(jax.tree_util.tree_leaves(cur[0]),
                          jax.tree_util.tree_leaves(cur[1])):
            np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                                       rtol=1e-5)
    finally:
        sim.shutdown()


def test_pipeline_matches_sequential_and_trains():
    """GPipe schedule over pp=4: outputs match the sequential stack, and a
    jitted pipelined train step learns."""
    from geomx_tpu.parallel.pipeline import (
        init_mlp_stack, mlp_block, pipeline_apply, sequential_apply,
    )

    mesh = make_mesh({"pp": 4})
    d, f, L, M, mb = 16, 32, 8, 8, 4
    params = init_mlp_stack(jax.random.PRNGKey(0), L, d, f)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (M, mb, d)), jnp.float32)

    ref = sequential_apply(params, x)
    out = jax.jit(lambda p, x: pipeline_apply(mesh, mlp_block, p, x))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # differentiable: one pipelined SGD step reduces an MSE loss
    y = ref + 0.1

    def loss_fn(p):
        o = pipeline_apply(mesh, mlp_block, p, x)
        return jnp.mean((o - y) ** 2)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), l

    p1, l0 = step(params)
    _, l1 = step(p1)
    assert float(l1) < float(l0)


def test_transformer_sharded_train_step_dp_sp_tp_ep():
    """The dryrun_multichip path: full train step (fwd+bwd+adam) jitted
    over a dp×sp×tp mesh with a MoE (ep) layer, on 8 virtual devices."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq=64, moe_every=2, n_experts=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    apply_fn = make_apply(cfg, mesh)
    tx = optax.adam(1e-3)

    specs = param_specs(cfg)
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(params, pshard)
    opt_state = tx.init(params)
    tok_shard = NamedSharding(mesh, P("dp", "sp"))

    @jax.jit
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(apply_fn, p, tokens))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jax.device_put(
        jnp.asarray(np.random.default_rng(1).integers(0, 64, (4, 32)),
                    jnp.int32), tok_shard)
    p1, opt_state, loss1 = train_step(params, opt_state, tokens)
    p2, _, loss2 = train_step(p1, opt_state, tokens)
    assert np.isfinite(float(loss1))
    assert float(loss2) < float(loss1)  # learns on the repeated batch

    # sharded-vs-dense numerical agreement of the forward pass
    dense_apply = make_apply(cfg)
    dense_logits = dense_apply(jax.device_get(params), np.asarray(tokens))
    shard_logits = jax.jit(apply_fn)(params, tokens)
    np.testing.assert_allclose(np.asarray(shard_logits),
                               np.asarray(dense_logits), rtol=3e-2, atol=3e-2)
