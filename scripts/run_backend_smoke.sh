#!/usr/bin/env bash
# Merge-backend smoke lane: run the kvstore/failover/eviction/recovery
# test subset with the server merge lanes forced onto the JAX backend
# (GEOMX_MERGE_BACKEND shakes directly-constructed Configs too, the way
# GEOMX_SERVER_SHARDS does for the striped-merge path), so the device
# merge path cannot silently rot while tier-1 runs the numpy default.
# JAX_PLATFORMS=cpu: the point is the backend MACHINERY (staged H2D,
# donated-argument accumulate, mesh psum under the virtual 8-device
# conftest mesh), not accelerator hardware.
#
# Since ISSUE 11 the sweep runs with the DEVICE OPTIMIZER STAGE on
# (GEOMX_MERGE_OPT_DEVICE=1, the default — pinned here so a default
# flip can't silently shrink the lane) and includes the checkpoint/
# restore and device-optimizer suites: every failover, eviction,
# reassignment and warm-boot path runs with device-resident weights +
# moments, proving the export_state/import_state snapshot hooks carry
# the trajectory across all of them.
#
# Env: PYTEST_ARGS (extra pytest flags), GEOMX_MERGE_BACKEND (default jax),
#      GEOMX_MERGE_OPT_DEVICE (default 1)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_MERGE_BACKEND=${GEOMX_MERGE_BACKEND:-jax}
export GEOMX_MERGE_OPT_DEVICE=${GEOMX_MERGE_OPT_DEVICE:-1}

exec python -m pytest -q -m 'not slow' -p no:cacheprovider \
  tests/test_kvstore.py tests/test_failover.py tests/test_eviction.py \
  tests/test_sharded_merge.py tests/test_recovery.py \
  tests/test_sharded_global.py \
  tests/test_merge_backend.py tests/test_device_opt.py \
  ${PYTEST_ARGS:-}
