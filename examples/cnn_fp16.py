#!/usr/bin/env python
"""Reference example-file parity: cnn_fp16.py == cnn.py --compression fp16
(ref: examples/cnn_fp16.py in the reference)."""
import sys
sys.argv[1:1] = "--compression fp16".split()
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parent))
from cnn import main

if __name__ == "__main__":
    sys.exit(main())
