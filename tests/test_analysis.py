"""The static-analysis suite's own tests (ISSUE 14).

Two layers:

- *fixture* tests: each checker runs over a tiny synthetic project
  containing a seeded violation and a known-good twin, proving the
  checker actually catches its bug class (the mutation check the
  acceptance criteria ask for) and does not flag the disciplined
  pattern.
- *live-tree* tests: the real repo is clean modulo the committed
  ``analysis-baseline.toml``, every baseline entry matches something
  (no stale suppressions), and every baseline entry carries a real
  justification (the loader enforces it; the test pins the contract).
"""

import pathlib
import textwrap

import pytest

from geomx_tpu.analysis import (CHECKERS, Baseline, BaselineError, Project,
                                repo_root, run_checkers)
from geomx_tpu.analysis.baseline import parse as parse_baseline
from geomx_tpu.analysis.baseline import skeleton
from geomx_tpu.analysis.config_drift import ConfigDrift
from geomx_tpu.analysis.doc_drift import MetricsDoc
from geomx_tpu.analysis.lock_discipline import LockDiscipline
from geomx_tpu.analysis.reactor_blocking import ReactorBlocking
from geomx_tpu.analysis.wire_protocol import WireProtocol

ROOT = pathlib.Path(__file__).resolve().parent.parent


def make_project(tmp_path, files, docs=None):
    """Build a throwaway project: ``files``/``docs`` map relative paths
    to source text."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    for rel, text in (docs or {}).items():
        p = tmp_path / "docs" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return Project(tmp_path)


def keys(findings):
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# lock discipline


LOCK_FIXTURE = {
    "geomx_tpu/mod.py": '''
    import threading

    class Server:
        def __init__(self):
            self._mu = threading.RLock()
            self._boot_locked()        # ok: construction is pre-concurrent

        def good(self):
            with self._mu:
                self._apply_locked()   # ok: dominated by the lock

        def bad(self):
            self._apply_locked()       # VIOLATION: no lock held

        def chained_locked(self):
            self._apply_locked()       # ok: caller-chain contract

        def documented(self):
            """Caller holds the stripe for this key."""
            self._apply_locked()       # ok: documented contract

        def drains_under_lock(self, shards):
            with self._mu:
                shards.drain()         # VIOLATION: drain under a lock

        def _apply_locked(self):
            pass

        def _boot_locked(self):
            pass

    class Cyclic:
        def __init__(self):
            self._a_mu = threading.Lock()
            self._b_mu = threading.Lock()

        def ab(self):
            with self._a_mu:
                with self._b_mu:
                    pass

        def ba(self):
            with self._b_mu:
                with self._a_mu:
                    pass
    ''',
}


def test_lock_discipline_fixture(tmp_path):
    project = make_project(tmp_path, LOCK_FIXTURE)
    got = keys(LockDiscipline().run(project))
    assert "geomx_tpu/mod.py::Server.bad::_apply_locked" in got
    assert ("geomx_tpu/mod.py::Server.drains_under_lock::drain-under-lock"
            in got)
    assert any(k.startswith("lock-order-cycle::") for k in got)
    # the disciplined patterns stay clean
    for qual in ("Server.good", "Server.chained_locked",
                 "Server.documented", "Server.__init__"):
        assert not any(f"::{qual}::" in k for k in got), (qual, got)


def test_lock_order_interprocedural(tmp_path):
    project = make_project(tmp_path, {"geomx_tpu/mod.py": '''
    import threading

    class A:
        def __init__(self):
            self._a_mu = threading.Lock()
            self._b_mu = threading.Lock()

        def outer(self):
            with self._a_mu:
                self.inner()

        def inner(self):
            with self._b_mu:
                pass

        def reversed_outer(self):
            with self._b_mu:
                with self._a_mu:
                    pass
    '''})
    got = keys(LockDiscipline().run(project))
    assert any(k.startswith("lock-order-cycle::") for k in got), got


# ---------------------------------------------------------------------------
# reactor blocking


REACTOR_FIXTURE = {
    "geomx_tpu/mod.py": '''
    import time

    class BadHandler:
        def __init__(self, reactor):
            self.chan = reactor.channel(self._on_msg)

        def _on_msg(self, msg):
            time.sleep(0.5)                     # VIOLATION
            self._helper(msg)

        def _helper(self, msg):
            self.app.send_cmd(msg.sender, 1)    # VIOLATION (wait=True)

    class GoodHandler:
        def __init__(self, reactor):
            self.chan = reactor.channel(self._on_msg)

        def _on_msg(self, msg):
            self.app.send_cmd(msg.sender, 1, wait=False)   # ok
            self.ev.wait(0.1)                   # ok: bounded Event.wait

    class Tick:
        def __init__(self, reactor):
            reactor.call_every(1.0, self._sweep)

        def _sweep(self):
            self.q.get()                        # VIOLATION (periodic)

    class OffThread:
        def __init__(self, reactor):
            self.chan = reactor.channel(self._on_msg)

        def _on_msg(self, msg):
            import threading
            threading.Thread(target=self._blocking_work).start()  # ok

        def _blocking_work(self):
            time.sleep(5)                       # ok: own thread
    ''',
}


def test_reactor_blocking_fixture(tmp_path):
    project = make_project(tmp_path, REACTOR_FIXTURE)
    got = keys(ReactorBlocking().run(project))
    assert "geomx_tpu/mod.py::BadHandler._on_msg::sleep:sleep" in got
    assert "geomx_tpu/mod.py::BadHandler._helper::send-cmd:send_cmd" in got
    assert "geomx_tpu/mod.py::Tick._sweep::queue-get:get" in got
    # the escape hatch (Thread target) and bounded waits stay clean
    assert not any("GoodHandler" in k for k in got), got
    assert not any("_blocking_work" in k for k in got), got


def test_reactor_blocking_customer_wait_default(tmp_path):
    project = make_project(tmp_path, {"geomx_tpu/mod.py": '''
    class H:
        def __init__(self, reactor):
            self.chan = reactor.channel(self._on)

        def _on(self, msg):
            ts = self.app.send_cmd(msg.sender, 1, wait=False)  # ok
            self.customer.wait(ts)   # VIOLATION: 120 s default timeout
    '''})
    got = keys(ReactorBlocking().run(project))
    assert "geomx_tpu/mod.py::H._on::wait-default:wait" in got


# ---------------------------------------------------------------------------
# wire protocol


WIRE_FIXTURE = {
    "geomx_tpu/transport/message.py": '''
    import enum
    import struct

    class Control(enum.Enum):
        EMPTY = 0
        USED = 1
        ORPHAN = 2          # VIOLATION: never referenced elsewhere
        ALIAS_A = 7
        ALIAS_B = 7         # VIOLATION: duplicate value

    class Message:
        _HDR = struct.Struct("<ii")

        def _pack_hdr(self):
            return self._HDR.pack(self.timestamp, self.boot)

        @classmethod
        def _unpack_hdr(cls, data, off):
            (timestamp, _boot) = cls._HDR.unpack_from(data, off)
            return dict(timestamp=timestamp)   # VIOLATION: boot dropped
    ''',
    "geomx_tpu/transport/dgt.py": '''
    from geomx_tpu.transport.message import Message

    class DgtSender:
        def split(self, msg):
            return [Message(timestamp=msg.timestamp)]  # VIOLATION: no boot

    class DgtReassembler:
        def accept(self, final):
            return Message(timestamp=final.timestamp,
                           boot=final.boot)            # carries boot: ok
    ''',
    "geomx_tpu/user.py": '''
    from geomx_tpu.transport.message import Control

    def handle(m):
        if m.control is Control.USED:
            return True
        return m.control is Control.ALIAS_A or Control.ALIAS_B
    ''',
}


def test_wire_protocol_fixture(tmp_path):
    project = make_project(tmp_path, WIRE_FIXTURE)
    got = keys(WireProtocol().run(project))
    assert "geomx_tpu/transport/message.py::Control::unused:ORPHAN" in got
    assert "geomx_tpu/transport/message.py::Control::dup:7" in got
    assert ("geomx_tpu/transport/message.py::Message._unpack_hdr::"
            "unpack:boot" in got)
    assert "geomx_tpu/transport/dgt.py::DgtSender.split::field:boot" in got
    # the reassembler DOES carry boot
    assert ("geomx_tpu/transport/dgt.py::DgtReassembler.accept::field:boot"
            not in got)
    assert not any(":USED" in k for k in got), got


# ---------------------------------------------------------------------------
# config drift


CONFIG_FIXTURE = {
    "geomx_tpu/core/config.py": '''
    import dataclasses
    import os

    def _env_int(name, default):
        v = os.environ.get(name)
        return default if v is None else int(v)

    @dataclasses.dataclass
    class Config:
        wired: int = 1
        manual_only: float = 2.0    # documented with an em-dash env cell
        drifted: int = 3            # VIOLATION: no env, no doc row

        @staticmethod
        def from_env():
            return Config(wired=_env_int("GEOMX_WIRED", 1))
    ''',
    "geomx_tpu/orphan.py": '''
    import os

    SECRET = os.environ.get("GEOMX_ORPHAN_KNOB", "")  # VIOLATION: no doc
    ''',
}

CONFIG_DOCS = {
    "env-vars.md": '''
    # Config

    | Env | Legacy | Field | Default | Meaning |
    |---|---|---|---|---|
    | `GEOMX_WIRED` | — | `wired` | 1 | a wired knob |
    | — | — | `manual_only` | 2.0 | code-only tuning field |
    | `GEOMX_GONE` | — | — | — | stale row |
    ''',
}


def test_config_drift_fixture(tmp_path):
    project = make_project(tmp_path, CONFIG_FIXTURE, CONFIG_DOCS)
    got = keys(ConfigDrift().run(project))
    assert "geomx_tpu/core/config.py::Config::noenv:drifted" in got
    assert "geomx_tpu/core/config.py::Config::undoc:drifted" in got
    assert "geomx_tpu/orphan.py::env::envundoc:GEOMX_ORPHAN_KNOB" in got
    assert "docs/env-vars.md::doc::stale:GEOMX_GONE" in got
    # wired + documented-manual fields stay clean
    assert not any(":wired" in k or ":manual_only" in k for k in got), got


# ---------------------------------------------------------------------------
# metrics doc (the refactored grep-audit)


METRICS_FIXTURE = {
    "geomx_tpu/mod.py": '''
    from geomx_tpu.utils.metrics import system_counter, system_gauge

    class M:
        def tick(self):
            system_counter(f"{self.node}.good_metric").inc()
            system_gauge(f"{self.node}.bad_metric").set(1)  # undocumented
    ''',
}

METRICS_DOCS = {
    "metrics.md": '''
    # Metrics

    | Name | Meaning |
    |---|---|
    | `good_metric` | documented |
    | `stale_metric` | VIOLATION: no call site |
    ''',
}


def test_metrics_doc_fixture(tmp_path):
    project = make_project(tmp_path, METRICS_FIXTURE, METRICS_DOCS)
    got = keys(MetricsDoc().run(project))
    assert "geomx_tpu/mod.py::metric::missing:`bad_metric`" in got
    assert "docs/metrics.md::row::stale_metric" in got
    assert not any("good_metric" in k for k in got), got


# ---------------------------------------------------------------------------
# baseline mechanics


def test_baseline_rejects_placeholder_reasons():
    with pytest.raises(BaselineError):
        parse_baseline('[[suppress]]\nchecker = "x"\nkey = "a::b::c"\n'
                       'reason = "TODO"\n')
    with pytest.raises(BaselineError):
        parse_baseline('[[suppress]]\nchecker = "x"\nkey = "a::b::c"\n'
                       'reason = "short"\n')


def test_baseline_requires_all_fields():
    with pytest.raises(BaselineError):
        parse_baseline('[[suppress]]\nchecker = "x"\n'
                       'reason = "a perfectly fine justification"\n')


def test_baseline_filter_and_globs(tmp_path):
    project = make_project(tmp_path, LOCK_FIXTURE)
    findings = LockDiscipline().run(project)
    assert findings
    bl = Baseline(parse_baseline(
        '[[suppress]]\nchecker = "lock-discipline"\n'
        'key = "geomx_tpu/mod.py::Server.bad::*"\n'
        'reason = "fixture test exercising glob suppression keys"\n'))
    fresh, eaten = bl.filter(findings)
    assert any(f.key.startswith("geomx_tpu/mod.py::Server.bad::")
               for f in eaten)
    assert not any(f.key.startswith("geomx_tpu/mod.py::Server.bad::")
                   for f in fresh)
    assert not bl.unused()


def test_baseline_skeleton_is_rejected_until_justified(tmp_path):
    project = make_project(tmp_path, LOCK_FIXTURE)
    findings = LockDiscipline().run(project)
    text = skeleton(findings)
    assert "[[suppress]]" in text
    with pytest.raises(BaselineError):
        parse_baseline(text)


# ---------------------------------------------------------------------------
# live tree: the tier-1 audit


def test_live_tree_clean_modulo_baseline():
    """The audit itself: the committed tree has zero unsuppressed
    findings.  Un-fixing any repaired violation (e.g. dropping ``boot``
    from the DGT reassembler again) fails here."""
    fresh, eaten, bl = run_checkers()
    assert not fresh, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in fresh)
    # and no committed suppression has gone stale
    stale = bl.unused()
    assert not stale, (
        "baseline entries that matched nothing (delete them): "
        + str([(s.checker, s.key) for s in stale]))


def test_live_tree_baseline_is_committed_and_justified():
    text = (repo_root() / "analysis-baseline.toml").read_text()
    entries = parse_baseline(text)   # raises on placeholder reasons
    assert entries, "the committed baseline should document the audited "
    "exceptions"


def test_checker_registry_catalog():
    assert set(CHECKERS) == {"lock-discipline", "reactor-blocking",
                             "wire-protocol", "config-drift",
                             "metrics-doc", "decode-bounds"}
    for name, cls in CHECKERS.items():
        assert cls.name == name and cls.description
