"""Worker-side training loop gluing JAX compute to the HiPS kvstore.

Reproduces the reference hot loop (ref: examples/cnn.py:112-126 —
autograd → per-layer kv.push(grad, priority=-idx) → kv.pull → next step),
with the device↔host handoff at the slice edge: grads leave jit as numpy,
pulls come back and are re-wrapped as jax arrays.  Per-layer priorities
mean shallow layers jump the send queue under P3 exactly like the
reference's engine priorities.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Tuple

import jax
import numpy as np

from geomx_tpu.kvstore.client import WorkerKVStore


def save_params(path: str, params) -> None:
    """Client-side parameter checkpoint (ref: gluon save_parameters /
    Module save_checkpoint — python/mxnet/gluon/block.py,
    module/module.py).  Atomic write; msgpack via flax serialization, so
    the tree structure restores without a template."""
    from flax import serialization

    from geomx_tpu.utils.io import atomic_write

    data = serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, params))
    with atomic_write(path) as f:
        f.write(data)


def load_params(path: str):
    """Inverse of :func:`save_params`."""
    from flax import serialization

    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def _preempt_noticed(kv) -> bool:
    """True once a spot-preemption notice landed on this worker
    (Control.PREEMPT_NOTICE / the launch.py SIGTERM mapping): the
    training loops poll it at every step boundary — the noticed worker
    finishes its in-flight step, then stops pushing so the drain can
    flush and leave gracefully.  One attribute load + Event check."""
    ev = getattr(kv, "preempt_noticed", None)
    return ev is not None and ev.is_set()


def flatten_params(params) -> Tuple[List[np.ndarray], object]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


def unflatten_params(treedef, arrs: List[np.ndarray]):
    return jax.tree_util.tree_unflatten(treedef, [jax.numpy.asarray(a) for a in arrs])


def run_worker_hfa(
    kv: WorkerKVStore,
    params,
    grad_fn: Callable,
    data_iter: Iterable,
    steps: int,
    k1: int = 2,
    optimizer=None,
    barrier_init: bool = True,
    log_fn: Optional[Callable[[int, float, float], None]] = None,
    params_out: Optional[dict] = None,
    measure=None,
) -> List[Tuple[float, float]]:
    """HFA client loop (ref: examples/cnn_hfa.py): each worker runs a LOCAL
    optimizer for k1 steps, then pushes weight/num_workers (the local server
    averages weights; every k2-th sync the milestone delta crosses the WAN).
    """
    import optax

    from geomx_tpu.utils.measure import Measure

    m = measure if measure is not None else Measure()
    if optimizer is None:
        optimizer = optax.adam(1e-2)
    leaves, treedef = flatten_params(params)
    for tid, leaf in enumerate(leaves):
        kv.init(tid, leaf, barrier=barrier_init)
    params = unflatten_params(treedef, leaves)
    opt_state = optimizer.init(params)
    history: List[Tuple[float, float]] = []
    buf: List[Optional[np.ndarray]] = [None] * len(leaves)

    for step, (x, y) in enumerate(data_iter):
        if step >= steps or _preempt_noticed(kv):
            break
        m.step_start()
        with m.phase("grad"):
            loss, acc, grads = grad_fn(params, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
        if (step + 1) % k1 == 0:
            params, _ = _hfa_sync_round(kv, params, treedef, len(leaves),
                                        buf, m)
        m.step_end()
        history.append((float(loss), float(acc)))
        if log_fn is not None:
            log_fn(step, float(loss), float(acc))
    if params_out is not None:
        params_out["params"] = params
    return history


def _hfa_sync_round(kv, params, treedef, n_leaves, buf, m,
                    measure_comm: bool = False):
    """One weight-exchange sync: push party-mean weights, pull the
    merged result (shared by the HFA and ESync loops — one place for
    the push normalization and pull-into-buf pattern).

    Returns ``(params, comm_s)``.  ``comm_s`` (only when
    ``measure_comm``) is the TRANSMISSION time: the server acks each
    push on receipt, so waiting on push acks measures the uplink — the
    pull barrier below it is the straggler wait ESync exists to
    eliminate, and counting it as comm would feed the wait back into
    the plan and pin every fast worker at min_steps."""
    import time as _time

    w_leaves, _ = jax.tree_util.tree_flatten(params)
    comm_s = None
    # re-read the party size EVERY sync: dynamic join/leave moves it
    # mid-training (membership broadcast -> kv.num_workers), and the
    # denominator each push used is announced as ``hfa_n`` so the
    # server can renormalize a transition round's mixed-scale mean
    n = kv.num_workers
    t1 = _time.perf_counter()
    with m.phase("push"):
        push_ts = [kv.push(tid, np.asarray(w) / n, priority=-tid,
                           body={"hfa_n": n})
                   for tid, w in enumerate(w_leaves)]
        if measure_comm:
            for pts in push_ts:
                kv.worker.wait(pts)
            comm_s = _time.perf_counter() - t1
        for tid in range(n_leaves):
            kv.pull(tid, lambda t, arr: buf.__setitem__(t, arr),
                    priority=-tid)
    with m.phase("pull_wait"):
        kv.wait_all()
    return unflatten_params(treedef, buf), comm_s


def build_flagship_lm():
    """One shared builder for the flagship LM workload (>=10 M params)
    so the TCP acceptance run (launch.py --workload lm) and the bench's
    lm child train the IDENTICAL step — a size tweak applied to one
    cannot silently diverge the other.  Size via GEOMX_LM_* env.
    Returns ``(cfg, params, n_params, grad_fn, data)``."""
    import os

    import jax
    import numpy as np

    from geomx_tpu.data import synthetic_lm
    from geomx_tpu.models.transformer import (
        TransformerConfig, init_params, make_lm_grad_fn)

    def _e(name, dflt):
        return int(os.environ.get(name, dflt))

    moe_experts = _e("GEOMX_LM_MOE_EXPERTS", 0)
    cfg = TransformerConfig(
        vocab=_e("GEOMX_LM_VOCAB", 8192),
        d_model=_e("GEOMX_LM_DMODEL", 384),
        n_heads=_e("GEOMX_LM_HEADS", 6),
        n_layers=_e("GEOMX_LM_LAYERS", 4),
        d_ff=_e("GEOMX_LM_DFF", 1536),
        max_seq=_e("GEOMX_LM_SEQ", 128),
        attn_impl="fast",
        # GEOMX_LM_MOE_EXPERTS > 0 makes every 2nd layer a top-k routed
        # MoE (real EP) — the flagship's expert gradients then ride the
        # same PS stack as the dense leaves.  top_k clamps to the expert
        # count (top_k > E would raise an opaque trace-time error from
        # lax.top_k inside every worker)
        moe_every=2 if moe_experts > 0 else 0,
        n_experts=max(moe_experts, 1),
        moe_top_k=(min(_e("GEOMX_LM_MOE_TOP_K", 2), moe_experts)
                   if moe_experts > 0 else 0),
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    grad_fn = make_lm_grad_fn(cfg)
    data = synthetic_lm(n=512, seq=cfg.max_seq, vocab=cfg.vocab, seed=0)
    return cfg, params, n_params, grad_fn, data


def run_worker_esync(
    kv: WorkerKVStore,
    params,
    grad_fn: Callable,
    data_iter: Iterable,
    rounds: int,
    optimizer=None,
    barrier_init: bool = True,
    log_fn: Optional[Callable[[int, float, float], None]] = None,
    params_out: Optional[dict] = None,
    max_local_steps: int = 64,
    measure=None,
    rounds_out: Optional[list] = None,
) -> List[Tuple[float, float]]:
    """ESync client loop (geomx_tpu.sched.esync; ref README.md:45 — the
    reference's planned-but-unintegrated straggler balancer, ESync
    TSC'20).

    Like HFA, each worker runs a LOCAL optimizer and pushes mean weights
    at every sync — but the number of local steps between syncs is
    assigned per worker per round by the party's state server, which
    balances reach-server time across heterogeneous workers: fast
    workers fill the slowest worker's round with extra local progress
    instead of idling at the barrier.

    ``rounds`` counts SYNC rounds, identical on every worker of the
    party (one push per worker per round keeps the HFA merge in
    lockstep; a per-worker local-step budget would deadlock the party
    when fast workers exhausted it in fewer rounds).  Local step counts
    per round vary per worker.  ``data_iter`` should yield enough
    batches (up to rounds × max_local_steps) or be cyclic; if it runs
    dry the worker still pushes each remaining round.  Requires HFA mode
    on the servers (weights, not gradients, cross the tiers;
    Config.use_hfa / SET_HFA).
    """
    import time as _time

    import optax

    from geomx_tpu.utils.measure import Measure

    m = measure if measure is not None else Measure()
    if optimizer is None:
        optimizer = optax.adam(1e-2)
    leaves, treedef = flatten_params(params)
    for tid, leaf in enumerate(leaves):
        kv.init(tid, leaf, barrier=barrier_init)
    params = unflatten_params(treedef, leaves)
    opt_state = optimizer.init(params)
    history: List[Tuple[float, float]] = []
    buf: List[Optional[np.ndarray]] = [None] * len(leaves)

    it = iter(data_iter)
    local_steps = 1  # until the state server has a plan
    loss = acc = 0.0
    for _round in range(rounds):
        if _preempt_noticed(kv):
            break
        m.step_start()
        t0 = _time.perf_counter()
        ran = 0
        with m.phase("grad"):
            for _ in range(local_steps):
                try:
                    x, y = next(it)
                except StopIteration:
                    break
                loss, acc, grads = grad_fn(params, x, y)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                ran += 1
                history.append((float(loss), float(acc)))
        step_s = (_time.perf_counter() - t0) / max(ran, 1)
        params, comm_s = _hfa_sync_round(kv, params, treedef, len(leaves),
                                         buf, m, measure_comm=True)
        m.step_end()
        if rounds_out is not None:
            # acceptance observable: (assigned local steps, reach-server
            # seconds) per round — heterogeneous workers must receive
            # different assignments and their reach spread must shrink
            rounds_out.append((ran, round(step_s * ran + comm_s, 4)))
        if ran > 0:
            # a dry data iterator (ran == 0) must not report: its
            # near-zero "step time" would make the planner believe this
            # worker is infinitely fast, collapse the reach-time target,
            # and pin every worker that still has data at min_steps
            local_steps = kv.esync_report(step_s, comm_s,
                                          max_steps=max_local_steps)
        if log_fn is not None:
            log_fn(_round, float(loss), float(acc))
    if params_out is not None:
        params_out["params"] = params
    return history


class Trainer:
    """High-level fit/evaluate facade over the worker loop.

    The reference's user surface is ``gluon.Trainer`` + ``Module.fit``
    (ref: python/mxnet/gluon/trainer.py; module/base_module.py:410 fit —
    bind/init/optimizer/metric handled for the user).  This wraps the
    same ceremony: rank-0 control-plane configuration (optimizer to the
    global tier, compression to the party server), init barrier, the
    training loop (plain FSA or HFA), and streaming-metric evaluation.
    """

    def __init__(self, kv: WorkerKVStore, params, grad_fn: Callable,
                 model=None, optimizer: Optional[dict] = None,
                 compression: Optional[dict] = None,
                 hfa_k1: Optional[int] = None):
        self.kv = kv
        self.params = params
        self.grad_fn = grad_fn
        self.model = model  # flax module; needed for evaluate()
        self.hfa_k1 = hfa_k1
        if (hfa_k1 is not None) != bool(kv.config.use_hfa):
            # the HFA client loop pushes WEIGHTS, the plain loop pushes
            # GRADIENTS — a mismatch with the servers' mode silently
            # corrupts training (weights fed to the optimizer as grads)
            raise ValueError(
                "hfa_k1 must be set if and only if the cluster runs with "
                f"use_hfa (got hfa_k1={hfa_k1!r}, "
                f"config.use_hfa={kv.config.use_hfa})")
        if kv.party == 0 and kv.rank == 0 and optimizer is not None:
            kv.set_optimizer(optimizer)
        if kv.rank == 0 and compression is not None:
            kv.set_gradient_compression(compression)
        kv.barrier()

    def fit(self, data_iter: Iterable, steps: int,
            log_fn: Optional[Callable[[int, float, float], None]] = None,
            measure=None,
            ) -> List[Tuple[float, float]]:
        """Train; returns [(loss, acc)] per step.  Updated params stay on
        the trainer for evaluate()/further fits.  Pass a
        ``utils.Measure`` to collect the per-phase timing report
        (ref: examples/utils.py:120-192)."""
        captured: dict = {}
        if self.hfa_k1 is not None:
            hist = run_worker_hfa(self.kv, self.params, self.grad_fn,
                                  data_iter, steps, k1=self.hfa_k1,
                                  log_fn=log_fn, params_out=captured,
                                  measure=measure)
        else:
            hist = run_worker(self.kv, self.params, self.grad_fn,
                              data_iter, steps, log_fn=log_fn,
                              params_out=captured, measure=measure)
        if "params" in captured:
            self.params = captured["params"]
        return hist

    def save(self, path: str) -> None:
        """Persist the current params (ref: Module save_checkpoint)."""
        save_params(path, self.params)

    def load(self, path: str) -> None:
        """Restore params AND propagate them to the servers (overwrite
        init) — on an already-initialized cluster a local-only load
        would be silently discarded at the first sync.

        Call collectively on every worker of every party, between fits
        (fit() completes all its rounds before returning, so nothing is
        in flight then).  The barrier is party-local; across parties the
        overwrites commute because every party restores the same file —
        the worst cross-party race discards one racing round's gradient
        (equivalent to joining that round one step late)."""
        self.params = load_params(path)
        leaves, _ = flatten_params(self.params)
        self.kv.init_all(dict(enumerate(leaves)), overwrite=True)
        self.kv.barrier()

    def evaluate(self, data_iter: Iterable, batches: int, metric=None):
        """Forward `batches` batches through the model, streaming
        (labels, probabilities) into `metric` (default Accuracy);
        returns ``metric.get()`` — the reference's Module.score
        (ref: module/base_module.py score + metric.py).  Logits are
        softmaxed before the metric so probability-contract metrics
        (CrossEntropy) are correct; argmax metrics are unaffected."""
        from geomx_tpu.utils import metrics as _metrics

        if self.model is None:
            raise ValueError("evaluate() needs the model; pass it to "
                             "Trainer(model=...)")
        if metric is None:
            metric = _metrics.Accuracy()
        for i, (x, y) in enumerate(data_iter):
            if i >= batches:
                break
            logits = self.model.apply(self.params, x)
            probs = np.asarray(jax.nn.softmax(logits, axis=-1))
            metric.update(np.asarray(y), probs)
        return metric.get()


def run_worker(
    kv: WorkerKVStore,
    params,
    grad_fn: Callable,
    data_iter: Iterable,
    steps: int,
    normalize: bool = True,
    barrier_init: bool = True,
    log_fn: Optional[Callable[[int, float, float], None]] = None,
    params_out: Optional[dict] = None,
    measure=None,
) -> List[Tuple[float, float]]:
    """Train `steps` steps; returns [(loss, acc), ...] per step.

    Under FSA the returned params after each step are identical on every
    worker (the convergence oracle the acceptance tests assert).

    ``measure`` (utils.Measure) brackets each phase — grad compute /
    push / pull-wait — per step, the reference examples' per-phase
    timing report (ref: examples/utils.py:120-192).
    """
    from geomx_tpu.utils.measure import Measure

    m = measure if measure is not None else Measure()
    leaves, treedef = flatten_params(params)
    for tid, leaf in enumerate(leaves):
        kv.init(tid, leaf, barrier=barrier_init)
    params = unflatten_params(treedef, leaves)
    # grads are summed across the party then averaged over parties at the
    # global server; pre-divide by party size so the update is the all-worker
    # mean (the reference examples normalize client-side the same way,
    # ref: examples/cnn_hfa.py pushes param/num_local_workers)
    history: List[Tuple[float, float]] = []
    buf: List[Optional[np.ndarray]] = [None] * len(leaves)

    for step, (x, y) in enumerate(data_iter):
        if step >= steps or _preempt_noticed(kv):
            break
        # re-read per step: dynamic join/leave changes the party size
        # mid-training (the server broadcasts the new count, the client
        # hook updates kv.num_workers) — a scale frozen at start would
        # weight this worker's contribution wrongly after a membership
        # change
        scale = 1.0 / kv.num_workers if normalize else 1.0
        m.step_start()
        # the whole step under one sampled root span (no-op unless
        # Config.trace_sample_every hits this round): every push/pull the
        # step issues joins the round's cross-node trace
        with kv.trace_round(step):
            with m.phase("grad"):
                loss, acc, grads = grad_fn(params, x, y)
                g_leaves, _ = jax.tree_util.tree_flatten(grads)
                # block HERE so the phase split is honest: jax dispatch
                # is async, and without this the whole backward pass
                # would be billed to the push phase's first np.asarray
                # (the plain loop converts leaf-by-leaf right below
                # anyway, so this does not change the schedule; the
                # staged OVERLAP loop — overlap.py — is the path that
                # interleaves, not this one)
                jax.block_until_ready(g_leaves)
            with m.phase("push"):
                if kv.ts_push is not None:
                    # TS push direction: worker-to-worker merge tree; the
                    # elected holder pushes the merged set for the party
                    kv.ts_merge_push({tid: np.asarray(g) * scale
                                      for tid, g in enumerate(g_leaves)})
                    for tid in range(len(leaves)):
                        kv.pull(tid,
                                lambda t, arr: buf.__setitem__(t, arr),
                                priority=-tid)
                elif kv.config.enable_p3:
                    # P3: sliced push+pull, values ride the response
                    for tid, g in enumerate(g_leaves):
                        kv.push_pull(tid, np.asarray(g) * scale,
                                     lambda t, arr: buf.__setitem__(t, arr),
                                     priority=-tid)
                else:
                    for tid, g in enumerate(g_leaves):
                        kv.push(tid, np.asarray(g) * scale, priority=-tid)
                    for tid in range(len(leaves)):
                        kv.pull(tid,
                                lambda t, arr: buf.__setitem__(t, arr),
                                priority=-tid)
            with m.phase("pull_wait"):
                kv.wait_all()
        params = unflatten_params(treedef, buf)  # type: ignore[arg-type]
        m.step_end()
        history.append((float(loss), float(acc)))
        if log_fn is not None:
            log_fn(step, float(loss), float(acc))
    if params_out is not None:
        params_out["params"] = params
    return history
