from geomx_tpu.parallel.mesh import make_mesh, named_sharding  # noqa: F401
from geomx_tpu.parallel.quantized_allreduce import (  # noqa: F401
    make_party_step_quantized, quantized_psum_mean)
from geomx_tpu.parallel.moe import (  # noqa: F401
    expert_capacity, moe_ffn_topk, topk_dispatch_combine)
from geomx_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from geomx_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
