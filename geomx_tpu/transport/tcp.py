"""TCP fabric: real sockets for multi-process / multi-host deployment.

The reference's transport is ZeroMQ ROUTER/DEALER TCP plus raw UDP
(ref: 3rdparty/ps-lite/src/zmq_van.h:41-193); this fabric provides the
same role with plain sockets and the framework's binary message format
(Message.to_bytes / from_bytes — length-prefixed frames).  It implements
the InProcFabric interface (register → mailbox, deliver), so the Van and
everything above it is transport-agnostic.

Addressing is static: every node gets ``base_port + index`` within the
deterministic ``Topology.all_nodes()`` order on its host (127.0.0.1 for
pseudo-distributed runs, per-node hosts via GEOMX_NODE_HOSTS JSON for
multi-host).  The reference's dynamic ADD_NODE registration is replaced
by this static plan; elastic join/recovery rides the heartbeat layer.
"""

from __future__ import annotations

import errno
import json
import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from geomx_tpu.core.config import Config, NodeId, Topology
from geomx_tpu.transport import message as _message
from geomx_tpu.transport.message import (Control, Domain, Message,
                                         WireCorruption)
from geomx_tpu.transport.reactor import Reactor, resolve_transport
from geomx_tpu.transport.van import FaultPolicy, _Mailbox, corrupt_bytes


class _RecvConn:
    """Reactor-mode inbound connection: a readiness-driven scatter-gather
    ``recv_into`` state machine (8-byte length header, then the frame
    body into ONE writeable bytearray) replacing the per-connection recv
    thread.  The completed buffer goes straight to
    ``Message.from_bytes`` — zero-copy views over the receive buffer,
    exactly the wire-v2 contract the thread path honors."""

    __slots__ = ("fabric", "sock", "box", "node_s", "_hdr", "_hdr_view",
                 "_hdr_got", "_buf", "_view", "_got", "_need", "_reg")

    def __init__(self, fabric: "TcpFabric", sock: socket.socket,
                 box: _Mailbox, node_s: str = ""):
        self.fabric = fabric
        self.sock = sock
        self.box = box
        self.node_s = node_s
        self._hdr = bytearray(8)
        self._hdr_view = memoryview(self._hdr)
        self._hdr_got = 0
        self._buf: Optional[bytearray] = None
        self._view: Optional[memoryview] = None
        self._got = 0
        self._need = 0
        sock.setblocking(False)
        self._reg = fabric.reactor.register(sock, read_cb=self._on_readable)

    def _on_readable(self):
        try:
            while True:
                if self._buf is None:
                    n = self.sock.recv_into(self._hdr_view[self._hdr_got:],
                                            8 - self._hdr_got)
                    if n == 0:
                        self.close()
                        return
                    self._hdr_got += n
                    if self._hdr_got < 8:
                        continue
                    (need,) = struct.unpack("<q", self._hdr)
                    self._hdr_got = 0
                    if need <= 0:
                        continue  # defensive: empty frame
                    self._buf = bytearray(need)
                    self._view = memoryview(self._buf)
                    self._got = 0
                    self._need = need
                else:
                    n = self.sock.recv_into(self._view[self._got:],
                                            self._need - self._got)
                    if n == 0:
                        self.close()
                        return
                    self._got += n
                    if self._got < self._need:
                        continue
                    buf = self._buf
                    self._buf = self._view = None
                    # the frame buffer is a WRITEABLE bytearray this
                    # state machine never touches again: from_bytes
                    # returns zero-copy np.frombuffer views over it and
                    # the ``donated`` contract lets servers adopt them
                    try:
                        self.box.put(Message.from_bytes(buf))
                    except WireCorruption as e:
                        # checksum verdict on a complete frame: the
                        # length-prefix framing is INTACT, so the stream
                        # stays up — reject the frame, NACK the sender
                        self.fabric._on_corrupt_frame(self.node_s, e)
                    except Exception:
                        # a malformed frame poisons the stream framing —
                        # drop the connection like the thread path does
                        # when the decode raises out of its loop
                        import logging

                        logging.getLogger(__name__).exception(
                            "reactor recv: frame decode failed")
                        self.close()
                        return
        except (BlockingIOError, InterruptedError):
            return  # drained: wait for the next readiness event
        except OSError:
            self.close()

    def close(self):
        self._reg.close()
        with self.fabric._registry_mu:
            try:
                self.fabric._accepted.remove(self)
            except ValueError:
                pass


class _SendConn:
    """Reactor-mode outbound connection: non-blocking sends with a
    per-connection write queue drained on write readiness.  The caller
    tries an optimistic ``sendmsg`` first (the common, uncongested
    case costs no loop round-trip); leftovers queue and arm write
    interest.  Backpressure: a sender whose queue passes the high
    watermark BLOCKS until the loop drains it below — the same
    flow-control a blocking socket applied, without a thread per
    connection."""

    HIGH_WATER = int(os.environ.get("GEOMX_REACTOR_SENDQ_MAX",
                                    str(64 << 20)))
    _IOV = 64  # buffers per sendmsg call (stays far under IOV_MAX)

    __slots__ = ("sock", "broken", "_mu", "_cv", "_bufs", "_queued",
                 "_reg")

    def __init__(self, sock: socket.socket, reactor: Reactor):
        self.sock = sock
        self.broken = False
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._bufs: list = []
        self._queued = 0
        sock.setblocking(False)
        self._reg = reactor.register(sock, read_cb=self._on_readable,
                                     write_cb=self._on_writable)

    # outgoing conns receive nothing: readable means peer EOF/reset
    def _on_readable(self):
        try:
            data = self.sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            self._break_locked_notify()

    def _break_locked_notify(self):
        with self._cv:
            self.broken = True
            self._bufs.clear()
            self._queued = 0
            self._cv.notify_all()
        self._reg.close()

    @staticmethod
    def _advance(bufs: list, sent: int) -> None:
        while sent > 0 and bufs:
            n = bufs[0].nbytes
            if sent >= n:
                sent -= n
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0

    def send(self, frames) -> None:
        """Queue one message's frames atomically (whole-frame-list
        append under the lock keeps concurrent senders' messages from
        interleaving).  Raises OSError when the connection is broken —
        the fabric's redial-once path takes over."""
        bufs = [memoryview(f).cast("B") for f in frames]
        with self._cv:
            if self.broken:
                raise OSError(errno.EPIPE, "reactor send conn broken")
            if not self._bufs:
                # optimistic fast path: push what the kernel will take
                try:
                    while bufs:
                        sent = self.sock.sendmsg(bufs[:self._IOV])
                        self._advance(bufs, sent)
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    self.broken = True
                    self._reg.close()
                    raise
            if bufs:
                self._queued += sum(b.nbytes for b in bufs)
                self._bufs.extend(bufs)
                self._reg.want_write(True)
                while self._queued > self.HIGH_WATER and not self.broken:
                    self._cv.wait(timeout=1.0)  # backpressure
                if self.broken:
                    raise OSError(errno.EPIPE, "reactor send conn broke "
                                               "under backpressure")

    def _on_writable(self):
        with self._cv:
            try:
                while self._bufs:
                    sent = self.sock.sendmsg(self._bufs[:self._IOV])
                    self._queued -= sent
                    self._advance(self._bufs, sent)
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                # broken mid-drain: the queued tail dies with the
                # stream (the resend layer recovers reliable traffic)
                self.broken = True
                self._bufs.clear()
                self._queued = 0
                self._cv.notify_all()
                self._reg.close()
                return
            if not self._bufs:
                self._reg.want_write(False)
            self._cv.notify_all()

    def close(self):
        with self._cv:
            self.broken = True
            self._bufs.clear()
            self._queued = 0
            self._cv.notify_all()
        self._reg.close()


def default_address_plan(topology: Topology, base_port: int = 9200,
                         hosts: Optional[Dict[str, str]] = None
                         ) -> Dict[str, Tuple[str, int]]:
    """node-str → (host, port).  Hosts default to loopback (the reference's
    pseudo-distributed mode, ref: docs/source/pseudo-distributed-deployment.rst);
    ``hosts`` overrides per node for multi-host."""
    hosts = hosts or {}
    plan = {}
    for i, n in enumerate(topology.all_nodes()):
        s = str(n)
        plan[s] = (hosts.get(s, "127.0.0.1"), base_port + i)
    return plan


def plan_from_env(topology: Topology) -> Dict[str, Tuple[str, int]]:
    base = int(os.environ.get("GEOMX_BASE_PORT", "9200"))
    hosts = json.loads(os.environ.get("GEOMX_NODE_HOSTS", "{}"))
    return default_address_plan(topology, base, hosts)


class TcpFabric:
    """One per process. Only the local node(s) register; deliver() dials
    the static plan.

    DGT's lossy channels (``msg.channel >= 1``) travel as real **UDP
    datagrams** to the peer's port (the reference's raw UDP sockets with
    DSCP/TOS marks, ref: zmq_van.h:95-193): no connection, no
    retransmission, genuinely lossy — a dropped datagram is simply a
    zero-filled chunk at the reassembler.  Each lossy channel sends from
    its own TOS-marked socket (descending priority, ref: the tos ladder
    in zmq_van.h); oversized payloads fall back to the reliable TCP conn
    (the reference sizes DGT blocks for UDP, kv_app.h:841-850 — the
    fallback keeps misconfigured block sizes correct, just not lossy).
    """

    UDP_MAX = 60_000  # payloads above this ride TCP (IP fragmentation
    #                   would turn one lost fragment into a lost chunk
    #                   anyway; 60k stays under the 64k datagram limit)

    # descending DSCP ladder for channels 1..n (ref: zmq_van.h TOS marks)
    _TOS = (0x88, 0x68, 0x48, 0x28)

    def __init__(self, plan: Dict[str, Tuple[str, int]],
                 fault: Optional[FaultPolicy] = None,
                 config: Optional[Config] = None):
        if fault is None:
            fault = FaultPolicy.from_config(config) if config else FaultPolicy()
        self.fault = fault
        self.plan = plan
        # event-driven mode (GEOMX_TRANSPORT=reactor / Config.transport):
        # every endpoint in the process is serviced by the shared
        # per-process Reactor — non-blocking accept, readiness-driven
        # recv state machines, write queues — instead of accept/recv
        # threads per listener/connection.  "threads" (default) keeps
        # the pre-reactor path bit-for-bit.
        self.mode = resolve_transport(config)
        self.reactor = Reactor.shared() if self.mode == "reactor" else None
        self._reactor_regs: list = []  # listener/udp registrations
        self._boxes: Dict[str, _Mailbox] = {}
        self._listeners = []
        self._conns: Dict[str, socket.socket] = {}
        # per-destination locks: one slow/unreachable peer must not stall
        # sends to every other peer (heartbeats would time out and trigger
        # false dead-node detection)
        self._conn_mus: Dict[str, threading.Lock] = {}
        self._registry_mu = threading.Lock()
        self._accepted: list = []
        self._established: set = set()
        self._dial_window: Dict[str, float] = {}
        self._udp_send: Dict[int, socket.socket] = {}  # channel -> socket
        self._udp_recv: list = []
        self._stop = False
        self.dropped = 0
        self.udp_datagrams_sent = 0
        self.udp_datagrams_recv = 0
        self.udp_dropped = 0  # lossy-channel losses only (injected or
        #                       sendto failures), distinct from `dropped`
        #                       which also counts reliable-channel
        #                       drop injection
        # system-metrics mirrors of the two loss ledgers, named by the
        # first registered node (one process = one fabric): transport
        # loss shows up in utils.metrics.system_snapshot next to the
        # failover / replication / eviction counters
        self._sys_dropped = None
        self._sys_udp_dropped = None
        # data-integrity ledger: frames a receiver's checksum rejected
        # (per-node counters live in the metrics registry)
        self.corrupt_rejected = 0
        self._integrity_counters: Dict[str, object] = {}

    def _count_integrity_reject(self, node_s: str):
        with self._registry_mu:
            self.corrupt_rejected += 1
        if not node_s:
            return
        c = self._integrity_counters.get(node_s)
        if c is None:
            from geomx_tpu.utils.metrics import system_counter

            c = self._integrity_counters.setdefault(
                node_s, system_counter(f"{node_s}.integrity_wire_rejects"))
            # first reject for this receiver only — the counter carries
            # the volume, the log line is the operator breadcrumb
            print(f"{node_s}: wire checksum rejected a corrupt frame "
                  "(counted in integrity_wire_rejects)", flush=True)
        c.inc()

    def _on_corrupt_frame(self, node_s: str, err: WireCorruption):
        """A complete TCP frame failed its checksum.  Count the reject,
        then NACK the sender (when the verified meta named one) so its
        resender retransmits NOW instead of waiting out the backoff.
        The NACK is sent from a short-lived thread: deliver() may dial
        a cold connection, and neither the reactor loop nor a recv
        thread may block on that."""
        self._count_integrity_reject(node_s)
        if not err.sender or err.msg_sig < 0 or err.channel != 0:
            return  # no trustworthy sender identity, or a lossy channel
        nack = Message(sender=node_s, recipient=err.sender,
                       control=Control.NACK,
                       domain=err.domain or Domain.LOCAL,
                       msg_sig=err.msg_sig, boot=err.boot)

        def _send():
            try:
                self.deliver(nack)
            except (KeyError, OSError):
                pass  # sender unreachable: its resend timer recovers

        threading.Thread(target=_send, daemon=True,
                         name=f"tcp-nack-{node_s}").start()

    def _count_drop(self, udp: bool = False):
        """Ledger a lost message (caller holds ``_registry_mu``)."""
        self.dropped += 1
        if self._sys_dropped is not None:
            self._sys_dropped.inc()
        if udp:
            self.udp_dropped += 1
            if self._sys_udp_dropped is not None:
                self._sys_udp_dropped.inc()

    # ---- local side ---------------------------------------------------------
    def register(self, node: NodeId) -> _Mailbox:
        s = str(node)
        if s in self._boxes:
            return self._boxes[s]
        box = _Mailbox()
        host, port = self.plan[s]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # a restarted role re-binds its fixed port; sockets lingering from
        # the previous incarnation can hold it for a moment — retry, but
        # only on EADDRINUSE (anything else is a real config error).
        # NOTE: deliberately no SO_REUSEPORT — it would let two live
        # incarnations share the port and silently split inbound traffic.
        deadline = time.monotonic() + 5.0
        try:
            while True:
                try:
                    srv.bind(("0.0.0.0", port))
                    break
                except OSError as e:
                    if (e.errno != errno.EADDRINUSE
                            or time.monotonic() >= deadline):
                        raise
                    time.sleep(0.1)
            srv.listen(64)
        except OSError:
            srv.close()  # a retried register() must not find a dead box
            raise
        # UDP receiver on the same port number for DGT's lossy channels.
        # Bound BEFORE the box/threads are installed so a bind failure
        # leaves no half-registered node (a retried register() finding a
        # mailbox with no UDP receiver would silently zero-fill every
        # lossy chunk forever).  Deliberately no SO_REUSEADDR: UDP has no
        # TIME_WAIT to work around, and on Linux it would let two live
        # incarnations share the port and split inbound datagrams.
        udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            deadline = time.monotonic() + 5.0
            while True:
                try:
                    udp.bind(("0.0.0.0", port))
                    break
                except OSError as e:
                    if (e.errno != errno.EADDRINUSE
                            or time.monotonic() >= deadline):
                        raise
                    time.sleep(0.1)
        except OSError:
            udp.close()
            srv.close()
            raise
        self._boxes[s] = box
        if self._sys_dropped is None:
            from geomx_tpu.utils.metrics import system_counter

            self._sys_dropped = system_counter(f"{s}.tcp_dropped")
            self._sys_udp_dropped = system_counter(f"{s}.tcp_udp_dropped")
        self._listeners.append(srv)
        self._udp_recv.append(udp)
        if self.reactor is not None:
            # reactor mode: no accept thread, no UDP thread, no thread
            # per accepted connection — the shared loops service all of
            # them via readiness callbacks
            srv.setblocking(False)
            udp.setblocking(False)
            self._reactor_regs.append(self.reactor.register(
                srv, read_cb=lambda: self._accept_ready(srv, box, s)))
            self._reactor_regs.append(self.reactor.register(
                udp, read_cb=lambda: self._udp_ready(udp, box, s)))
        else:
            threading.Thread(target=self._accept_loop, args=(srv, box, s),
                             name=f"tcp-accept-{s}", daemon=True).start()
            threading.Thread(target=self._udp_recv_loop,
                             args=(udp, box, s),
                             name=f"udp-recv-{s}", daemon=True).start()
        return box

    # ---- reactor-mode readiness callbacks -----------------------------------
    def _accept_ready(self, srv: socket.socket, box: _Mailbox,
                      node_s: str):
        while not self._stop:
            try:
                conn, _ = srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            rc = _RecvConn(self, conn, box, node_s)
            with self._registry_mu:
                self._accepted.append(rc)

    def _udp_ready(self, sock: socket.socket, box: _Mailbox,
                   node_s: str):
        while not self._stop:
            try:
                data, _ = sock.recvfrom(65535)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if not data:
                continue  # shutdown poke
            try:
                msg = Message.from_bytes(data)
            except WireCorruption:
                # checksum verdict on a lossy datagram: counted but
                # never NACKed — DGT chunks are never retransmitted, and
                # the reassembler zero-fills the hole by design
                self._count_integrity_reject(node_s)
                continue
            except Exception:
                continue  # truncated/corrupt datagram: lossy by design
            with self._registry_mu:
                self.udp_datagrams_recv += 1
            box.put(msg)

    def _udp_recv_loop(self, sock: socket.socket, box: _Mailbox,
                       node_s: str):
        while not self._stop:
            try:
                data, _ = sock.recvfrom(65535)
            except OSError:
                return
            try:
                msg = Message.from_bytes(data)
            except WireCorruption:
                self._count_integrity_reject(node_s)  # see _udp_ready
                continue
            except Exception:
                continue  # truncated/corrupt datagram: lossy by design
            with self._registry_mu:
                self.udp_datagrams_recv += 1
            box.put(msg)

    def _udp_sock(self, channel: int) -> socket.socket:
        with self._registry_mu:
            if self._stop:  # lost the race against shutdown()
                raise OSError(errno.ESHUTDOWN, "fabric shut down")
            s = self._udp_send.get(channel)
            if s is None:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                tos = self._TOS[min(channel - 1, len(self._TOS) - 1)]
                try:
                    s.setsockopt(socket.IPPROTO_IP, socket.IP_TOS, tos)
                except OSError:
                    pass  # TOS is advisory; some sandboxes deny it
                self._udp_send[channel] = s
            return s

    def _accept_loop(self, srv: socket.socket, box: _Mailbox,
                     node_s: str):
        while not self._stop:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=self._recv_loop,
                             args=(conn, box, node_s),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket, box: _Mailbox,
                   node_s: str = ""):
        with self._registry_mu:
            self._accepted.append(conn)
        try:
            while not self._stop:
                hdr = self._recv_exact(conn, 8)
                if hdr is None:
                    return
                (n,) = struct.unpack("<q", bytes(hdr))
                data = self._recv_exact(conn, n)
                if data is None:
                    return
                # the frame buffer is a WRITEABLE bytearray this loop
                # never touches again: from_bytes returns zero-copy
                # np.frombuffer views over it, and the message's
                # ``donated`` contract lets the server adopt them as
                # its accumulators without a defensive copy
                try:
                    box.put(Message.from_bytes(data))
                except WireCorruption as e:
                    # complete frame, bad checksum: framing is intact,
                    # the stream survives — reject + NACK the sender
                    self._on_corrupt_frame(node_s, e)
        except OSError:
            return  # connection torn down (peer reset or fabric shutdown)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._registry_mu:
                try:
                    self._accepted.remove(conn)
                except ValueError:
                    pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytearray]:
        """Read exactly ``n`` bytes into a fresh writeable buffer via
        recv_into — no per-chunk bytes objects, no quadratic b"" +=
        reassembly, and the result can back zero-copy array views."""
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = conn.recv_into(view[got:], n - got)
            if not r:
                return None
            got += r
        return buf

    # ---- send side ----------------------------------------------------------
    def deliver(self, msg: Message, _dup_ok: bool = True) -> bool:
        if self.fault.should_drop(msg):
            with self._registry_mu:
                # separate ledger: DGT acceptance metrics must not
                # conflate lossy-channel loss with reliable-channel drop
                # injection — and only count it as UDP loss if the
                # message would actually have ridden the UDP path
                # (remote destination, datagram-sized)
                # nbytes underestimates the serialized frame (headers /
                # keys / lens); leave margin so a message the real path
                # would have sent over TCP isn't ledgered as UDP loss
                self._count_drop(udp=(
                    msg.channel >= 1
                    and str(msg.recipient) not in self._boxes
                    and msg.nbytes <= self.UDP_MAX - 4096))
            return False
        if _dup_ok and self.fault.should_duplicate(msg):
            # at-least-once injection (mirrors InProcFabric): a copy of
            # the frame goes out ahead of the original
            import copy

            self.deliver(copy.copy(msg), _dup_ok=False)
        dest = str(msg.recipient)
        box = self._boxes.get(dest)
        if box is not None:  # local shortcut (several roles per process)
            box.put(msg)
            return True
        if dest not in self.plan:
            raise KeyError(f"no mailbox for {msg.recipient}")
        # scatter-gather: the payload arrays go onto the socket as their
        # own iovecs — no getvalue()/concat copy of a multi-hundred-MB
        # frame anywhere on the send path (the length prefix and prelude
        # share the first small buffer)
        if _message.WIRE_V2:
            frames = msg.to_frames()
            total = sum(memoryview(f).nbytes for f in frames)
        else:  # v1-pinned encoder (GEOMX_WIRE_FORMAT=v1)
            frames = [msg.to_bytes()]
            total = len(frames[0])
        roll = self.fault.corruption_roll(msg)
        if roll is not None:
            # seeded in-flight damage: flatten the scatter-gather list
            # and corrupt the serialized frame — what a flipped bit on
            # the physical WAN does; the receiver's checksum (or lack of
            # one) decides what happens next
            mode, rng = roll
            data = corrupt_bytes(b"".join(bytes(f) for f in frames),
                                 rng, mode)
            frames = [data]
            total = len(data)
        if msg.channel >= 1 and total <= self.UDP_MAX:
            # lossy DGT channel: one best-effort datagram, no dial, no
            # retransmit; send failures are losses by design
            data = b"".join(bytes(f) for f in frames)
            host, port = self.plan[dest]
            try:
                self._udp_sock(msg.channel).sendto(data, (host, port))
            except OSError:
                with self._registry_mu:
                    self._count_drop(udp=True)
                return False
            with self._registry_mu:
                self.udp_datagrams_sent += 1
            return True
        frames.insert(0, struct.pack("<q", total))
        with self._registry_mu:
            mu = self._conn_mus.setdefault(dest, threading.Lock())
        with mu:
            conn = self._conns.get(dest)
            if conn is None or getattr(conn, "broken", False):
                if conn is not None:  # async write failure marked it
                    conn.close()
                    self._conns.pop(dest, None)
                conn = self._dial(dest)
            try:
                self._send_on(conn, frames)
            except OSError:
                # peer restarted: redial once; drop the dead socket from
                # the registry first so a failed redial doesn't leave it
                # there for every later send to trip over.  Resending
                # from frame 0 on the FRESH stream is safe — the broken
                # connection dies with whatever partial frame it carried
                conn.close()
                self._conns.pop(dest, None)
                conn = self._dial(dest)
                self._send_on(conn, frames)
        return True

    def _send_on(self, conn, frames) -> None:
        """One message onto ``conn`` — blocking ``sendmsg`` loop on the
        thread path, write-queue submit (with backpressure) on a
        reactor ``_SendConn``."""
        if isinstance(conn, _SendConn):
            conn.send(frames)
        else:
            self._sendmsg_all(conn, frames)

    @staticmethod
    def _sendmsg_all(conn: socket.socket, frames) -> None:
        """sendall for a buffer list: one sendmsg gathers every iovec;
        short writes advance into the list without copying."""
        bufs = [memoryview(f).cast("B") for f in frames]
        while bufs:
            sent = conn.sendmsg(bufs)
            while sent > 0 and bufs:
                n = bufs[0].nbytes
                if sent >= n:
                    sent -= n
                    bufs.pop(0)
                else:
                    bufs[0] = bufs[0][sent:]
                    sent = 0

    # connect errors worth waiting out during bring-up; anything else
    # (DNS failure, ENETUNREACH, …) is a config error and raises at once
    _TRANSIENT_ERRNOS = frozenset({errno.ECONNREFUSED, errno.ECONNRESET,
                                   errno.ECONNABORTED, errno.ETIMEDOUT})

    def _dial(self, dest: str, retry_for: float = 30.0) -> socket.socket:
        """Connect to a peer, retrying while its listener comes up.

        Roles start as independent processes in arbitrary order (the
        reference's ZMQ sockets reconnect transparently); a connection
        refused during the bring-up window must retry, not drop — a lost
        control command (e.g. set_optimizer) would hang the caller.

        The retry window opens at the FIRST dial attempt to a peer and is
        never re-armed: once the peer has been reached — or the window
        has expired without contact — later dial failures fail fast, so
        the (serial) heartbeat and resend loops are not head-of-line
        blocked behind a dead destination.  Redelivery to a restarted
        peer is the resend layer's job."""
        host, port = self.plan[dest]
        with self._registry_mu:
            if dest in self._established:
                deadline = 0.0
            else:
                opened = self._dial_window.setdefault(dest, time.monotonic())
                deadline = opened + retry_for
        while True:
            try:
                conn = socket.create_connection((host, port), timeout=5)
                break
            except OSError as e:
                # connect timeouts surface as TimeoutError with errno None
                transient = (isinstance(e, TimeoutError)
                             or e.errno in self._TRANSIENT_ERRNOS)
                if (self._stop or not transient
                        or time.monotonic() >= deadline):
                    raise
                time.sleep(0.1)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.reactor is not None:
            # wrap in the write-queue state machine; the reactor's loop
            # drains it on write readiness — no send thread, no redials
            # hidden inside the loop
            conn = _SendConn(conn, self.reactor)
        with self._registry_mu:
            if self._stop:  # lost the race against shutdown()
                conn.close()
                raise OSError(errno.ESHUTDOWN, "fabric shut down")
            self._conns[dest] = conn
            self._established.add(dest)
        return conn

    def add_address(self, node: str, addr: Tuple[str, int]) -> None:
        """Explicitly register an OUT-OF-PLAN peer (a dynamically joined
        worker, ref: ADD_NODE van.cc:41-112).  Distinct from
        ``update_address``, which deliberately ignores unknown nodes as
        stale broadcasts."""
        with self._registry_mu:
            if node not in self.plan:
                self.plan[node] = addr
        self.update_address(node, addr)

    def update_address(self, node: str, addr: Tuple[str, int]) -> None:
        """Re-point a peer's address (replacement node at a new
        host:port).  Drops any live connection to the old address and
        re-arms the bring-up dial window so the next send retries while
        the replacement finishes starting."""
        if node not in self.plan:
            return  # unknown node: a stale broadcast from another epoch
        with self._registry_mu:
            if self.plan[node] == addr:
                return
            self.plan[node] = addr
            conn = self._conns.pop(node, None)
            self._established.discard(node)
            self._dial_window.pop(node, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self):
        self._stop = True
        for srv in self._listeners:
            # close() alone does not release a listener whose accept() is
            # blocked in another thread — the kernel keeps the socket (and
            # the port) alive until accept returns; shutdown() wakes it
            try:
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                srv.close()
            except OSError:
                pass
        # wake UDP recv loops blocked in recvfrom: close() alone does not
        # release the port while the syscall holds the open file
        # description (the UDP analog of the listener-shutdown note
        # above; shutdown() on an unconnected UDP socket is ENOTCONN on
        # Linux, so poke it with a self-datagram instead)
        for sock in list(self._udp_recv):
            try:
                port = sock.getsockname()[1]
                sock.sendto(b"", ("127.0.0.1", port))
            except OSError:
                pass
        # reactor mode: unregister the listener/udp fds from the shared
        # loops (closing their sockets as a side effect — the reactor
        # itself is process-lifetime and keeps running for other users)
        for reg in self._reactor_regs:
            reg.close()
        self._reactor_regs.clear()
        # snapshot under the lock, close OUTSIDE it: a reactor
        # _RecvConn.close re-enters _registry_mu to delist itself
        with self._registry_mu:
            targets = (list(self._conns.values()) + list(self._accepted)
                       + list(self._udp_recv)
                       + list(self._udp_send.values()))
            self._conns.clear()
            self._accepted.clear()
            self._udp_recv.clear()
            self._udp_send.clear()
        for c in targets:
            try:
                c.close()
            except OSError:
                pass
