"""Device-resident WAN codecs (ISSUE 20): the JAX backend's codec stage.

Contracts pinned here:

- CROSS-DECODE PARITY: the numpy codecs are the bit-compat wire
  reference.  fp16 and 2bit device ENCODERS emit byte-identical frames
  for identical state; the BSC device encoder may pick a different
  (equally legal) support via exact top-k, but every legal frame —
  device- or numpy-encoded — reconstructs BITWISE identically under
  both families' decoders, f32 and f16-sourced, with integer-valued
  gradients surviving exactly where the codec is lossless on them;
- DONATION SAFETY: ``compress`` never donates the gradient input — it
  may alias an in-flight view (a pull response, a store snapshot), so
  its bits must be untouched after encode; only stage-private state
  (residuals, momentum) is donated;
- STEADY-STATE RESIDENCY: 5 training rounds under device codecs + the
  device optimizer move the LOCAL tier's ``d2h_bytes`` by exactly
  nothing and the codec stage's full-tensor host counter by exactly
  nothing — the only D2H is the wire-ready compressed payload
  (``codec_d2h_bytes``), and the GLOBAL tier re-stages nothing
  (``h2d_bytes`` flat: decoded grads land as device arrays);
- FUZZ: the PR 17 damage model (truncations, seeded bit flips) against
  the DEVICE decoders lands the same typed :class:`CodecError`, never
  an out-of-bounds scatter or a mis-shaped tensor;
- SELECTION: ``resolve_codec_device`` — default on under the jax
  backend, env/config off-switches honored, deterministic mode forces
  the numpy reference, numpy backend never offers the stage.

Runs on the virtual 8-device CPU mesh (conftest)."""

import numpy as np
import pytest

from geomx_tpu.compression import (BscCodec, Fp16Codec, MpqSelector,
                                   TwoBitCodec, decompress_payload)
from geomx_tpu.compression.codecs import CodecError, unpack_sparse
from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.backend import NumpyBackend, resolve_codec_device


def _cfg(**kw):
    return Config(topology=Topology(), **kw)


def _stage(**cfg_kw):
    from geomx_tpu.kvstore.jax_backend import JaxBackend

    cfg = _cfg(**cfg_kw)
    stage = JaxBackend(cfg).make_codec_stage(cfg)
    assert stage is not None
    return stage


def _grad(n=4096, seed=0, dtype=np.float32, integer=False):
    rng = np.random.default_rng(seed)
    if integer:
        return rng.integers(-8, 9, n).astype(dtype)
    return (rng.standard_normal(n) * 2.0).astype(dtype)


def _host(x):
    out = np.asarray(x)
    assert out.dtype == np.float32
    return out


# ---------------------------------------------------------------------------
# cross-decode bitwise parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("src_dtype", [np.float32, np.float16],
                         ids=["f32", "f16"])
def test_fp16_frames_byte_identical_and_cross_decode(src_dtype):
    """fp16 is stateless: device and numpy encoders must emit the SAME
    bytes (XLA's f32→f16 cast is the same round-to-nearest-even), and
    each frame decodes bitwise identically under both decoders."""
    stage = _stage()
    n = 4096
    g = _grad(n, seed=1, dtype=src_dtype).astype(np.float32)
    dev_frame = _stage().make_push_codec({"type": "fp16"}).compress(1, g)
    np_frame = Fp16Codec().compress(1, g.copy())
    assert np.asarray(dev_frame).tobytes() == np.asarray(np_frame).tobytes()
    ref = decompress_payload("fp16", 1, np.asarray(np_frame), n)
    for frame in (dev_frame, np_frame):
        out_dev = _host(stage.decode("fp16", 1, np.asarray(frame), n))
        out_np = decompress_payload("fp16", 1, np.asarray(frame), n)
        assert out_dev.tobytes() == ref.tobytes()
        assert out_np.tobytes() == ref.tobytes()


def test_2bit_frames_byte_identical_across_rounds():
    """2bit carries a per-key residual; feeding IDENTICAL gradients to
    both engines must produce byte-identical frames every round (the
    quantize decisions are exact f32 compares on IEEE-identical sums),
    and the cross-decode matrix stays bitwise-green per round."""
    stage = _stage()
    dev = stage.make_push_codec({"type": "2bit", "threshold": 0.5})
    ref = TwoBitCodec(threshold=0.5)
    n = 2048
    for r in range(4):
        g = _grad(n, seed=10 + r)
        dev_frame = np.asarray(dev.compress(7, g))
        np_frame = np.asarray(ref.compress(7, g.copy()))
        assert dev_frame.tobytes() == np_frame.tobytes(), f"round {r}"
        want = decompress_payload("2bit", 7, np_frame, n,
                                  threshold=0.5).tobytes()
        assert _host(stage.decode("2bit", 7, dev_frame, n,
                                  0.5)).tobytes() == want
        assert decompress_payload("2bit", 7, dev_frame, n,
                                  threshold=0.5).tobytes() == want


def test_2bit_integer_grads_are_exact():
    """Integer-valued gradients with an integer threshold: every
    emitted ±t is exact on both engines and the residuals stay
    integer-valued — the decoded tensors match bitwise AND equal the
    direct {−t,0,+t} quantization."""
    stage = _stage()
    dev = stage.make_push_codec({"type": "2bit", "threshold": 1.0})
    n = 512
    g = _grad(n, seed=3, integer=True)
    frame = np.asarray(dev.compress(2, g))
    out = _host(stage.decode("2bit", 2, frame, n, 1.0))
    want = np.where(g > 1.0, np.float32(1.0),
                    np.where(g < -1.0, np.float32(-1.0), np.float32(0.0)))
    assert out.tobytes() == want.tobytes()
    assert decompress_payload("2bit", 2, frame, n,
                              threshold=1.0).tobytes() == want.tobytes()


def test_bsc_cross_decode_bitwise_both_directions():
    """BSC frames are ``[f32 values ‖ int32 indices bit-cast to f32]``.
    The device encoder's exact top-k may pick a different support than
    the reference's sampled-threshold scan, so frames need not match —
    but EVERY legal frame must reconstruct bitwise identically under
    both decoders, and the transmitted values must be exact f32 bits
    of the accumulated mass (integer grads → integer values)."""
    stage = _stage()
    n = 4096
    g = _grad(n, seed=5, integer=True)
    dev = stage.make_push_codec(
        {"type": "bsc", "ratio": 0.05, "momentum": 0.0})
    ref = BscCodec(ratio=0.05, momentum=0.0, sample_rate=1.0, seed=0)
    for frame in (np.asarray(dev.compress(9, g)),
                  np.asarray(ref.compress(9, g.copy()))):
        out_dev = _host(stage.decode("bsc", 9, frame, n))
        out_np = decompress_payload("bsc", 9, frame, n)
        assert out_dev.tobytes() == out_np.tobytes()
        vals, idx = unpack_sparse(frame)
        # integer grads + momentum 0: the round's accumulated mass is
        # integer-exact, so every transmitted value is a whole number
        assert np.all(vals == np.round(vals))
        np.testing.assert_array_equal(out_np[idx], vals)


def test_mpq_selector_is_isinstance_compatible_and_splits():
    """The device MPQ subclasses the numpy selector (the server's
    isinstance dispatch and QUERY_STATS counters must keep working)
    and swaps both rungs for device implementations."""
    from geomx_tpu.kvstore.jax_backend import (DeviceBscCodec,
                                               DeviceFp16Codec,
                                               DeviceMpqSelector)

    sel = _stage().make_push_codec({"type": "mpq", "size_bound": 100})
    assert isinstance(sel, DeviceMpqSelector)
    assert isinstance(sel, MpqSelector)
    assert isinstance(sel.select(50), DeviceFp16Codec)
    assert isinstance(sel.select(100), DeviceBscCodec)


def test_make_push_codec_parity_with_reference_factory():
    stage = _stage()
    assert stage.make_push_codec({"type": "none"}) is None
    with pytest.raises(ValueError):
        stage.make_push_codec({"type": "zstd"})


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("body", [
    {"type": "fp16"},
    {"type": "2bit", "threshold": 0.5},
    {"type": "bsc", "ratio": 0.05, "momentum": 0.9},
], ids=lambda b: b["type"])
def test_compress_never_corrupts_aliased_device_input(body):
    """The gradient handed to ``compress`` may alias an in-flight view
    (a pull response being serialized, a white-box snapshot).  The jit
    kernels donate only stage-private state — after two encodes (the
    second reusing donated residual buffers) the input's bits must be
    untouched."""
    import jax.numpy as jnp

    stage = _stage()
    codec = stage.make_push_codec(body)
    g = jnp.asarray(_grad(2048, seed=8))
    before = np.asarray(g).tobytes()
    codec.compress(4, g)
    codec.compress(4, g)  # residual/velocity now donated buffers
    assert np.asarray(g).tobytes() == before, (
        f"{body['type']}: encode mutated an aliased input")


# ---------------------------------------------------------------------------
# steady-state residency: the geo-round never touches host numpy
# ---------------------------------------------------------------------------

def test_steady_state_rounds_zero_host_copies(monkeypatch):
    """THE acceptance assertion: 5 compressed training rounds under
    device codecs + device optimizer pay ZERO merge-plane D2H on the
    local tier and ZERO re-staging H2D on the global tier — the only
    device→host traffic in the codec stage is the wire-ready
    compressed payload, billed to ``codec_d2h_bytes``, and the global
    tier's D2H is exactly the per-round weight serve (pulls), nothing
    else."""
    monkeypatch.setenv("GEOMX_MERGE_BACKEND", "jax")
    monkeypatch.setenv("GEOMX_CODEC_DEVICE", "1")
    n = 20000
    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=1)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(n, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.05})
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression(
                {"type": "bsc", "ratio": 0.05})

        def one_round():
            g = np.ones(n, np.float32)
            for w in ws:
                w.push(0, g)
            return [w.pull_sync(0) for w in ws]

        one_round()  # warmup: jit compile + first-touch residency

        def counters(servers):
            return [(be.d2h_bytes, be.h2d_bytes, be.codec_host_bytes,
                     be.codec_d2h_bytes)
                    for be in (s._backend for s in servers)]

        loc0 = counters(sim.local_servers)
        glob0 = counters(sim.global_servers)
        for _ in range(5):
            one_round()
        # k = ratio*n per key per round: [vals ‖ idx] = 2k f32
        wire = 5 * 2 * max(1, int(0.05 * n)) * 4
        for (d0, h0, c0, w0), (d1, h1, c1, w1) in zip(
                loc0, counters(sim.local_servers)):
            assert d1 - d0 == 0, f"local merge plane paid D2H: {d1 - d0}"
            assert c1 - c0 == 0, f"full-tensor host copy in codec: {c1 - c0}"
            assert w1 - w0 == wire, (w1 - w0, wire)
            # worker pushes arrive as host frames: staging them is the
            # one H2D the local tier legitimately pays
            assert h1 - h0 == 5 * n * 4
        for (d0, h0, c0, _), (d1, h1, c1, _) in zip(
                glob0, counters(sim.global_servers)):
            assert h1 - h0 == 0, f"global tier re-staged grads: {h1 - h0}"
            assert c1 - c0 == 0
            # each round's pull is ONE weight materialization, no more
            assert d1 - d0 == 5 * n * 4, (d1 - d0, 5 * n * 4)
        # and the replicas actually trained
        outs = one_round()
        assert outs[0].mean() < -0.05
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# fuzz: the PR 17 damage model against the device decoders
# ---------------------------------------------------------------------------

def _fuzz_decode(decode, orig_len):
    """Same oracle as tests/test_integrity.py: a (possibly damaged)
    payload may only land a typed CodecError or a right-shaped f32
    tensor — struct.error / IndexError / OOB scatter / short arrays
    are the bug class this exists to catch."""
    try:
        out = decode()
    except CodecError:
        return "typed-reject"
    out = np.asarray(out)
    assert out.shape == (orig_len,), f"wrong shape {out.shape}"
    assert out.dtype == np.float32
    return "decoded"


@pytest.mark.parametrize("codec_name", ["bsc", "fp16", "2bit"])
def test_device_decoder_fuzz_truncate_bitflip(codec_name):
    rng = np.random.default_rng(abs(hash("dev" + codec_name)) % (2 ** 32))
    n = 4096
    grad = rng.standard_normal(n).astype(np.float32) * 2.0
    stage = _stage()
    body = {"bsc": {"type": "bsc", "ratio": 0.05},
            "fp16": {"type": "fp16"},
            "2bit": {"type": "2bit", "threshold": 0.5}}[codec_name]
    codec = stage.make_push_codec(body)
    payload = np.asarray(codec.compress(1, grad))
    tag = codec.name

    # clean roundtrip: deterministic, right-shaped, device-resident
    out1 = _host(stage.decode(tag, 1, payload, n))
    out2 = _host(stage.decode(tag, 1, payload.copy(), n))
    assert out1.shape == (n,)
    assert out1.tobytes() == out2.tobytes()

    raw = payload.tobytes()
    item = payload.dtype.itemsize

    def decode_bytes(b):
        arr = (np.frombuffer(b, dtype=payload.dtype)
               if len(b) % item == 0
               else np.frombuffer(b, dtype=np.uint8))
        return stage.decode(tag, 1, arr, n)

    # truncations: every cut point is a typed reject or right-shaped
    rejects = 0
    for cut in rng.choice(max(1, len(raw) - 1), size=48, replace=False):
        rejects += _fuzz_decode(
            lambda: decode_bytes(raw[:int(cut)]), n) == "typed-reject"
    assert rejects > 0, "no truncation was ever rejected"

    # seeded bit flips: never crash, never mis-shape, never OOB-scatter
    for _ in range(96):
        dam = bytearray(raw)
        pos = int(rng.integers(len(dam) * 8))
        dam[pos // 8] ^= 1 << (pos % 8)
        _fuzz_decode(lambda: decode_bytes(bytes(dam)), n)


def test_device_decoder_rejects_unknown_tag_and_bad_geometry():
    stage = _stage()
    with pytest.raises(CodecError, match="unknown"):
        stage.decode("zstd9", 1, np.ones(4, np.float32), 4)
    with pytest.raises(CodecError):
        stage.decode("fp16", 1, np.ones(3, np.float16), 4)  # short
    with pytest.raises(CodecError):
        stage.decode("2bit", 1, np.zeros(2, np.uint8), 64)  # short
    with pytest.raises(CodecError):  # odd sparse frame
        stage.decode("bsc", 1, np.ones(3, np.float32), 16)


def test_device_sparse_scatter_indices_are_fenced():
    """A flipped int32 index turns negative or huge; jax's scatter
    would silently DROP or WRAP it.  The device decode path runs the
    reference bounds gate BEFORE any device work."""
    from geomx_tpu.compression.codecs import pack_sparse

    stage = _stage()
    vals = np.array([1.0, 2.0], np.float32)
    for idx in ([-3, 0], [0, 10 ** 6]):
        payload = pack_sparse(vals, np.array(idx, np.int64))
        with pytest.raises(CodecError, match="index"):
            stage.decode("bsc", 5, payload, 16)


# ---------------------------------------------------------------------------
# selection rules
# ---------------------------------------------------------------------------

def test_codec_stage_selection_rules(monkeypatch):
    from geomx_tpu.kvstore.jax_backend import JaxBackend

    monkeypatch.delenv("GEOMX_CODEC_DEVICE", raising=False)
    cfg = _cfg()
    assert resolve_codec_device(cfg) is True
    assert JaxBackend(cfg).make_codec_stage(cfg) is not None
    # deterministic mode forces the numpy reference (replayable wires)
    det = _cfg(deterministic=True)
    assert resolve_codec_device(det) is False
    assert JaxBackend(det).make_codec_stage(det) is None
    # config field off wins without the env
    off = _cfg(codec_device=False)
    assert resolve_codec_device(off) is False
    # env off-switch for directly-constructed configs
    monkeypatch.setenv("GEOMX_CODEC_DEVICE", "0")
    assert resolve_codec_device(_cfg()) is False
    monkeypatch.setenv("GEOMX_CODEC_DEVICE", "1")
    assert resolve_codec_device(_cfg()) is True
    # the numpy backend never offers the stage
    assert NumpyBackend(_cfg()).make_codec_stage(_cfg()) is None
