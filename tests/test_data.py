"""IO/data subsystem tests: record-IO round-trips (native scan vs python
scan), format iterators (record/MNIST-idx/CSV/libsvm), sharding
completeness, augmentation, prefetch (ref strategy: src/io/ iterators +
dmlc recordio; per-worker sharding as in examples/cnn.py:49)."""

import struct

import numpy as np
import pytest

from geomx_tpu.data import (AugmentIter, CSVIter, LibSVMIter, MNISTIter,
                            PrefetchIter, RecordDatasetIter, RecordReader,
                            RecordWriter, pack_array, unpack_array,
                            write_array_dataset)
from geomx_tpu.data.recordio import _index_python


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    payloads = [b"x", b"hello", b"", b"0123456789" * 100]
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    r = RecordReader(path)
    assert len(r) == len(payloads)
    assert [r.read(i) for i in range(len(r))] == payloads
    assert list(r) == payloads


def test_recordio_native_matches_python(tmp_path):
    from geomx_tpu.native import bindings

    path = str(tmp_path / "b.rec")
    rng = np.random.default_rng(0)
    with RecordWriter(path) as w:
        for _ in range(50):
            w.write(rng.bytes(int(rng.integers(0, 200))))
    buf = open(path, "rb").read()
    py_idx = _index_python(buf)
    if bindings.available():
        from geomx_tpu.data.recordio import _index_native

        assert _index_native(buf) == py_idx
    else:
        pytest.skip("native toolchain unavailable")


def test_recordio_corruption_detected(tmp_path):
    path = str(tmp_path / "c.rec")
    with RecordWriter(path) as w:
        w.write(b"abcdef")
    buf = bytearray(open(path, "rb").read())
    buf[0] ^= 0xFF  # smash the magic
    bad = str(tmp_path / "bad.rec")
    open(bad, "wb").write(bytes(buf))
    with pytest.raises(IOError):
        RecordReader(bad)


def test_pack_unpack_array():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    x2, label = unpack_array(pack_array(x, label=7.0))
    np.testing.assert_array_equal(x, x2)
    assert label == 7.0
    u8 = np.random.default_rng(0).integers(0, 255, (2, 2), dtype=np.uint8)
    u8b, _ = unpack_array(pack_array(u8))
    np.testing.assert_array_equal(u8, u8b)


def test_record_dataset_iter_shards_cover_all(tmp_path):
    path = str(tmp_path / "d.rec")
    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20) % 3
    write_array_dataset(path, x, y)
    seen = set()
    for w in range(4):
        it = RecordDatasetIter(path, batch_size=5, worker_index=w,
                               num_workers=4, shuffle=False)
        xb, yb = next(it)
        assert xb.shape == (5, 2) and yb.dtype == np.int32
        seen.update(xb[:, 0].astype(int) // 2)
    assert seen == set(range(20))  # shards disjointly cover the file


def test_record_iter_sequential_sweeps_whole_shard(tmp_path):
    """shuffle=False must sweep every record, not repeat the first batch."""
    path = str(tmp_path / "s.rec")
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    write_array_dataset(path, x, np.zeros(10, np.int64))
    it = RecordDatasetIter(path, batch_size=3, shuffle=False)
    seen = set()
    for _ in range(4):  # 4*3 = 12 > 10 → full coverage with wrap
        xb, _ = next(it)
        seen.update(xb[:, 0].astype(int).tolist())
    assert seen == set(range(10))


def test_empty_shard_raises(tmp_path):
    imgs = np.zeros((2, 4, 4), np.uint8)
    labels = np.zeros(2, np.uint8)
    ip, lp = str(tmp_path / "im.idx"), str(tmp_path / "lb.idx")
    MNISTIter.write_idx(ip, imgs)
    MNISTIter.write_idx(lp, labels)
    with pytest.raises(ValueError, match="empty shard"):
        MNISTIter(ip, lp, batch_size=1, worker_index=2, num_workers=3)


def test_mnist_idx_roundtrip(tmp_path):
    imgs = np.random.default_rng(0).integers(
        0, 255, (30, 8, 8), dtype=np.uint8)
    labels = (np.arange(30) % 10).astype(np.uint8)
    ip, lp = str(tmp_path / "im.idx"), str(tmp_path / "lb.idx")
    MNISTIter.write_idx(ip, imgs)
    MNISTIter.write_idx(lp, labels)
    it = MNISTIter(ip, lp, batch_size=6)
    x, y = next(it)
    assert x.shape == (6, 8, 8, 1) and x.dtype == np.float32
    assert x.max() <= 1.0 and y.dtype == np.int32
    np.testing.assert_array_equal(it.x, imgs)


def test_mnist_idx_rejects_garbage(tmp_path):
    p = str(tmp_path / "junk.idx")
    open(p, "wb").write(struct.pack(">HBB", 1, 0x08, 1) + b"\x00" * 8)
    with pytest.raises(IOError):
        MNISTIter._read_idx(p)


def test_csv_iter(tmp_path):
    p = str(tmp_path / "t.csv")
    rows = ["1,0.5,0.25", "0,1.5,2.5", "2,3.0,4.0", "1,5.0,6.0"]
    open(p, "w").write("\n".join(rows))
    it = CSVIter(p, batch_size=3)
    x, y = next(it)
    assert x.shape == (3, 2) and y.dtype == np.int32
    assert set(np.unique(y)) <= {0, 1, 2}


def test_libsvm_iter_row_sparse_layout(tmp_path):
    p = str(tmp_path / "t.svm")
    open(p, "w").write("1 2:0.5 7:1.0\n0 2:2.0\n1 9:3.0\n")
    it = LibSVMIter(p, batch_size=3, num_features=10, seed=1)
    ids, slab, labels = next(it)
    assert ids.dtype == np.int64 and slab.shape == (len(ids), 1)
    assert np.all(np.diff(ids) > 0)  # sorted distinct rows
    assert labels.shape == (3,)
    assert set(ids.tolist()) <= {2, 7, 9}


def test_augment_iter_shapes():
    x = np.random.default_rng(0).random((8, 10, 10, 1)).astype(np.float32)
    y = np.zeros(8, np.int32)
    base = iter([(x, y)] * 3)
    it = AugmentIter(base, flip=True, pad_crop=2, seed=0)
    xa, ya = next(it)
    assert xa.shape == x.shape and ya is y


def test_prefetch_iter_order_and_close():
    src = iter([(np.full(2, i), i) for i in range(10)])
    it = PrefetchIter(src, depth=3)
    got = [y for _, y in it]
    assert got == list(range(10))
    it.close()


def test_prefetch_propagates_errors():
    def gen():
        yield (np.zeros(1), 0)
        raise ValueError("boom")

    it = PrefetchIter(gen(), depth=2)
    next(it)
    with pytest.raises(ValueError, match="boom"):
        next(it)


def test_libsvm_feeds_row_sparse_push():
    """End-to-end: libsvm batches drive the row-sparse kvstore path."""
    import tempfile

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation

    with tempfile.TemporaryDirectory() as d:
        p = f"{d}/t.svm"
        open(p, "w").write("1 0:1.0 3:1.0\n0 1:1.0\n1 2:1.0 3:1.0\n")
        it = LibSVMIter(p, batch_size=2, num_features=4, seed=0)
        sim = Simulation(Config(topology=Topology(num_parties=1,
                                                  workers_per_party=1)))
        try:
            w = sim.all_workers()[0]
            w.init(0, np.zeros((4, 1), np.float32))
            w.set_optimizer({"type": "sgd", "lr": 1.0})
            ids, slab, _ = next(it)
            w.push_row_sparse(0, ids, slab)
            got = {}
            w.pull_row_sparse(0, ids,
                              lambda t, rows: got.__setitem__("r", rows))
            w.wait_all()
            assert got["r"].shape == slab.shape
            assert np.any(got["r"] != 0)
        finally:
            sim.shutdown()


def test_idx_reader_transparent_gzip(tmp_path):
    """MNIST idx files are commonly distributed gzipped; the reader
    decodes them in place (the real-data drop path of examples/cnn.py
    --mnist needs no unzip step)."""
    import gzip

    from geomx_tpu.data import MNISTIter

    x = np.arange(2 * 4 * 4, dtype=np.uint8).reshape(2, 4, 4)
    y = np.array([3, 7], dtype=np.uint8)
    MNISTIter.write_idx(str(tmp_path / "imgs"), x)
    MNISTIter.write_idx(str(tmp_path / "lbls"), y)
    (tmp_path / "imgs.gz").write_bytes(
        gzip.compress((tmp_path / "imgs").read_bytes()))
    (tmp_path / "lbls.gz").write_bytes(
        gzip.compress((tmp_path / "lbls").read_bytes()))
    it = MNISTIter(str(tmp_path / "imgs.gz"), str(tmp_path / "lbls.gz"),
                   batch_size=2)
    bx, by = next(it)
    assert bx.shape == (2, 4, 4, 1) and by.shape == (2,)
    assert set(by) <= {3, 7}
