"""Cross-tier distributed tracing (ISSUE 3 tentpole).

Covers: end-to-end causal-chain propagation over the HiPS tree (the
acceptance criterion: one round's push → local-merge → WAN →
global-merge → pull chain connected by parent/child span ids across
>= 3 node roles, critical-path report naming the dominant stage),
trace-context survival through the DGT multi-channel chunk path
(reordered + lost lossy chunks) and the KVWorker.retarget replay path,
round sampling, heartbeat-RTT clock metrics, the per-codec WAN byte
registry, and the disabled-path overhead guard (spans gated before
construction — no per-message allocation).
"""

import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.trace import context as tctx
from geomx_tpu.trace.recorder import _NULL_SPAN, Tracer, get_tracer
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.utils.metrics import system_snapshot


def _trace_cfg(parties=2, workers=1, **kw):
    kw.setdefault("trace_sample_every", 1)
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _run_rounds(sim, rounds, tid=0, n=64):
    """Drive FSA rounds the way the training loop does: every worker's
    push+pull issued under its round span, waits after all parties
    pushed (an FSA round only completes with every party's push)."""
    ws = sim.all_workers()
    for r in range(rounds):
        for w in ws:
            with w.trace_round(r):
                w.push(tid, np.full(n, 0.1, np.float32))
                w.pull(tid, lambda t, a: None)
        for w in ws:
            w.wait_all()


def test_e2e_chain_across_three_roles_and_critical_path(tmp_path):
    """Acceptance: merged trace connects one round's chain across
    worker / local server / global server, and the critical-path report
    names a dominant stage per round."""
    sim = Simulation(_trace_cfg())
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        _run_rounds(sim, 3)
        assert sim.flush_traces() > 0
        evs = sim.trace_collector.merged_events()
        roles = {e["pid"].split(":")[0] for e in evs}
        assert {"worker", "server", "global_server"} <= roles
        # every recorded parent resolves to a recorded span — the chain
        # has no dangling edges
        ids = {e["args"]["span"] for e in evs}
        dangling = [e for e in evs
                    if e["args"]["parent"] and e["args"]["parent"] not in ids]
        assert not dangling, [e["name"] for e in dangling]
        # walk one global-merge span up to its worker root: the chain
        # must cross >= 3 distinct roles connected by parent ids
        by_span = {e["args"]["span"]: e for e in evs}
        gl = [e for e in evs if e["name"] == "global.push"]
        assert gl, "no global-server merge spans collected"
        e, chain_roles, chain_names = gl[0], set(), []
        while e is not None:
            chain_roles.add(e["pid"].split(":")[0])
            chain_names.append(e["name"])
            e = by_span.get(e["args"]["parent"])
        assert len(chain_roles) >= 3, (chain_roles, chain_names)
        assert chain_names[-1] == "round", chain_names
        # critical path: every sampled round reported, dominant named
        rep = sim.trace_report()
        rounds = {r["round"]: r for r in rep["rounds"]}
        assert {0, 1, 2} <= set(rounds)
        for r in rounds.values():
            assert r["dominant_stage"] in (
                "lan_push", "local_merge", "codec", "wan", "global_merge",
                "pull_fanout", "barrier")
            assert r["stages"][r["dominant_stage"]]["worst_node"]
        # the merged file dump is valid JSON with the same events
        out = sim.dump_trace(str(tmp_path / "trace.json"))
        assert len(out["traceEvents"]) == len(evs)
    finally:
        sim.shutdown()


def test_round_sampling_every_n():
    """trace_sample_every=2: rounds 0 and 2 trace, rounds 1 and 3 add
    NOTHING — the sampling gate is the overhead contract when on."""
    sim = Simulation(_trace_cfg(trace_sample_every=2))
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in ws:
            w.init(0, np.zeros(16, np.float32))
        _run_rounds(sim, 4, n=16)
        sim.flush_traces()
        traced = {r["round"] for r in sim.trace_report()["rounds"]}
        assert traced == {0, 2}
    finally:
        sim.shutdown()


def test_dgt_chunks_preserve_trace_context_under_reorder_and_loss():
    """Satellite: the trace context survives the DGT multi-channel UDP
    path — chunks arrive reordered and lossy-channel chunks go missing,
    and the reassembled logical message still carries the original
    trace/span/parent ids."""
    from geomx_tpu.transport.dgt import DgtReassembler, DgtSender

    cfg = Config(enable_dgt=1, dgt_block_size=8, dgt_k=0.25,
                 dgt_udp_channels=3)
    sender = DgtSender(cfg)
    msg = Message(
        recipient=None, domain=Domain.GLOBAL, app_id=0, customer_id=1,
        timestamp=7, request=True, push=True,
        keys=np.array([5], np.int64),
        vals=np.arange(64, dtype=np.float32),
        lens=np.array([64], np.int64),
        trace_id=4242, span_id=99, parent_span_id=55, sampled=True,
    )
    msg.sender = "worker:0@p0"
    chunks = sender.split(msg)
    assert len(chunks) > 2
    assert all(c.trace_id == 4242 and c.span_id == 99
               and c.parent_span_id == 55 and c.sampled for c in chunks)
    # drop one lossy chunk, deliver the rest in reverse order
    lossy = [c for c in chunks if c.channel >= 1]
    assert lossy, "k=0.25 must put chunks on lossy channels"
    dropped = lossy[0]
    arriving = [c for c in chunks if c is not dropped]
    arriving.reverse()
    reasm = DgtReassembler()
    whole = None
    for c in arriving:
        out = reasm.accept(c)
        if out is not None:
            assert whole is None, "reassembled twice"
            whole = out
    assert whole is not None
    assert whole.trace_id == 4242
    assert whole.span_id == 99
    assert whole.parent_span_id == 55
    assert whole.sampled
    # the dropped lossy chunk zero-filled, the rest intact
    assert len(whole.vals) == 64


def test_retarget_replay_keeps_original_trace_id():
    """Satellite: a request replayed through KVWorker.retarget (the
    PR 1 failover path) keeps its ORIGINAL trace_id — the replay shows
    up as part of the original round's trace, not as a fresh one."""
    from geomx_tpu.kvstore.common import APP_PS
    from geomx_tpu.ps import KVPairs, KVServer, KVWorker, Postoffice
    from geomx_tpu.ps.postoffice import split_range
    from geomx_tpu.transport import InProcFabric

    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1,
                                   num_standby_globals=1),
                 request_retry_s=30.0)  # long: only retarget may resend
    topo = cfg.topology
    fabric = InProcFabric()
    offices = {str(n): Postoffice(n, topo, fabric, cfg)
               for n in topo.all_nodes()}
    for po in offices.values():
        po.start()
    old = topo.global_servers()[0]
    new = topo.standby_globals()[0]
    got = []

    def handle(msg, kvs, server):
        got.append((msg.trace_id, msg.parent_span_id, msg.span_id))
        server.response(msg)

    srv_old = KVServer(APP_PS, 0, offices[str(old)], lambda *a: None)
    srv_new = KVServer(APP_PS, 0, offices[str(new)], handle)
    wnode = topo.workers(0)[0]
    kw = KVWorker(APP_PS, 1, offices[str(wnode)], [old], split_range(1))
    tctx.activate()
    prev = tctx.swap(tctx.TraceContext(4321, 17))
    try:
        ts = kw.zpush(KVPairs(np.array([1], np.int64),
                              np.ones(4, np.float32), np.array([4])))
    finally:
        tctx.restore(prev)
    time.sleep(0.2)
    assert kw.customer.num_response(ts) == 0  # blackholed at old target
    assert kw.retarget(old, new) == 1
    kw.wait(ts)
    assert got, "replayed request never reached the new target"
    trace_id, parent, span = got[0]
    assert trace_id == 4321
    assert parent == 17
    assert span != 0  # assigned at first send, preserved by the replay
    kw.stop(); srv_old.stop(); srv_new.stop()
    for po in offices.values():
        po.stop()
    fabric.shutdown()


def test_disabled_tracing_no_per_message_work():
    """Tier-1 overhead guard (satellite): with tracing off, spans are
    gated BEFORE construction (the factory returns one shared no-op
    object) and messages cross the van completely unstamped."""
    from geomx_tpu.ps import Postoffice
    from geomx_tpu.transport import InProcFabric

    was_active = tctx.ACTIVE
    tctx.ACTIVE = False
    try:
        tr = Tracer("overhead-guard-node")
        # no allocation: the identical shared null object every call
        assert tr.span("local.push") is _NULL_SPAN
        assert tr.span("anything") is tr.span("else")
        assert tr.round(0, 0) is _NULL_SPAN
        tr.instant("evict.worker")  # gated: records nothing
        assert tr.pending() == 0

        topo = Topology(num_parties=1, workers_per_party=1)
        fabric = InProcFabric()
        po = Postoffice(topo.workers(0)[0], topo, fabric, Config())
        po.start()
        try:
            msg = Message(recipient=topo.server(0), domain=Domain.LOCAL,
                          control=Control.HEARTBEAT)
            po.van.send(msg)
            assert msg.trace_id == 0
            assert msg.span_id == 0
            assert msg.parent_span_id == 0
            assert not msg.sampled
        finally:
            po.stop()
            fabric.shutdown()
    finally:
        tctx.ACTIVE = was_active


def test_response_inherits_request_trace():
    """reply_to: the response joins the request's trace as a child of
    the request message (the timestamp/Customer correlation)."""
    req = Message(request=True, trace_id=9, span_id=33,
                  parent_span_id=11, sampled=True)
    rep = req.reply_to()
    assert rep.trace_id == 9
    assert rep.parent_span_id == 33  # child of the request MESSAGE
    assert rep.span_id == 0          # fresh id assigned at send
    assert rep.sampled


def test_trace_fields_survive_wire_serialization():
    m = Message(request=True, push=True,
                keys=np.array([1], np.int64),
                vals=np.ones(3, np.float32), lens=np.array([3], np.int64),
                trace_id=77, span_id=88, parent_span_id=66, sampled=True)
    m.sender = None
    back = Message.from_bytes(m.to_bytes())
    assert back.trace_id == 77
    assert back.span_id == 88
    assert back.parent_span_id == 66
    assert back.sampled


def test_heartbeat_rtt_and_clock_offsets_in_registry():
    """Satellite: heartbeat pings are echoed; RTT + clock offset land in
    the system-metrics registry and Postoffice.clock_offsets — the same
    numbers the trace collector merges timestamps with."""
    sim = Simulation(_trace_cfg(heartbeat_interval_s=0.05,
                                enable_eviction=False))
    try:
        w = sim.all_workers()[0]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not w.po.clock_offsets():
            time.sleep(0.05)
        offs = w.po.clock_offsets()
        assert offs, "no heartbeat echo arrived"
        sched = str(sim.topology.scheduler(0))
        assert sched in offs
        # one host, one clock: offset within the RTT, RTT sane
        rtts = w.po.heartbeat_rtts()
        assert 0.0 <= rtts[sched] < 1.0
        assert abs(offs[sched]) <= max(rtts[sched], 0.05)
        snap = system_snapshot()
        assert snap.get(f"{w.po.node}.heartbeat_rtt_s", float("nan")) >= 0.0
        assert np.isfinite(snap.get(f"{w.po.node}.clock_offset_s",
                                    float("nan")))
        # local servers heartbeat BOTH tiers — the collector's chaining
        # input (worker->psched + server->psched + server->gsched)
        ls = sim.local_servers[0]
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and len(ls.po.clock_offsets()) < 2):
            time.sleep(0.05)
        assert len(ls.po.clock_offsets()) == 2
    finally:
        sim.shutdown()


def test_wan_codec_bytes_in_registry():
    """Satellite: every GLOBAL-domain data send is ledgered per wire
    codec tag in the system-metrics registry (wan_bytes_vanilla /
    wan_bytes_fp16 / ...) — the ledger bench.py's wan child reports."""
    base = system_snapshot()
    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=1)))
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in ws:
            w.init(0, np.zeros(4096, np.float32))
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression({"type": "fp16"})
        for w in ws:
            w.push(0, np.ones(4096, np.float32))
        for w in ws:
            w.pull_sync(0)
        snap = system_snapshot()

        def delta(suffix):
            return sum(v - base.get(k, 0) for k, v in snap.items()
                       if k.endswith(suffix))

        assert delta(".wan_bytes_fp16") > 0      # compressed push-ups
        assert delta(".wan_bytes_vanilla") > 0   # INIT forwarding
    finally:
        sim.shutdown()


def test_phase_tracer_artifact(tmp_path):
    """The soak-deflake helper: phases land as root spans in a dumpable
    Chrome-trace artifact."""
    from geomx_tpu.trace import PhaseTracer

    pt = PhaseTracer("unit")
    with pt.phase("setup"):
        time.sleep(0.01)
    pt.mark("kill", node="worker:0@p0")
    with pt.phase("recovery"):
        time.sleep(0.01)
    path = pt.dump(str(tmp_path / "phases.json"))
    import json

    events = json.load(open(path))["traceEvents"]
    names = [e["name"] for e in events]
    assert "phase.setup" in names
    assert "phase.recovery" in names
    assert "mark.kill" in names
    setup = next(e for e in events if e["name"] == "phase.setup")
    assert setup["dur"] >= 10_000  # microseconds
