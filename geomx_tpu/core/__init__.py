from geomx_tpu.core.config import Config, Role, Topology, NodeId  # noqa: F401
