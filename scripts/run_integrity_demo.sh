#!/usr/bin/env bash
# Data-integrity demo (ISSUE 17): a real OS-process TCP cluster rides
# out in-flight frame corruption AND a byzantine NaN worker, with the
# integrity plane (wire checksums + gradient hygiene) catching both.
#
# Two faults land mid-training, both on party 0 (party 1 is the healthy
# control — and the cluster terminator is party 0's rank-0 worker, so
# the FAULTED party must be the slow one or the exit broadcast would
# tear the cluster down under the laggard's feet):
#
#   * party 0's server carries a scripted GEOMX_NETFAULT_PLAN: ~25 s in,
#     its WAN uplink to the global server starts corrupting 25 % of data
#     frames in flight (seeded bit flips) for 10 s — the rot a flaky NIC
#     inflicts;
#   * worker:1@p0 turns byzantine at step 40: every gradient it pushes
#     from then on is all-NaN (GEOMX_TEST_POISON_STEPS).
#
# Asserted, in order:
#
#   1. the corruption tape cuts in and the RECEIVER's wire checksum
#      rejects the damaged frames (counted + NACK-resent — training
#      never sees them);
#   2. the local server's finiteness screen rejects the poisoned pushes
#      and QUARANTINES the poisoner after GEOMX_POISON_QUARANTINE_N
#      strikes — reversibly folded out, never evicted;
#   3. the status console shows the quarantined worker (qworkers=1) and
#      the health engine pages a data_corruption alert;
#   4. training completes on every worker with finite losses — zero
#      corrupted payloads reached a merge.
#
# Env: BASE_PORT (9700), STEPS (120)
set -euo pipefail
cd "$(dirname "$0")/.."

BASE_PORT="${BASE_PORT:-9700}"
STEPS="${STEPS:-120}"
LOG_DIR="$(mktemp -d)"
export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_BASE_PORT="$BASE_PORT"
# the status console derives its address plan from env (the launchers
# get the same topology via flags)
export GEOMX_NUM_PARTIES=2
export GEOMX_WORKERS_PER_PARTY=2
# the integrity plane (all off by default — this demo turns it on)
export GEOMX_INTEGRITY_WIRE=1
export GEOMX_INTEGRITY_PUSH_SCREEN=1
export GEOMX_POISON_QUARANTINE_N=3
# health plane: data_corruption pages fast so the demo can grep it
export GEOMX_OBS=1
export GEOMX_OBS_INTERVAL=0.3
export GEOMX_OBS_CORRUPTION_EVENTS=5
export GEOMX_REQUEST_RETRY_S="${GEOMX_REQUEST_RETRY_S:-1.0}"
# pace every worker ~250 ms/step so the corrupt window (25 s..35 s)
# lands provably mid-training and steps remain after it heals
export GEOMX_TEST_STEP_SLEEP_MS='{"worker:0@p0": 250, "worker:1@p0": 250,
                                  "worker:0@p1": 250, "worker:1@p1": 250}'
# worker:1@p0 pushes all-NaN gradients from step 40 on
export GEOMX_TEST_POISON_STEPS='{"worker:1@p0": 40}'

# the corruption tape, applied ONLY inside party 0's server process:
# bit-flip 25 % of its outbound WAN data frames for 10 s
NETFAULT_PLAN='[{"at_s": 25.0, "duration_s": 10.0, "kind": "corrupt",
                 "src": "server:0@p0", "dst": "global_server:0",
                 "rate": 0.25, "corrupt_mode": "bitflip"}]'

COMMON=(--parties 2 --workers 2 --base-port "$BASE_PORT" \
        --steps "$STEPS" --sync mixed)

pids=()
declare -A PID_OF
launch() {  # launch <role> [extra env as K=V ...]
  local role="$1"; shift
  env "$@" python -m geomx_tpu.launch --role "$role" "${COMMON[@]}" \
    >"$LOG_DIR/${role//[:@]/_}.log" 2>&1 &
  pids+=($!)
  PID_OF["$role"]=$!
}

launch "global_scheduler:0"
launch "global_server:0"
launch "scheduler:0@p0"
launch "server:0@p0" GEOMX_NETFAULT_PLAN="$NETFAULT_PLAN"
launch "worker:0@p0"
launch "worker:1@p0"
launch "scheduler:0@p1"
launch "server:0@p1"
launch "worker:0@p1"
launch "worker:1@p1"
cleanup() {
  local status=$?
  kill "${pids[@]}" 2>/dev/null || true
  if [ "$status" -eq 0 ]; then
    rm -rf "$LOG_DIR"
  else
    echo "demo failed — logs kept at $LOG_DIR"
  fi
}
trap cleanup EXIT

wait_for_log() {  # wait_for_log <file> <pattern> <tries>
  for _ in $(seq 1 "$3"); do
    grep -q "$2" "$LOG_DIR/$1" 2>/dev/null && return 0
    sleep 0.5
  done
  echo "TIMEOUT waiting for '$2' in $1"; tail -5 "$LOG_DIR/$1" || true
  return 1
}

wait_for_log "worker_0_p0.log" "configured — training begins" 300
echo ">>> training running; waiting for the scripted corruption window"

# ---- 1. the tape cuts in; the receiver's checksum rejects -------------
wait_for_log "server_0_p0.log" \
  "netfault cut corrupt server:0@p0->global_server:0" 120
echo ">>> party 0's WAN uplink is corrupting frames"
wait_for_log "global_server_0.log" "wire checksum rejected a corrupt frame" 60
echo ">>> wire checksum caught the damage (NACK resend in flight)"

# ---- 2. the byzantine worker strikes out and is quarantined -----------
wait_for_log "server_0_p0.log" \
  "quarantined worker:1@p0 after .* poisoned pushes" 180
if grep -hq "evicted worker\|evicted: worker:1@p0" "$LOG_DIR"/*.log; then
  echo "FAIL: the poisoner was evicted instead of quarantined"
  exit 1
fi
echo ">>> poisoner quarantined (reversibly folded out, not evicted)"

# ---- 3. the telemetry plane sees both -----------------------------------
QSEEN=0
for _ in $(seq 1 12); do
  python -m geomx_tpu.status --timeout 5 >"$LOG_DIR/status.txt" \
    2>"$LOG_DIR/status.err" || true
  if grep -q "qworkers=1" "$LOG_DIR/status.txt"; then QSEEN=1; break; fi
  sleep 0.5
done
[ "$QSEEN" = 1 ] \
  || { echo "FAIL: status console never showed the quarantined worker"
       cat "$LOG_DIR/status.txt" 2>/dev/null || true; exit 1; }
echo ">>> status console shows p0 qworkers=1"
wait_for_log "global_scheduler_0.log" "health ALERT data_corruption" 60
echo ">>> health engine paged data_corruption"

# ---- 4. heal + training completes with finite losses ------------------
wait_for_log "server_0_p0.log" \
  "netfault heal corrupt server:0@p0->global_server:0" 120
fail=0
for role in "worker:0@p0" "worker:1@p0" "worker:0@p1" "worker:1@p1"; do
  wait "${PID_OF[$role]}" || fail=1
  f="$LOG_DIR/${role//[:@]/_}.log"
  grep -q "steps=" "$f" || { echo "FAIL: $role never finished"; fail=1; }
done
if grep -hq "last_loss=nan" "$LOG_DIR"/worker_*.log; then
  echo "FAIL: a NaN reached the model — corrupted payload merged"
  fail=1
fi

echo "=== summary ==="
grep -h "netfault\|wire checksum\|quarantined\|health ALERT" \
  "$LOG_DIR"/*.log | sort -u || true
grep -h "steps=" "$LOG_DIR"/worker_*.log || true
echo "integrity demo exit=$fail"
exit $fail
