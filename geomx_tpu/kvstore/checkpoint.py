"""Server-state checkpoint/restore.

The reference keeps server model state only in RAM and supports
client-side optimizer-state saves that are explicitly unsupported for
distributed updaters (ref: python/mxnet/kvstore.py:566-591;
kvstore_dist_server.h:1923 store_ map) — SURVEY.md §7 flags server-side
checkpointing as an improvement to build.  Format: a single .npz holding
the weight slabs keyed by ps-key plus pickled optimizer state, written
atomically (tmp + rename) so a crash mid-save never corrupts the last
good checkpoint.
"""

from __future__ import annotations

import pickle
from typing import Dict

import numpy as np

from geomx_tpu.utils.io import atomic_write


def save_server_state(path: str, store: Dict[int, np.ndarray],
                      optimizer_state: dict, meta: dict) -> None:
    payload: Dict[str, np.ndarray] = {
        f"k{k}": v for k, v in store.items()
    }
    payload["__opt__"] = np.frombuffer(
        pickle.dumps(optimizer_state, protocol=4), dtype=np.uint8)
    payload["__meta__"] = np.frombuffer(
        pickle.dumps(meta, protocol=4), dtype=np.uint8)
    with atomic_write(path) as f:
        np.savez(f, **payload)


def load_server_state(path: str):
    """Returns (store, optimizer_state, meta)."""
    with np.load(path, allow_pickle=False) as z:
        store = {int(name[1:]): z[name] for name in z.files
                 if name.startswith("k")}
        opt = pickle.loads(z["__opt__"].tobytes())
        meta = pickle.loads(z["__meta__"].tobytes())
    return store, opt, meta
