from geomx_tpu.utils.profiler import Profiler, get_profiler  # noqa: F401
