"""Single-process simulation of a full HiPS deployment.

The reference tests multi-node behavior by launching 12 OS processes on
localhost (ref: scripts/cpu/run_vanilla_hips.sh;
docs/source/pseudo-distributed-deployment.rst:1-16).  We stand the same
topology up as threads over the in-proc fabric — every role, both
domains, programmable WAN loss/latency — in one Python process, which is
what tests and the ``--simulate`` mode of the examples use.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from geomx_tpu.core.config import Config, NodeId, Topology
from geomx_tpu.kvstore.client import WorkerKVStore
from geomx_tpu.kvstore.server import GlobalServer, LocalServer
from geomx_tpu.ps import Postoffice
from geomx_tpu.transport.van import FaultPolicy, InProcFabric


class Simulation:
    def __init__(self, config: Config, fault: Optional[FaultPolicy] = None,
                 lightweight: Optional[bool] = None):
        import threading

        from geomx_tpu.transport.reactor import Reactor, resolve_transport

        self._join_mu = threading.Lock()
        self.config = config
        self.topology = config.topology
        # lightweight-party mode: all in-process nodes share the
        # per-process Reactor — van recv / customer handler threads
        # become serial dispatch channels on the shared pool, heartbeat
        # / resend / monitor loops land on the timer wheel, and server
        # merge lanes run inline (server_shards forced to 1) — so an
        # O(100)-party topology runs O(reactor loops + handler pool)
        # threads instead of O(nodes).  On by Config.lightweight /
        # GEOMX_LIGHTWEIGHT, by the explicit constructor arg, or
        # whenever the process transport is "reactor" (GEOMX_TRANSPORT
        # — the knob the parity suites are shaken under).
        if lightweight is None:
            lightweight = bool(getattr(config, "lightweight", False)
                               or resolve_transport(config) == "reactor")
        self.lightweight = bool(lightweight)
        if self.lightweight and not config.lightweight:
            # components read the flag off the config (merge-lane
            # sizing, resolve_server_shards) — flip it before any node
            # is constructed
            config.lightweight = True
        self.reactor = Reactor.shared() if self.lightweight else None
        self.fabric = InProcFabric(fault=fault, config=config,
                                   reactor=self.reactor,
                                   lightweight=self.lightweight)
        self.offices: Dict[str, Postoffice] = {}
        # distributed tracing (geomx_tpu/trace): collector on the global
        # scheduler, a reporter per node.  Constructed BEFORE the other
        # postoffices start so no TRACE_REPORT can beat the collector's
        # customer registration.
        self.trace_collector = None
        gsched = str(self.topology.global_scheduler())
        for n in self.topology.all_nodes():
            po = Postoffice(n, self.topology, self.fabric, config)
            if config.trace_sample_every > 0 and str(n) == gsched:
                from geomx_tpu.trace import get_collector

                self.trace_collector = get_collector(po)
            po.start()
            self.offices[str(n)] = po
            self._attach_tracer(po, fresh=True)
        # cluster telemetry plane (geomx_tpu/obs): collector + health
        # engine on the global scheduler, constructed BEFORE any pump so
        # no METRICS_REPORT can beat the endpoint registration
        self.metrics_collector = None
        self.health = None
        self.metrics_pumps: Dict[str, "MetricsPump"] = {}
        if config.enable_obs:
            from geomx_tpu.obs import HealthEngine, MetricsCollector

            self.metrics_collector = MetricsCollector(
                self.offices[gsched], config,
                trace_collector=self.trace_collector)
            self.health = HealthEngine(
                self.metrics_collector, config,
                trace_collector=self.trace_collector)
        self.ts_schedulers = []
        if config.enable_intra_ts:
            from geomx_tpu.sched.ts_push import TsPushScheduler
            from geomx_tpu.sched.tsengine import TsScheduler

            for p in range(self.topology.num_parties):
                sched_po = self.offices[str(self.topology.scheduler(p))]
                self.ts_schedulers.append(TsScheduler(
                    sched_po,
                    members=self.topology.workers(p),
                    greed_rate=config.ts_max_greed_rate,
                ))
                TsPushScheduler(sched_po,
                                num_workers=self.topology.workers_per_party)
        if config.enable_inter_ts:
            from geomx_tpu.sched.tsengine import TsScheduler

            gsched_po = self.offices[str(self.topology.global_scheduler())]
            self.ts_schedulers.append(TsScheduler(
                gsched_po,
                members=self.topology.servers(),
                greed_rate=config.ts_max_greed_rate,
            ))
            if config.enable_inter_ts_push:
                from geomx_tpu.sched.ts_push import TsPushScheduler

                TsPushScheduler(
                    gsched_po,
                    num_workers=self.topology.num_global_workers)
        self.local_servers: List[LocalServer] = [
            LocalServer(self.offices[str(self.topology.server(p))], config)
            for p in range(self.topology.num_parties)
        ]
        # standbys FIRST: a primary with a standby configured ships a
        # baseline replication snapshot at startup, and the standby must
        # exist to receive it
        self.standby_globals: List[GlobalServer] = [
            GlobalServer(self.offices[str(sb)], config, standby=True)
            for sb in self.topology.standby_globals()
        ]
        self.global_servers: List[GlobalServer] = [
            GlobalServer(self.offices[str(gs)], config)
            for gs in self.topology.global_servers()
        ]
        self.failover_monitor = None
        if (self.topology.num_standby_globals
                and config.heartbeat_interval_s > 0):
            from geomx_tpu.kvstore.replication import GlobalFailoverMonitor

            self.failover_monitor = GlobalFailoverMonitor(
                self.offices[str(self.topology.global_scheduler())])
        # read-serving replica tier (geomx_tpu/serve): replicas after
        # the global servers they subscribe to; the monitor (eviction +
        # subscriber prune) only with heartbeats on.  num_replicas == 0
        # (the default) constructs nothing — no threads, no endpoints.
        self.replicas: List["ModelReplica"] = []
        self.replica_monitor = None
        self.replica_autoscaler = None
        self._serve_clients: List = []
        if self.topology.num_replicas:
            from geomx_tpu.serve import ModelReplica

            self.replicas = [
                ModelReplica(self.offices[str(r)], config)
                for r in self.topology.replicas()
            ]
            if config.heartbeat_interval_s > 0 and config.enable_eviction:
                from geomx_tpu.serve import ReplicaMonitor

                self.replica_monitor = ReplicaMonitor(
                    self.offices[str(self.topology.global_scheduler())])
            if config.serve_autoscale:
                # elastic serve capacity (geomx_tpu/serve/autoscaler):
                # decisions read the telemetry plane, scale-down retires
                # over the wire, scale-up revives through the same path
                # a restarted --role replica:K process takes
                from geomx_tpu.serve import ReplicaAutoscaler

                self.replica_autoscaler = ReplicaAutoscaler(
                    self.offices[gsched], config,
                    collector=self.metrics_collector,
                    spawn=self.restart_replica)
        self.workers: Dict[str, WorkerKVStore] = {}
        for p in range(self.topology.num_parties):
            for w in self.topology.workers(p):
                self.workers[str(w)] = WorkerKVStore(self.offices[str(w)], config)
        self.master: Optional["MasterWorker"] = None
        mw = self.topology.master_worker()
        if mw is not None:
            from geomx_tpu.kvstore.client import MasterWorker

            self.master = MasterWorker(self.offices[str(mw)], config)
        # crash-tolerant membership (kvstore/eviction.py): when
        # heartbeats are on, each party scheduler evicts dead workers
        # and the global scheduler folds/recovers dead local servers
        self.eviction_monitors = []
        self.recovery_monitor = None
        if config.heartbeat_interval_s > 0 and config.enable_eviction:
            from geomx_tpu.kvstore.eviction import (
                LocalServerRecoveryMonitor, WorkerEvictionMonitor)

            for p in range(self.topology.num_parties):
                self.eviction_monitors.append(WorkerEvictionMonitor(
                    self.offices[str(self.topology.scheduler(p))]))
            self.recovery_monitor = LocalServerRecoveryMonitor(
                self.offices[str(self.topology.global_scheduler())])
        # adaptive WAN control plane (geomx_tpu/control): closed-loop
        # codec/ratio retuning on the global scheduler.  With
        # adapt_interval_s == 0 no sweep thread runs — tests drive
        # wan_controller.tick() deterministically.
        self.wan_controller = None
        if config.adaptive_wan:
            from geomx_tpu.control import AdaptiveWanController

            self.wan_controller = AdaptiveWanController(
                self.offices[str(self.topology.global_scheduler())],
                config, collector=self.trace_collector,
                metrics=self.metrics_collector)
        # per-node metrics pumps (telemetry plane): server roles ship
        # their QUERY_STATS-equivalent stats dict, everyone ships their
        # registry slice; frames ride the wire like every other node's
        # traffic (the gsched's own pump short-circuits in-proc)
        if config.enable_obs:
            from geomx_tpu.obs import MetricsPump

            stats_fns = {str(ls.po.node): ls.stats
                         for ls in self.local_servers}
            stats_fns.update({str(gs.po.node): gs.stats for gs in
                              self.global_servers + self.standby_globals})
            stats_fns.update({str(r.po.node): r.stats
                              for r in self.replicas})
            for s, po in self.offices.items():
                self.metrics_pumps[s] = MetricsPump(
                    po, config, stats_fn=stats_fns.get(s),
                    collector=(self.metrics_collector
                               if s == gsched else None))
        # live cluster-state console: always on (costs nothing until
        # queried); Simulation.cluster_state() and the Ctrl.CLUSTER_STATE
        # wire query share compose()
        from geomx_tpu.obs import ClusterStateService

        self.state_service = ClusterStateService(
            self.offices[gsched], config,
            failover_monitor=self.failover_monitor,
            recovery_monitor=self.recovery_monitor,
            wan_controller=self.wan_controller,
            collector=self.metrics_collector,
            health=self.health)

    def _attach_tracer(self, po: Postoffice, fresh: bool = False) -> None:
        """Bind the node's tracer to its (possibly replacement)
        postoffice so completed spans batch-ship to the collector.
        ``fresh`` (deployment construction) drops spans left over from a
        previous Simulation reusing the same node names — their
        round-derived trace ids would collide with this run's."""
        if self.config.trace_sample_every <= 0:
            return
        from geomx_tpu.trace import get_tracer

        tr = get_tracer(str(po.node))
        if fresh:
            tr.reset()
        tr.batch_events = self.config.trace_batch_events
        tr.attach(po)

    def flush_traces(self, timeout: float = 5.0) -> int:
        """Ship every node's pending spans and wait for the collector's
        event count to settle; returns the number of collected events."""
        if self.trace_collector is None:
            return 0
        from geomx_tpu.trace import get_tracer

        import time as _time

        for s in self.offices:
            get_tracer(s).flush()
        deadline = _time.monotonic() + timeout
        last = -1
        while _time.monotonic() < deadline:
            cur = len(self.trace_collector.merged_events())
            if cur == last:
                break
            last = cur
            _time.sleep(0.05)
        return last

    def dump_trace(self, path: str) -> dict:
        """Merged cross-node Chrome-trace JSON (see docs/tracing.md)."""
        assert self.trace_collector is not None, \
            "tracing off: set Config.trace_sample_every"
        self.flush_traces()
        return self.trace_collector.dump(path)

    def trace_report(self) -> dict:
        """Per-round critical-path report from the collector."""
        assert self.trace_collector is not None, \
            "tracing off: set Config.trace_sample_every"
        self.flush_traces()
        return self.trace_collector.critical_path()

    def pump_metrics(self, timeout: float = 5.0) -> int:
        """Ship one sample from every node's pump and wait for the
        collector to have ingested them; returns reports_received.
        The deterministic driver for ``obs_interval_s == 0`` tests."""
        assert self.metrics_collector is not None, \
            "telemetry off: set Config.enable_obs"
        import time as _time

        before = self.metrics_collector.reports_received
        sent = sum(1 for p in self.metrics_pumps.values() if p.ship())
        deadline = _time.monotonic() + timeout
        while (_time.monotonic() < deadline
               and self.metrics_collector.reports_received < before + sent):
            # a killed node's ship() can claim success into a dead van —
            # settle on "no growth" rather than the exact count
            cur = self.metrics_collector.reports_received
            _time.sleep(0.02)
            if self.metrics_collector.reports_received == cur >= before:
                _time.sleep(0.05)
                if self.metrics_collector.reports_received == cur:
                    break
        return self.metrics_collector.reports_received

    def dump_flight(self, out_dir: str,
                    incident: Optional[str] = None) -> List[str]:
        """Snapshot every LIVE node's flight-recorder ring to
        ``out_dir`` (killed nodes' vans are dead, so — like a real
        SIGKILL — they leave no dump; the postmortem assembler treats
        that absence as the finding).  ``incident=None`` is the
        exit-style dump (repeatable, overwrites); a named incident
        dumps at most once per node.  Returns the written paths."""
        paths = []
        for po in self.offices.values():
            fl = po.flight
            if fl is None or po.van.killed or not po._started:
                continue
            p = fl.dump(out_dir, incident=incident)
            if p:
                paths.append(p)
        return paths

    def cluster_state(self) -> dict:
        """The merged live cluster state (same composition the
        Ctrl.CLUSTER_STATE wire query and ``python -m geomx_tpu.status``
        render — see docs/observability.md)."""
        return self.state_service.compose()

    def worker(self, party: int, rank: int) -> WorkerKVStore:
        return self.workers[str(NodeId.parse(f"worker:{rank}@p{party}"))]

    def add_worker(self, party: int) -> WorkerKVStore:
        """Dynamically join a NEW worker to a running party (ref:
        ADD_NODE van.cc:41-112): stand up its postoffice on the live
        fabric, register with the party server, and return the client.
        The server folds it into each key's count at the next fresh
        round; the caller still has to init/pull its replica and start
        pushing (see WorkerKVStore.join_party).

        The out-of-plan NODE ID is chosen here, before the server sees
        the join (in a real deployment the operator picks it, e.g.
        ``--role worker:2@p0``); concurrent add_worker calls serialize
        the pick so two joiners can't collide on one id — the server's
        rank assignment itself is already lock-serialized."""
        with self._join_mu:
            rank = sum(1 for w in self.workers.values()
                       if w.party == party)
            n = NodeId.parse(f"worker:{rank}@p{party}")
            po = Postoffice(n, self.topology, self.fabric, self.config)
            po.start()
            self.offices[str(n)] = po
            kv = WorkerKVStore(po, self.config)
            self.workers[str(n)] = kv
            self._attach_tracer(po)
        kv.join_party()
        return kv

    def all_workers(self) -> List[WorkerKVStore]:
        return [self.workers[str(w)] for w in self.topology.all_workers()]

    # ---- targeted fault injection ---------------------------------------
    def _stamp_netfault(self, note: str, target, extra: int = 0):
        """Every injected cut/heal lands in the global scheduler's
        flight ring (FlightEv.NETFAULT) — postmortems separate INJECTED
        partitions from organic silence the same way CHURN events
        separate injected kills from crashes."""
        po = self.offices.get(str(self.topology.global_scheduler()))
        fl = getattr(po, "flight", None) if po is not None else None
        if fl is not None:
            from geomx_tpu.obs.flight import FlightEv

            fl.record(FlightEv.NETFAULT, a=extra,
                      peer=None if target is None else str(target),
                      note=note)

    def partition(self, a, b="*", symmetric: bool = True):
        """Cut the link a→b (both directions unless ``symmetric=False``)
        at the fabric, CONTROL TRAFFIC INCLUDED — heartbeats starve, so
        the failure detectors actually fire.  ``a``/``b`` are NodeIds or
        node strings; ``"*"`` wildcards.  ``partition(gs)`` with a
        single argument isolates exactly that node's links — what the
        shard-failure and split-brain soaks use instead of approximating
        with a global drop_rate."""
        from geomx_tpu.utils.metrics import system_counter

        self.fabric.fault.partition(str(a), str(b), symmetric=symmetric)
        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.partition_cuts").inc()
        self._stamp_netfault("netfault_cut", a)

    def heal(self, a=None, b=None, symmetric: bool = True):
        """Undo :meth:`partition` cuts (all of them with no args;
        ``symmetric=False`` restores only the a→b direction)."""
        from geomx_tpu.utils.metrics import system_counter

        self.fabric.fault.heal(None if a is None else str(a),
                               None if b is None else str(b),
                               symmetric=symmetric)
        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.partition_heals").inc()
        self._stamp_netfault("netfault_heal", a)

    def _wan_peers_of(self, party: int) -> List[str]:
        """The WAN-side endpoints of one party's local server: the
        global tier plus every OTHER party's server (inter-party TS
        relays) — everything a region-scoped blackhole must cut while
        leaving the party's own LAN intact."""
        t = self.topology
        peers = [str(t.global_scheduler())]
        peers += [str(n) for n in t.global_servers()]
        peers += [str(n) for n in t.standby_globals()]
        peers += [str(t.server(p)) for p in range(t.num_parties)
                  if p != party]
        return peers

    def partition_party(self, party: int, symmetric: bool = True):
        """Region outage: blackhole ``party``'s WAN uplink (its local
        server ↔ the global tier and every other party) while the
        party-internal LAN keeps working — workers keep pushing, the
        server keeps merging, only the up-stream goes dark.  This is
        the partition-tolerance soak's primary fault (ROADMAP item 5's
        "blackhole a whole region")."""
        srv = str(self.topology.server(party))
        self.fabric.fault.blackhole(srv, self._wan_peers_of(party),
                                    symmetric=symmetric)
        from geomx_tpu.utils.metrics import system_counter

        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.partition_cuts").inc()
        self._stamp_netfault("netfault_cut", srv, extra=party)

    def heal_party(self, party: int):
        """Undo :meth:`partition_party` — both directions of every WAN
        pair come back at once (a real uplink heal)."""
        srv = str(self.topology.server(party))
        for p in self._wan_peers_of(party):
            self.fabric.fault.heal(srv, p)
        from geomx_tpu.utils.metrics import system_counter

        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.partition_heals").inc()
        self._stamp_netfault("netfault_heal", srv, extra=party)

    def corrupt_link(self, a, b="*", rate: float = 1.0,
                     mode: str = "bitflip", seed: int = 0):
        """Seeded in-flight payload corruption on the link a→b: each
        data frame is serialized, damaged (single seeded bit flip or a
        seeded truncation — a deterministic per-rule tape) and decoded
        back at the fabric, the rot a flaky NIC/switch buffer inflicts
        on a real WAN.  The wire checksums (GEOMX_INTEGRITY_WIRE)
        detect it and the NACK fast-resend recovers; with the flag off
        the fabric's ``corrupt_delivered`` ledger counts how much
        damage would have reached the merge silently."""
        self.fabric.fault.corrupt(str(a), str(b), rate=rate, mode=mode,
                                  seed=seed)
        from geomx_tpu.utils.metrics import system_counter

        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.corruption_cuts").inc()
        self._stamp_netfault("netfault_corrupt", a)

    def heal_corrupt(self, a=None, b=None):
        """Undo :meth:`corrupt_link` rules (all of them with no args)."""
        self.fabric.fault.heal_corrupt(None if a is None else str(a),
                                       None if b is None else str(b))
        from geomx_tpu.utils.metrics import system_counter

        gsched = str(self.topology.global_scheduler())
        system_counter(f"{gsched}.corruption_heals").inc()
        self._stamp_netfault("netfault_corrupt_heal", a)

    def set_duplicate_rate(self, rate: float):
        """Message-duplication injection: each data message is
        re-delivered (a copy, ahead of the original) with probability
        ``rate`` — the at-least-once failure mode the replay-dedup
        windows must absorb."""
        self.fabric.fault.duplicate_rate = float(rate)

    def kill_global_server(self, rank: int = 0) -> GlobalServer:
        """Thread-level kill of a primary global server (SIGKILL-free):
        stop its postoffice — the van's receive loop and heartbeat
        thread die, so it processes nothing further and the global
        scheduler's dead-node table names it after the heartbeat
        timeout.  The failover smoke test's kill switch."""
        gs = self.global_servers[rank]
        gs.po.stop()
        return gs

    def kill_worker(self, party: int, rank: int) -> WorkerKVStore:
        """Thread-level SIGKILL of a worker: its van neither receives
        nor transmits (``Van.kill``), its heartbeat and client retry
        loop die, and NO leave message is sent — recovery is the party
        scheduler's eviction monitor's job.  ``kv.po.start()`` later
        revives the same incarnation as a ZOMBIE (same boot nonce) whose
        pushes the server fences until it rejoins."""
        kv = self.worker(party, rank)
        kv.worker._retry_stop.set()
        kv.po.van.kill()
        kv.po.stop()
        return kv

    def kill_local_server(self, party: int) -> LocalServer:
        """Thread-level SIGKILL of a party's local server: no leave, no
        checkpoint, the WAN up-link stops replaying.  The global
        scheduler's recovery monitor folds the party out of global
        rounds; ``restart_local_server`` brings up the replacement."""
        ls = self.local_servers[party]
        ls.up._retry_stop.set()
        ls.po.van.kill()
        ls.po.stop()
        return ls

    def _notice_rpc(self, sender_po: Postoffice, target, domain,
                    timeout: float):
        """Send Control.PREEMPT_NOTICE from ``sender_po`` and wait for
        the token-matched drain reply.  Returns the reply body plus the
        measured notice→drained latency, or None on timeout."""
        import threading
        import time as _time
        import uuid

        from geomx_tpu.transport.message import Control, Message

        assert self.config.enable_preempt, \
            "preempt notices off: set Config.enable_preempt"
        token = f"{sender_po.node}#{uuid.uuid4().hex[:8]}"
        cv = threading.Condition()
        reply: dict = {}

        def hook(msg) -> bool:
            b = msg.body if isinstance(msg.body, dict) else {}
            if (msg.control is Control.PREEMPT_NOTICE and not msg.request
                    and b.get("token") == token):
                with cv:
                    reply.update(b)
                    cv.notify_all()
                return True
            return False

        sender_po.add_control_hook(hook)
        t0 = _time.monotonic()
        try:
            sender_po.van.send(Message(
                recipient=target, control=Control.PREEMPT_NOTICE,
                domain=domain, request=True, body={"token": token}))
            with cv:
                if not cv.wait_for(lambda: bool(reply), timeout=timeout):
                    return None
        finally:
            sender_po.remove_control_hook(hook)
        out = dict(reply)
        out["latency_s"] = round(_time.monotonic() - t0, 4)
        return out

    def notice_worker(self, party: int, rank: int,
                      timeout: float = 30.0) -> Optional[dict]:
        """Deliver a spot-preemption notice to a worker over the wire
        (what a real preemption-notice daemon or SIGTERM mapping does):
        the worker finishes its in-flight step, flushes un-ACKed
        pushes, and leaves the party gracefully — the server folds it
        out immediately, no heartbeat-expiry stall.  Returns the drain
        reply ({ok, drain_s, latency_s}); the latency is the
        notice→member-folded reading the drain-latency acceptance
        judges.  Requires ``Config.enable_preempt``."""
        from geomx_tpu.transport.message import Domain

        sched = self.offices[str(self.topology.scheduler(party))]
        target = NodeId.parse(f"worker:{rank}@p{party}")
        return self._notice_rpc(sched, target, Domain.LOCAL, timeout)

    def notice_local_server(self, party: int,
                            timeout: float = 30.0) -> Optional[dict]:
        """Deliver a spot-preemption notice to a party's local server:
        it drains its WAN round, hands the party fold to the global
        tier proactively, and arms the recovery monitor's rejoin path
        for the replacement.  Requires ``Config.enable_preempt``."""
        from geomx_tpu.transport.message import Domain

        gsched = self.offices[str(self.topology.global_scheduler())]
        return self._notice_rpc(gsched, self.topology.server(party),
                                Domain.GLOBAL, timeout)

    def kill_replica(self, rank: int = 0) -> "ModelReplica":
        """Thread-level SIGKILL of a serve replica: its van neither
        receives nor transmits, its heartbeat and refresh pulls die —
        the replica monitor evicts it (subscriber views pruned at every
        shard) after the heartbeat timeout."""
        rep = self.replicas[rank]
        rep._stop.set()
        rep._wake.set()
        rep.up._retry_stop.set()
        rep.po.van.kill()
        rep.po.stop()
        return rep

    def restart_replica(self, rank: int) -> "ModelReplica":
        """Stand up a REPLACEMENT replica process (fresh postoffice,
        new boot incarnation, empty store — what a relaunched ``--role
        replica:K`` has).  Its first refresh pulls dense; the monitor
        logs the rejoin when its heartbeats resume."""
        from geomx_tpu.serve import ModelReplica

        n = self.topology.replica(rank)
        po = Postoffice(n, self.topology, self.fabric, self.config)
        rep = ModelReplica(po, self.config)
        po.start()
        self.offices[str(n)] = po
        self.replicas[rank] = rep
        self._attach_tracer(po)
        if self.config.enable_obs:
            from geomx_tpu.obs import MetricsPump

            old = self.metrics_pumps.pop(str(n), None)
            if old is not None:
                old.stop()
            self.metrics_pumps[str(n)] = MetricsPump(
                po, self.config, stats_fn=rep.stats)
        return rep

    def serve_balancer(self, replicas=None,
                       seed: int = 0) -> "ServeBalancer":
        """An out-of-plan balanced read frontend over the replica set
        (the wire path an inference frontend uses with the serving
        plane on).  Heartbeats off — a passive querier has no
        scheduler slot to ping."""
        import dataclasses

        from geomx_tpu.serve import ServeBalancer

        with self._join_mu:
            n = NodeId.parse(
                f"master_worker:{700 + len(self._serve_clients)}")
            cfg = dataclasses.replace(self.config,
                                      heartbeat_interval_s=0.0)
            po = Postoffice(n, self.topology, self.fabric, cfg)
            po.start()
            lb = ServeBalancer(po, cfg, replicas=replicas, seed=seed)
            self._serve_clients.append((lb, po))
        return lb

    def serve_client(self, replica_rank: int = 0) -> "ReplicaClient":
        """An out-of-plan read client against one replica (the wire
        path an inference frontend uses).  Heartbeats off — a passive
        querier has no scheduler slot to ping."""
        import dataclasses

        from geomx_tpu.serve import ReplicaClient

        # serialize id assignment: concurrent reader threads creating
        # clients must not collide on one out-of-plan node id
        with self._join_mu:
            n = NodeId.parse(
                f"master_worker:{700 + len(self._serve_clients)}")
            cfg = dataclasses.replace(self.config,
                                      heartbeat_interval_s=0.0)
            po = Postoffice(n, self.topology, self.fabric, cfg)
            po.start()
            client = ReplicaClient(po, cfg, replica=replica_rank)
            self._serve_clients.append((client, po))
        return client

    def reassign_shard(self, rank: int, target=None,
                       reason: str = "sim reassignment") -> bool:
        """Live key-range reassignment: move global shard ``rank``'s
        range onto ``target`` (its standby by default, or any live
        global server for a drain) through the epoch-fenced handoff
        protocol (``GlobalFailoverMonitor.reassign``).  Blocks until the
        handoff completed and the retarget broadcast went out."""
        if self.failover_monitor is None:
            from geomx_tpu.kvstore.replication import GlobalFailoverMonitor

            self.failover_monitor = GlobalFailoverMonitor(
                self.offices[str(self.topology.global_scheduler())])
            self.state_service.failover_monitor = self.failover_monitor
        t = None
        if target is not None:
            t = (target if isinstance(target, NodeId)
                 else NodeId.parse(str(target)))
        return self.failover_monitor.reassign(rank, t, reason=reason)

    def restart_local_server(self, party: int) -> LocalServer:
        """Stand up a REPLACEMENT local-server process for the party:
        fresh postoffice (new boot incarnation), empty store — exactly
        what a relaunched ``--role server:0@pK`` has.  The recovery
        monitor detects the resumed heartbeats, drives the warm-boot
        pull from the global tier, folds the party back in, and tells
        the workers to replay their un-ACKed requests."""
        n = self.topology.server(party)
        po = Postoffice(n, self.topology, self.fabric, self.config)
        ls = LocalServer(po, self.config)
        po.start()
        self.offices[str(n)] = po
        self.local_servers[party] = ls
        self._attach_tracer(po)
        if self.config.enable_obs:
            # the replacement ships under the same node name but a new
            # boot nonce — the collector fences its ring on the switch
            from geomx_tpu.obs import MetricsPump

            old = self.metrics_pumps.pop(str(n), None)
            if old is not None:
                old.stop()
            self.metrics_pumps[str(n)] = MetricsPump(
                po, self.config, stats_fn=ls.stats)
        return ls

    def set_wan_policy(self, compression: dict,
                       reason: str = "manual override") -> dict:
        """Manual override of the adaptive WAN policy: broadcast
        ``compression`` (e.g. ``{"type": "2bit"}``) under a fresh epoch
        through the same two-phase, fence-checked protocol the
        controller's automatic decisions use.  Requires
        ``Config.adaptive_wan``."""
        assert self.wan_controller is not None, \
            "adaptive WAN off: set Config.adaptive_wan"
        d = self.wan_controller.set_policy(compression, reason=reason)
        return {"epoch": self.wan_controller.epoch,
                "compression": d.compression}

    def process_threads(self) -> int:
        """Live OS threads in this process right now — the scaling
        reading ``bench.py --child parties`` records: O(nodes) under
        the thread-per-endpoint harness, O(1) under lightweight mode."""
        import threading

        return threading.active_count()

    def wan_bytes(self) -> dict:
        """Total WAN traffic (tier-2 links) across the deployment."""
        send = sum(ls.po.van.wan_send_bytes for ls in self.local_servers)
        send += sum(gs.po.van.wan_send_bytes for gs in self.global_servers)
        recv = sum(ls.po.van.wan_recv_bytes for ls in self.local_servers)
        recv += sum(gs.po.van.wan_recv_bytes for gs in self.global_servers)
        return {"wan_send_bytes": send, "wan_recv_bytes": recv}

    def shutdown(self):
        for p in self.metrics_pumps.values():
            p.stop()
        if self.health is not None:
            self.health.stop()
        self.state_service.stop()
        if self.metrics_collector is not None:
            self.metrics_collector.stop()
        if self.wan_controller is not None:
            self.wan_controller.stop()
        if self.trace_collector is not None:
            self.trace_collector.stop()
        if self.failover_monitor is not None:
            self.failover_monitor.stop()
        for m in self.eviction_monitors:
            m.stop()
        if self.recovery_monitor is not None:
            self.recovery_monitor.stop()
        if self.replica_monitor is not None:
            self.replica_monitor.stop()
        if self.replica_autoscaler is not None:
            self.replica_autoscaler.stop()
        for client, po in self._serve_clients:
            client.stop()
            po.stop()
        for rep in self.replicas:
            rep.stop()
        if self.master is not None:
            self.master.stop()
        for w in self.workers.values():
            w.stop()
        for s in self.local_servers:
            s.stop()
        for s in self.global_servers + self.standby_globals:
            s.stop()
        for po in self.offices.values():
            po.stop()
        self.fabric.shutdown()
