#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline", ...extras}.

Round-1 failure mode (BENCH_r01.json rc=1, parsed null): the axon TPU
tunnel flaked during backend init and one exception killed the run.
This harness therefore:

- runs every device benchmark in a **subprocess** with a hard timeout
  and retry/backoff, so a hung backend init (observed: jax.devices()
  blocking >2 min) can never wedge the whole bench;
- always runs the CPU-only WAN codec benchmark, so even a dead tunnel
  still yields a real number (the reference's headline is WAN-traffic
  reduction, README.md:21-45);
- on TPU failure emits the WAN figure as the primary metric plus an
  "error" field — never rc!=0, never an empty line.

Benchmarks:
- **cnn**   CIFAR-10-shape CNN images/sec/chip (BASELINE.md metric #1).
  The step loop runs on-device via lax.scan — one dispatch per
  measurement — because the axon tunnel adds O(100ms) per Python
  dispatch, which would measure the tunnel, not the chip.
- **mfu**   flagship transformer (models/transformer.py) fwd+bwd+adam,
  bf16: achieved TFLOP/s vs the chip's peak (VERDICT r1 item 1).
- **quant** on-chip pallas 2-bit quantization throughput vs the host
  C++/numpy codec (VERDICT r1 item 2).
- **wan**   WAN bytes/step per codec config on the full two-tier stack
  (CPU, in-proc sim).

vs_baseline: BASELINE.md's north star is >=0.9x the per-chip throughput
of an A100 running the reference CUDA build on the same CNN.  No A100
is reachable (zero egress), so the A100 reference is **derived**, not
measured: images/sec = EFF_A100 * A100_PEAK_BF16 / CNN_FLOPS_PER_IMAGE,
with the assumed efficiency stated in the output.  For the tiny
2-conv/3-dense CNN the honest statement is that both chips are
launch/input-bound; the FLOP-derived bound with a generous efficiency
is an upper estimate of the reference, making vs_baseline conservative.
"""

import argparse
import functools
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent
sys.path.insert(0, str(ROOT))

BATCH = 4096        # measured: throughput saturates at 4096 (584k img/s
#                     vs 302k at 1024 — the tiny CNN is HBM-bound and
#                     needs the batch to amortize per-step overheads)
STEPS = 32          # per on-device scan segment
A100_PEAK_BF16 = 312e12
A100_SXM_BW = 2039e9   # A100-SXM 80GB HBM2e
A100_PCIE_BW = 1555e9  # A100 40GB HBM2
V5E_PEAK_BF16 = 197e12  # TPU v5e (device reports "TPU v5 lite")
V5E_BW = 819e9


# --------------------------------------------------------------------------
# children (each runs in its own subprocess; prints one JSON line)
# --------------------------------------------------------------------------

def _cnn_flops_per_image():
    """Analytic fwd FLOPs/image of models/cnn.py's CNN at 32x32x3; the
    train step is ~3x fwd (fwd + 2x in bwd).  (XLA's cost_analysis is
    not usable here: over the axon AOT backend it omits the conv
    custom-calls and reports only the dense flops.)"""
    f = 0.0
    # conv1: 32x32x3 -> 32x32x32, 3x3;  conv2: pool-> 16x16x64, 3x3
    f += 2 * 32 * 32 * 32 * (3 * 3 * 3)
    f += 2 * 16 * 16 * 64 * (3 * 3 * 32)
    # dense: flatten 8*8*64=4096 -> 128 -> 64 -> 10 (models/cnn.py)
    f += 2 * (8 * 8 * 64) * 128 + 2 * 128 * 64 + 2 * 64 * 10
    return 3.0 * f


# per-image activation tensor sizes (elements) of the demo CNN
_CNN_T = dict(x=32 * 32 * 3, y1=32 * 32 * 32, p1=16 * 16 * 32,
              y2=16 * 16 * 64, p2=8 * 8 * 64, d1=128, d2=64, lg=10)
_CNN_PARAMS = (27 * 32 + 32) + (288 * 64 + 64) + \
    (4096 * 128 + 128) + (128 * 64 + 64) + (64 * 10 + 10)


def _cnn_bytes_per_image(act_b: float, fused: bool, batch: int) -> float:
    """HBM traffic per image of one train step, from a per-op table.

    ``act_b``: activation dtype bytes (2=bf16, 4=fp32).  ``fused``:
    True models an XLA-style executor (pointwise ops — relu, cast, bias
    — fused into the adjacent conv/pool/dense kernel, so they cost no
    extra HBM round-trip); False models the reference's MXNet 1.x
    executor, where each relu fwd/bwd is its own CUDA kernel that
    re-reads and re-writes the activation (MXNet's pointwise fuser only
    merges chains of pointwise ops; a lone relu between conv and pool
    stays a kernel).  Conv/pool/dense boundaries are never fused on
    either stack.  Input x stays fp32 (4B) in all scenarios.
    """
    T = _CNN_T
    b = 0.0
    # conv1: read x fp32, write y1
    b += T["x"] * 4 + T["y1"] * act_b
    if not fused:                       # relu1 kernel: r+w y1
        b += 2 * T["y1"] * act_b
    b += (T["y1"] + T["p1"]) * act_b    # pool1
    b += (T["p1"] + T["y2"]) * act_b    # conv2
    if not fused:
        b += 2 * T["y2"] * act_b        # relu2
    b += (T["y2"] + T["p2"]) * act_b    # pool2
    b += (T["p2"] + T["d1"]) * act_b    # dense1
    if not fused:
        b += 2 * T["d1"] * act_b
    b += (T["d1"] + T["d2"]) * act_b    # dense2
    if not fused:
        b += 2 * T["d2"] * act_b
    b += (T["d2"] + T["lg"]) * act_b    # dense3
    b += 2 * T["lg"] * act_b            # softmax+loss
    # bwd
    b += 2 * T["lg"] * act_b                                # dloss
    b += (T["lg"] + T["d2"] + T["d2"]) * act_b              # dense3 bwd
    if not fused:
        b += 3 * T["d2"] * act_b
    b += (T["d2"] + T["d1"] + T["d1"]) * act_b              # dense2 bwd
    if not fused:
        b += 3 * T["d1"] * act_b
    b += (T["d1"] + T["p2"] + T["p2"]) * act_b              # dense1 bwd
    b += (T["p2"] + T["y2"] + T["y2"]) * act_b              # pool2 bwd (mask)
    if not fused:
        b += 3 * T["y2"] * act_b                            # relu2 bwd
    b += (T["y2"] + T["p1"]) * act_b                        # conv2 dx
    b += (T["p1"] + T["y2"]) * act_b                        # conv2 dw
    b += (T["p1"] + T["y1"] + T["y1"]) * act_b              # pool1 bwd
    if not fused:
        b += 3 * T["y1"] * act_b                            # relu1 bwd
    b += T["x"] * 4 + T["y1"] * act_b                       # conv1 dw
    # adam: read g,p,m,v; write p,m,v — fp32, amortized over the batch
    b += _CNN_PARAMS * 4 * 7 / batch
    return b


def child_cnn():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from geomx_tpu.models import create_cnn_state

    rng = jax.random.PRNGKey(0)
    model, params, _ = create_cnn_state(
        rng, input_shape=(BATCH, 32, 32, 3), num_classes=10)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def loss_fn(p, x, y):
        logits = model.apply(p, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def step(carry, _):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(p, s):
        (p, s), losses = jax.lax.scan(step, (p, s), None, length=STEPS)
        return p, s, losses[-1]

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (BATCH, 32, 32, 3), dtype=np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 10, BATCH, dtype=np.int32))

    # compile + warmup; scalar readback is the sync point (on the remote
    # tunnel block_until_ready can return before execution finishes)
    params, opt_state, loss = run_steps(params, opt_state)
    _ = float(loss)

    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, opt_state, loss = run_steps(params, opt_state)
        _ = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)

    ips = BATCH * STEPS / best_dt

    # ---- A100 reference derivation (no A100 is reachable; BASELINE.md:
    # the reference repo publishes no throughput numbers either).  The
    # tiny CNN is HBM-bound on any modern chip (arithmetic intensity
    # ~50 FLOP/byte << both chips' ridge points), so the roofline is the
    # bandwidth one.  Method: compute per-op HBM traffic tables for (a)
    # our XLA execution and (b) the reference's MXNet-1.x execution
    # (unfused pointwise kernels; fp32 activations as its examples run,
    # plus a bf16-granted variant), calibrate the achievable bandwidth
    # fraction from OUR measured throughput, and grant the reference the
    # same fraction on A100 — i.e. the reference is modeled with
    # XLA-grade kernel efficiency and only pays for its own executor's
    # memory traffic.  Every input is a spec sheet number, a measured
    # number, or an auditable per-op count (_cnn_bytes_per_image).
    flops_img = _cnn_flops_per_image()
    xla_bytes = _cnn_bytes_per_image(2, fused=True, batch=BATCH)
    f_bw = ips * xla_bytes / V5E_BW        # our achieved HBM fraction

    # The reference is granted a FIXED 0.70 HBM fraction per kernel (the
    # practical ceiling of well-tuned bandwidth-bound CUDA kernels; its
    # executor's inefficiency is the extra traffic, already counted in
    # the per-op tables) — NOT our measured fraction.  Granting the
    # measured fraction would cancel ips out of the ratio entirely,
    # making vs_baseline blind to real regressions on our side.
    EFF_REF_BW = 0.70
    EFF_REF_FLOPS = 0.25

    def a100_ips(act_b, fused, bw, flop_peak):
        byt = _cnn_bytes_per_image(act_b, fused, BATCH)
        t_bytes = byt / (EFF_REF_BW * bw)
        t_flops = flops_img / (EFF_REF_FLOPS * flop_peak)
        return 1.0 / max(t_bytes, t_flops), byt

    # per-scenario matmul peak: fp32 convs on A100 run TF32 tensor cores
    # at best (156 TF; generous — the as-published cu80/cu101 builds
    # predate A100 and TF32 entirely); bf16 scenarios get the 312 TF
    # bf16 peak
    A100_TF32 = 156e12
    scen = {}
    for name, (act_b, fused, fpk) in {
        "reference_as_published_fp32": (4, False, A100_TF32),
        "reference_granted_bf16": (2, False, A100_PEAK_BF16),
        "hypothetical_xla_grade_peer": (2, True, A100_PEAK_BF16),
    }.items():
        sxm, byt = a100_ips(act_b, fused, A100_SXM_BW, fpk)
        pcie, _ = a100_ips(act_b, fused, A100_PCIE_BW, fpk)
        scen[name] = {
            "bytes_per_image": round(byt, 1),
            "a100_sxm80_ips": round(sxm, 1),
            "a100_pcie40_ips": round(pcie, 1),
            "vs_0.9x_sxm80": round(ips / (0.9 * sxm), 3),
            "vs_0.9x_pcie40": round(ips / (0.9 * pcie), 3),
        }
    primary = scen["reference_as_published_fp32"]["vs_0.9x_sxm80"]
    print(json.dumps({
        "images_per_sec": round(ips, 1),
        "vs_baseline": primary,
        "a100_ref_derivation": {
            "method": ("bandwidth roofline, per-op traffic tables; "
                       "reference granted a fixed 0.70 HBM fraction per "
                       "kernel + 0.25 matmul-peak fraction (see bench.py)"),
            "primary": "reference_as_published_fp32 on A100-SXM 80GB",
            "granted_ref_hbm_fraction": EFF_REF_BW,
            "measured_tpu_hbm_fraction": round(f_bw, 3),
            "tpu_xla_bytes_per_image": round(xla_bytes, 1),
            "cnn_train_flops_per_image": flops_img,
            "scenarios": scen,
        },
        "timing": "best_of_3_min, 32-step on-device scan",
        "batch": BATCH,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }))


# flagship MFU config: MXU-friendly shapes, fits v5e 16 GB with adam.
# attn_impl='flash' (pallas fused attention, no materialized probs) at
# batch 4 measured best on-chip: 84.5 TFLOP/s vs 82.8 for bf16-dense
# at batch 2 and 76.8 for the fp32-dense r1 config; batch 8/16(+remat)
# and seq 4096 all measured lower (see PROGRESS notes).
MFU_CFG = dict(vocab=8192, d_model=2048, n_heads=16, n_layers=8,
               d_ff=8192, max_seq=2048, attn_impl="flash")
MFU_BATCH = 4
MFU_STEPS = 8


def _transformer_train_flops_per_step(cfg, batch, seq):
    """Standard 6*N*T + attention-matmul term (12*L*T*seq*d_model*3 for
    fwd+bwd), counting the train step (fwd + 2x bwd)."""
    n_params = (cfg["vocab"] * cfg["d_model"]          # embed (tied head)
                + cfg["max_seq"] * cfg["d_model"]      # pos
                + cfg["n_layers"] * 12 * cfg["d_model"] ** 2)
    tokens = batch * seq
    dense = 6.0 * n_params * tokens
    attn = 12.0 * cfg["n_layers"] * tokens * seq * cfg["d_model"]
    return dense + attn, n_params


def child_mfu():
    import jax
    import jax.numpy as jnp
    import optax

    from geomx_tpu.models.transformer import (
        TransformerConfig, init_params, lm_loss, make_apply)

    cfg = TransformerConfig(**MFU_CFG)
    params = init_params(cfg, jax.random.PRNGKey(0))
    apply_fn = make_apply(cfg)
    tx = optax.adam(1e-4)
    opt_state = tx.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (MFU_BATCH, MFU_CFG["max_seq"]), 0,
        MFU_CFG["vocab"], dtype=jnp.int32)

    def step(carry, _):
        p, s = carry
        loss, grads = jax.value_and_grad(
            lambda p_: lm_loss(apply_fn, p_, tokens))(p)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(p, s):
        (p, s), losses = jax.lax.scan(step, (p, s), None, length=MFU_STEPS)
        return p, s, losses[-1]

    params, opt_state, loss = run_steps(params, opt_state)
    _ = float(loss)
    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, opt_state, loss = run_steps(params, opt_state)
        _ = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)

    flops_per_step, n_params = _transformer_train_flops_per_step(
        MFU_CFG, MFU_BATCH, MFU_CFG["max_seq"])
    achieved = flops_per_step * MFU_STEPS / best_dt
    platform = jax.devices()[0].platform
    peak = V5E_PEAK_BF16 if platform in ("tpu", "axon") else None
    print(json.dumps({
        "achieved_tflops": round(achieved / 1e12, 2),
        "peak_tflops": peak and peak / 1e12,
        "mfu": peak and round(achieved / peak, 4),
        "model": (f"transformer d{MFU_CFG['d_model']} L{MFU_CFG['n_layers']} "
                  f"ff{MFU_CFG['d_ff']} seq{MFU_CFG['max_seq']} "
                  f"batch{MFU_BATCH} bf16 ({n_params/1e6:.0f}M params)"),
        "tokens_per_sec": round(
            MFU_BATCH * MFU_CFG["max_seq"] * MFU_STEPS / best_dt, 1),
        "platform": platform,
    }))


QUANT_MB = 64


def child_quant():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from geomx_tpu.ops.quantize import dequantize_2bit_tpu, quantize_2bit_tpu

    n = QUANT_MB * (1 << 20) // 4
    g = jnp.asarray(np.random.default_rng(0).standard_normal(n).astype(np.float32))
    r = jnp.zeros_like(g)

    packed, newr = quantize_2bit_tpu(g, r)          # compile + correctness
    out = dequantize_2bit_tpu(packed, n)
    _ = float(out[0]); _ = float(newr[0])
    # spot-check round-trip semantics on-device
    gi = np.asarray(g[:4096]); oi = np.asarray(out[:4096])
    expect = np.where(gi > 0.5, 0.5, np.where(gi < -0.5, -0.5, 0.0))
    if not np.allclose(oi, expect):
        raise AssertionError("on-chip 2bit round-trip mismatch")

    # time the kernel with an ON-DEVICE scan loop: one Python dispatch
    # per measurement, so the axon tunnel's O(100ms) dispatch latency is
    # excluded (round-1 style per-call timing measured the tunnel: it
    # reported ~300 MB/s for a kernel that actually streams at GB/s)
    reps = 32

    @jax.jit
    def run_reps(g, r):
        def body(r, _):
            packed, r = quantize_2bit_tpu(g, r)
            return r, packed[0]
        r, lasts = jax.lax.scan(body, r, None, length=reps)
        return r, lasts[-1]

    rr, last = run_reps(g, r)      # compile + warmup
    _ = float(last)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        rr, last = run_reps(g, r)
        _ = float(last)
        best = min(best, time.perf_counter() - t0)
    dev_dt = best / reps

    # host codec throughput for comparison
    from geomx_tpu.compression.codecs import TwoBitCodec
    codec = TwoBitCodec(threshold=0.5)
    gh = np.asarray(g)
    codec.compress(0, gh)                            # residual warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        codec.compress(0, gh)
    host_dt = (time.perf_counter() - t0) / reps

    print(json.dumps({
        "tpu_quant_mbps": round(QUANT_MB / dev_dt, 1),
        "host_quant_mbps": round(QUANT_MB / host_dt, 1),
        "payload_mb": QUANT_MB,
        "platform": jax.devices()[0].platform,
        "roundtrip": "ok",
    }))


def child_overlap():
    """P3 staged-overlap vs BSP step time under a serialized WAN uplink
    (in-proc sim; VERDICT r1 item 3).  Thin wrapper over the shared
    harness in geomx_tpu.overlap — the regression test runs the same
    code, so benchmark and test cannot drift apart."""
    from geomx_tpu.overlap import overlap_vs_bsp_benchmark

    res = overlap_vs_bsp_benchmark()
    res["bsp_s_per_step"] = round(res["bsp_s_per_step"], 4)
    res["overlap_s_per_step"] = round(res["overlap_s_per_step"], 4)
    res["speedup"] = round(res["speedup"], 3)
    print(json.dumps(res))


def child_stress():
    """Server merge throughput at scale (VERDICT r1 item 5): one party of
    4 workers pushing a 50M-element tensor (200 MB) through the two-tier
    stack; reports merged GB/s per local server and the native threaded
    axpy's raw rate."""
    import numpy as np

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation
    from geomx_tpu.native import bindings

    N = 50_000_000
    rounds = 2
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=4)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(N, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        g = np.ones(N, np.float32)
        t0 = time.perf_counter()
        for _ in range(rounds):
            for w in ws:
                w.push(0, g)
            ws[0].pull_sync(0)
            for w in ws:
                w.wait_all()
        dt = time.perf_counter() - t0

        # native threaded axpy microbenchmark (the merge hot loop)
        acc = np.zeros(N, np.float32)
        t1 = time.perf_counter()
        bindings.accumulate(acc, g)
        axpy_dt = time.perf_counter() - t1
        print(json.dumps({
            "tensor_elems": N,
            "rounds": rounds,
            "round_s": round(dt / rounds, 3),
            "server_merged_gb_per_s": round(
                len(ws) * (N * 4 / 1e9) * rounds / dt, 3),
            "native_axpy_gb_per_s": round((N * 4 / 1e9) / axpy_dt, 2),
            "native_available": bindings.available(),
        }))
    finally:
        sim.shutdown()


def child_wan():
    """WAN bytes/step per codec config (in-proc sim, 2 parties x 1 worker —
    topology doesn't change the per-party WAN payload, codecs do)."""
    import numpy as np

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation

    N_BIG, N_SMALL = 400_000, 50_000
    STEPS_W = 4
    configs = {
        "vanilla": None,
        "fp16": {"type": "fp16"},
        "2bit": {"type": "2bit", "threshold": 0.5},
        "bsc": {"type": "bsc", "ratio": 0.01},
        "mpq": {"type": "mpq", "ratio": 0.01, "size_bound": 200_000},
    }
    out = {}
    for name, comp in configs.items():
        sim = Simulation(Config(
            topology=Topology(num_parties=2, workers_per_party=1)))
        try:
            ws = sim.all_workers()
            rng = np.random.default_rng(0)
            for w in ws:
                w.init(0, np.zeros(N_BIG, np.float32))
                w.init(1, np.zeros(N_SMALL, np.float32))
            ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
            if comp is not None:
                for p in range(2):
                    sim.worker(p, 0).set_gradient_compression(comp)
            base = sim.wan_bytes()["wan_send_bytes"]
            for _ in range(STEPS_W):
                for tid, nel in ((0, N_BIG), (1, N_SMALL)):
                    g = rng.standard_normal(nel).astype(np.float32)
                    for w in ws:
                        w.push(tid, g)
                for w in ws:
                    w.pull_sync(0)
                    w.pull_sync(1)
            out[name] = (sim.wan_bytes()["wan_send_bytes"] - base) / STEPS_W
        finally:
            sim.shutdown()
    print(json.dumps({
        "bytes_per_step": {k: round(v, 1) for k, v in out.items()},
        "reduction": {k: round(out["vanilla"] / v, 2)
                      for k, v in out.items() if v > 0},
    }))


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

def _run_child(name: str, timeout: float, env_extra=None):
    env = dict(os.environ)
    env.pop("BENCH_CHILD", None)
    if env_extra:
        env.update(env_extra)
    try:
        p = subprocess.run(
            [sys.executable, str(ROOT / "bench.py"), "--child", name],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or p.stdout or "").strip().splitlines()[-6:]
        return None, f"rc={p.returncode}: " + " | ".join(tail)
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no JSON in child output"


def _run_tpu_child(name: str, timeout: float, attempts: int = 2,
                   backoff: float = 20.0):
    err = None
    for i in range(attempts):
        if i:
            time.sleep(backoff)
        res, err = _run_child(name, timeout)
        if res is not None:
            return res, None
    return None, err


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child",
                    choices=["cnn", "mfu", "quant", "wan", "overlap",
                             "stress"])
    ap.add_argument("--wan", action="store_true",
                    help="legacy: run only the WAN codec benchmark")
    ap.add_argument("--skip-tpu", action="store_true")
    args = ap.parse_args()

    if args.child:
        # route a CPU request through jax.config: the sandbox's
        # sitecustomize imports jax at interpreter start, so the env var
        # alone is too late and a dead TPU tunnel would hang the child
        from geomx_tpu.core.platform import apply_platform_from_env
        apply_platform_from_env()
        {"cnn": child_cnn, "mfu": child_mfu, "quant": child_quant,
         "wan": child_wan, "overlap": child_overlap,
         "stress": child_stress}[args.child]()
        return

    cpu_env = {"JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "cpu"}
    wan, wan_err = _run_child("wan", timeout=300, env_extra=cpu_env)

    if args.wan:  # legacy single-benchmark mode: WAN codec numbers only
        print(json.dumps({
            "metric": "wan_bytes_per_step",
            "value": wan and wan["bytes_per_step"]["vanilla"],
            "unit": "bytes/step (vanilla; see configs)",
            "vs_baseline": None,
            "configs": wan and wan["bytes_per_step"],
            "reduction": wan and wan["reduction"],
            "error": wan_err,
        }))
        return

    overlap, overlap_err = _run_child("overlap", timeout=300,
                                      env_extra=cpu_env)
    stress, stress_err = _run_child("stress", timeout=600,
                                    env_extra=cpu_env)

    errors = {}
    cnn = mfu = quant = None
    if not args.skip_tpu:
        # the cnn child runs first and doubles as the tunnel probe:
        # jax.devices() has been observed to hang for minutes when the
        # tunnel is down, and the subprocess timeout contains that
        cnn, err = _run_tpu_child("cnn", timeout=420)
        if err:
            errors["cnn"] = err
        mfu, err = _run_tpu_child("mfu", timeout=600)
        if err:
            errors["mfu"] = err
        quant, err = _run_tpu_child("quant", timeout=420)
        if err:
            errors["quant"] = err
    if wan_err:
        errors["wan"] = wan_err
    if overlap_err:
        errors["overlap"] = overlap_err
    if stress_err:
        errors["stress"] = stress_err

    if cnn is not None:
        record = {
            "metric": "cifar10_cnn_images_per_sec_per_chip",
            "value": cnn["images_per_sec"],
            "unit": "images/sec/chip",
            "vs_baseline": cnn["vs_baseline"],
            "a100_ref_derivation": cnn["a100_ref_derivation"],
            "device": cnn.get("device"),
        }
    elif mfu is not None:
        record = {
            "metric": "transformer_achieved_tflops",
            "value": mfu["achieved_tflops"],
            "unit": "TFLOP/s",
            "vs_baseline": None,
        }
    else:
        record = {
            "metric": "wan_bytes_per_step",
            "value": wan and wan["bytes_per_step"]["vanilla"],
            "unit": "bytes/step (vanilla; see configs)",
            "vs_baseline": None,
            "error": "TPU benchmarks unavailable (see errors)",
        }
    if mfu:
        record["mfu"] = mfu
    if quant:
        record["quantize"] = quant
    if wan:
        record["wan"] = wan
    if overlap:
        record["overlap"] = overlap
    if stress:
        record["stress"] = stress
    if errors:
        record["errors"] = errors
    print(json.dumps(record))


if __name__ == "__main__":
    main()
