"""Hierarchical Parameter Server: local (tier-1) and global (tier-2) servers.

This replaces the reference's single 2000-line handler class
(ref: src/kvstore/kvstore_dist_server.h) with explicit per-key state
machines, as SURVEY.md §7 mandates.  The FSA data flow it implements
(ref call stack: kvstore_dist_server.h:1213-1366, 899-957, 974-1169):

  worker push ──► LocalServer: accumulate; ack worker immediately
      when all party workers pushed:
        merged gradient ──► zpush to global shards  [WAN]
        all global ACKs  ──► zpull updated weights  [WAN]
        pull response    ──► store; serve parked worker pulls
  worker pull ──► served from store when no round is in flight,
                  else parked (the reference spins on initialized_,
                  ref :1721-1723 — we park event-driven instead)

  GlobalServer: accumulate pushes from local servers; when all
  num_global_workers arrived → run optimizer → respond the parked
  pushes (the ACK is the "update done" signal, ref :1302-1319).
  Async mode (MixedSync): update per push immediately, DCASGD optional
  (ref :1519-1698).

Compression: configured via Ctrl.SET_COMPRESSION like the reference's
kSetGradientCompression; the geomx_tpu.compression codecs apply on the
push-up path (per-key, grouped by codec) and on pull responses
(per-subscriber sparsified deltas / fp16), with unknown types rejected
loudly.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from geomx_tpu.compression.codecs import CodecError
from geomx_tpu.core.config import Config, Group, NodeId, Topology
from geomx_tpu.kvstore.backend import _adopt_or_copy, make_merge_backend
from geomx_tpu.kvstore.common import (APP_PS, Cmd, Ctrl, RecentRequests,
                                      codec_pool, codec_pool_depth,
                                      make_merge_lanes)
from geomx_tpu.native.bindings import accumulate as _native_accumulate
from geomx_tpu.obs.flight import FlightEv, attach_server_pressure
from geomx_tpu.optim import DCASGD, ServerOptimizer, Sgd, make_optimizer
from geomx_tpu.ps import KVPairs, KVServer, KVWorker, Postoffice
from geomx_tpu.ps.postoffice import split_range
from geomx_tpu.trace import context as _tctx
from geomx_tpu.transport.message import Control, Domain, Message


def _ctx_bound(fn):
    """Carry the calling (handler) thread's trace context onto a merge
    lane: a sampled round's merge spans — and the WAN push-up messages
    the lane sends at round completion — must stay children of the
    inbound push, or sharding would sever every cross-node chain.
    Free when tracing is off (returns ``fn`` itself)."""
    if not _tctx.ACTIVE:
        return fn
    ctx = _tctx.current()
    if ctx is None:
        return fn

    def bound():
        prev = _tctx.swap(ctx)
        try:
            fn()
        finally:
            _tctx.restore(prev)

    return bound


def _handle_profiler_cmd(po: Postoffice, msg: Message, server: KVServer):
    """Remote profiler control on a server (ref: GeoMX's
    ProcessServerProfilerCommands kvstore_dist_server.h:409-456 — workers
    configure/start/pause/dump server profilers; dumps are node-prefixed
    like the reference's rank-prefixed filenames)."""
    from geomx_tpu.utils import get_profiler

    p = get_profiler(str(po.node))
    body = msg.body or {}
    action = body.get("action")
    if action == "config":
        p.configure(process_name=body.get("process_name"))
    elif action == "state":
        p.start() if body.get("run") else p.pause()
    elif action == "pause":
        p.pause()
    elif action == "reset":
        p.reset()
    elif action == "dump":
        prefix = body.get("path", "profile")
        safe = str(po.node).replace(":", "_").replace("@", "_")
        p.dump(f"{prefix}.{safe}.json")
    server.reply_cmd(msg, body=p.stats())


def _store_payload(arrs: List[np.ndarray]) -> np.ndarray:
    """Serve stored weights by read-only alias instead of copying.

    In-proc delivery is by reference, so a response must never expose a
    mutable view of live server state.  r3 isolated responses with a
    full copy (~0.27 s per 200 MB response on this single-core host);
    now the server FREEZES the stored array (``writeable=False``) and
    ships it as-is.  The freeze is permanent: every in-place mutation
    path (BSC pull decode is the only one) copies-on-write when it meets
    a frozen array, so any number of in-flight responses may alias the
    frozen buffer safely, and receivers may adopt a frozen payload as
    their own replica without a copy (see ``Message.donated`` for the
    ownership rules of *mutable* payloads)."""
    if len(arrs) == 1 and arrs[0].dtype == np.float32:
        arrs[0].flags.writeable = False  # freeze in place (idempotent)
        return arrs[0]
    # multi-key responses concatenate — the concat IS the isolation
    # copy, so the source arrays stay writeable (freezing them here
    # would buy nothing and force a COW copy on every later in-place
    # decode of those keys).  The sharded LocalServer assembles its
    # multi-key responses per key under each stripe instead of calling
    # this (same one-copy result, tear-safe without the big lock).
    return np.concatenate([np.asarray(a, np.float32) for a in arrs])


class WeightStore(dict):
    """``GlobalServer.store`` — a dict whose raw entries are host
    ndarrays OR device-resident weight handles
    (:class:`geomx_tpu.kvstore.jax_backend.DeviceWeight`, duck-typed by
    "not an ndarray, has .host()").

    Reads through the mapping interface always hand back a host f32
    array: ``store[k]`` / ``.get`` / ``.items()`` materialize a device
    entry on demand (one D2H, cached in the handle until the next
    round close replaces it) — which makes every existing host
    consumer (pull serving, dissemination, checkpoint/replication/
    handoff snapshots, the pull compressor) an explicit
    *materialization event* without touching its code.  Paths that
    must NOT pay a D2H use the raw accessors: ``.values()`` stays raw
    (both entry kinds expose ``.nbytes`` — the stats accounting),
    ``.length(k)`` reads a length without materializing, ``.raw(k)``
    hands the round close the device handle.  Plain host writes
    (``store[k] = arr``) simply replace the handle — the host array
    becomes the truth and the next device round re-adopts it."""

    def __getitem__(self, k):
        v = dict.__getitem__(self, k)
        if isinstance(v, np.ndarray):
            return v
        return v.host()

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def items(self):
        return [(k, self[k]) for k in self]

    def raw(self, k):
        return dict.__getitem__(self, k)

    def length(self, k) -> int:
        return len(dict.__getitem__(self, k))


def _mutable(arr: np.ndarray) -> np.ndarray:
    """THE gate for in-place mutation of a stored array.

    ``_store_payload`` freezes served arrays permanently
    (``writeable=False``); any path that writes a store entry in place
    must pass it through here first — a frozen array gets a
    copy-on-write, a writeable one passes through.  Writing without
    this gate raises "assignment destination is read-only" at runtime
    (numpy enforces the freeze), so a missed call is loud, but route
    new mutation paths here anyway so the invariant lives in one place.
    Paths that REPLACE a store entry (``store[k] = new_array``, e.g.
    the optimizer result — ``ServerOptimizer.update`` never writes
    ``weight`` in place) need no gate."""
    return arr if arr.flags.writeable else arr.copy()


class _KeyState:
    """Per-ps-key aggregation state on the local server."""

    __slots__ = ("accum", "count", "parked_pulls", "in_flight", "version",
                 "round", "row_sparse", "epoch", "priority", "expected",
                 "completing", "contributors", "hfa_inv")

    def __init__(self):
        self.accum: Optional[np.ndarray] = None
        self.count = 0
        self.parked_pulls: List[Message] = []
        self.in_flight = 0       # rounds between push-up and weights-back.
        #                          A COUNTER, not a bit: back-to-back
        #                          pushes launch overlapping WAN rounds of
        #                          one key, and round r's completion must
        #                          not serve pulls parked behind round r+1
        #                          with stale weights
        self.version = 0         # completed rounds (local or global)
        self.round = 0           # completed aggregation rounds (HFA K2 gate)
        self.row_sparse = False  # merged grad is mostly-zero rows
        self.epoch = 0           # bumped by overwrite-inits: a pull-down
        #                          from before the bump must not clobber
        #                          the restored value of THIS key
        self.expected = None     # workers this key's CURRENT round waits
        #                          for; seeded from the server's join-
        #                          adjusted target at each fresh round
        self.priority = 0        # P3: workers' push priority, inherited by
        #                          this key's WAN push-up and pull-down so
        #                          shallow layers outrank deep ones on the
        #                          server uplinks too (ref: P3_ZPush
        #                          priority propagation kv_app.h:204-259)
        self.contributors: set = set()  # senders in the OPEN round.
        #                          Pulls from NON-contributors are served
        #                          from the last completed round instead
        #                          of parking: a dynamic joiner's
        #                          bootstrap pulls must not wait on
        #                          rounds that can only complete with the
        #                          joiner's own push (advisor r4 high),
        #                          and a lagging worker asking for round
        #                          r while r+1 accumulates wants exactly
        #                          the r weights the store holds
        self.hfa_inv = 0.0       # HFA: Σ num_merge/n_i over this round's
        #                          contributions (each push announces the
        #                          denominator n_i it pre-scaled by).  At
        #                          completion the accumulated Σ w_i/n_i is
        #                          divided by this sum — a convex
        #                          renormalization that keeps the party
        #                          "mean" an actual mean across dynamic
        #                          membership (joiner scaled by new n,
        #                          statics by old n) AND when a leave
        #                          completes a round short (c < n pushes
        #                          would otherwise shrink the weights by
        #                          c/n — catastrophic for weights, unlike
        #                          a scaled gradient)
        self.completing = False  # round completion DECIDED but the
        #                          accumulator not yet taken.  Set under
        #                          _mu at the decision point; both
        #                          completion deciders (push handler,
        #                          leave fold) skip slated keys, so a
        #                          push deciding outside the lock and a
        #                          concurrent leave cannot both run
        #                          _round_complete on one key (the second
        #                          would crash on the taken accumulator)


class LocalServer:
    """Tier-1 aggregator; dual identity: KVServer to its party's workers
    (LOCAL domain) + KVWorker toward the global servers (GLOBAL domain)
    (ref: dual node identity van.h:98, postoffice.cc:40)."""

    def __init__(self, postoffice: Postoffice, config: Optional[Config] = None):
        self.po = postoffice
        self.config = config or postoffice.config
        topo = postoffice.topology
        self.num_workers = topo.workers_per_party
        # dynamic worker join (ref: ADD_NODE van.cc:41-112 — the
        # reference's scheduler assigns ids at runtime; our addressing is
        # plan-based, so the party SERVER owns rank assignment and the
        # aggregation count).  ``_workers_target`` is adopted per key at
        # the next fresh aggregation round (_KeyState.expected), never
        # mid-round.
        self._join_next_rank = topo.workers_per_party
        self._workers_target = self.num_workers
        # out-of-plan members' advertised TCP addresses, rebroadcast so
        # peers/schedulers can dial them (TS relays, ask replies)
        self._member_addrs: Dict[str, tuple] = {}
        # monotone stamp on membership broadcasts: two concurrent
        # join/leave broadcasts can arrive out of order, and the workers'
        # 1/num_workers pre-scale must converge to the LATEST target, not
        # whichever send raced last (advisor r4 low)
        self._membership_seq = 0
        # membership registry, seeded with the STATIC plan's workers so
        # a plan worker can leave too (idempotency: a replayed
        # join/leave must not move the count twice)
        self._members: Dict[str, int] = {
            str(w): w.rank
            for w in topo.workers(postoffice.node.party)}
        # out-of-plan joiners that have not yet pushed ANYTHING: their
        # bootstrap pulls mid-partial-merge are served from the last
        # completed round (parking them behind a round that may need
        # their own push is the advisor-r4 deadlock).  Every OTHER
        # member — plan workers included, whether or not they ever
        # pushed this key directly (under the TS push overlay
        # non-elected workers never do) — PARKS during a TS-merged
        # partial round instead of reading stale (advisor r5, round-5
        # refinement).  GIL-atomic set ops; cleared on first push.
        self._bootstrapping: set = set()
        self.joined_workers = 0  # observability
        self.left_workers = 0
        # heartbeat-driven eviction (kvstore/eviction.py): members the
        # party scheduler declared dead and folded out, mapped to the
        # boot incarnation observed at eviction.  Pushes from an evicted
        # identity are FENCED (error, not accumulated — a zombie's late
        # push would otherwise complete rounds early against the lowered
        # target) until it rejoins through the dynamic-join door, which
        # assigns a fresh rank and lifts the fence.
        self._evicted: Dict[str, int] = {}
        self.evicted_workers = 0
        self.eviction_fenced_pushes = 0
        # gradient hygiene (Config.integrity_push_screen; docs/
        # deployment.md "Data integrity"): every push payload is
        # screened for NaN/Inf (and, under poison_mag_max, magnitude)
        # before it can touch an accumulator.  A poisoned push merges
        # ZERO contribution — it still counts toward round completion,
        # so one faulty worker cannot stall the party barrier — and its
        # sender gets a typed error instead of the ack.  At
        # poison_quarantine_n strikes the sender is folded out through
        # the REVERSIBLE quarantine machinery (rank stashed,
        # incarnation NOT fenced) — quarantine, not eviction: a node
        # whose NaNs came from a transient (bad batch, flaky HBM) heals
        # back in via unquarantine; a truly poisoned one stays folded
        # out without zombie-fence complications.
        self._poison_strikes: Dict[str, int] = {}
        self.integrity_poison_rejects = 0
        self.poison_quarantines = 0
        self.integrity_codec_rejects = 0
        # local-server recovery: REJOIN warm boots served (observability)
        self.warm_boots = 0
        self._rejoin_waiters: List[Message] = []
        self._warm_boot_busy = False
        # graceful preemption drain (Control.PREEMPT_NOTICE): a noticed
        # local server drains its in-flight WAN round, hands its party
        # fold to the global tier proactively (the reversible EVICT
        # fold, so the PR 2 rejoin path brings the replacement back),
        # and tells the recovery monitor the fold already happened.
        # Hook registered only under Config.enable_preempt.
        self.preempt_server_drains = 0
        self.last_drain_s: Optional[float] = None
        self._wan_inflight = 0  # WAN push batches awaiting group acks
        self._preempt_waiters: List[Message] = []
        self._preempt_busy = False
        # partition tolerance (Config.enable_partition_mode; docs/
        # deployment.md "Partition tolerance").  Quarantined WORKERS:
        # members the party scheduler folded out reversibly — rank
        # stashed for restore, incarnation NOT fenced.  Quarantined
        # SELF: when this server's own WAN uplink goes dark (a stuck
        # un-ACKed push with no ack progress for the degrade window),
        # it keeps closing party rounds DEGRADED — the merged gradient
        # accumulates into a bounded per-key catch-up delta against
        # FROZEN weights (DC-ASGD compensates the staleness at the
        # merge) — and the heal ships one staleness-stamped Cmd.CATCHUP
        # push instead of discarding the party's progress behind a
        # dense warm boot.
        self._quarantined_members: Dict[str, int] = {}  # node -> rank
        self._partition_mode = bool(self.config.enable_partition_mode)
        self._degraded = False
        self._catchup: Dict[int, np.ndarray] = {}
        self._catchup_rounds = 0
        self._catchup_since: Optional[float] = None
        self._catchup_invalid = False  # HFA rounds push weights, not
        #                                gradients — delta semantics
        #                                break, heal must dense-resync
        self.degraded_rounds = 0
        self.catchup_pushes = 0
        self.catchup_fallbacks = 0
        self._wan_progress_t = time.monotonic()
        self._degrade_window = (
            self.config.partition_degrade_s
            or max(self.config.heartbeat_timeout_s, 1.0))
        self.store: Dict[int, np.ndarray] = {}
        self._keys: Dict[int, _KeyState] = {}
        # key-sharded server state: ``stripe(k)`` guards key k's merge /
        # pull / store entry; ``with self._mu:`` is the all-stripes
        # barrier every membership fold, fence, snapshot and config
        # change takes — their decide-under-lock semantics (PR 1-2) are
        # unchanged.  server_shards=1 (the deterministic default, and
        # the auto default on 1-core hosts) collapses both to the old
        # single server RLock with inline merges.
        # pluggable merge engine for the lanes below (kvstore/backend.py:
        # numpy = the host reference path, jax = staged device merge;
        # deterministic forces numpy).  The lanes themselves are built
        # per-backend — a device backend caps how many can usefully run.
        self._backend = make_merge_backend(self.config,
                                           str(postoffice.node))
        # device-resident WAN codec stage (ISSUE 20): non-None iff the
        # jax backend is active and codec_device resolves on — encode
        # then reads the device merge accumulator directly and the only
        # D2H is the wire-ready compressed payload
        self._codec_stage = self._backend.make_codec_stage(self.config)
        self._mu, self._shards = make_merge_lanes(
            self.config, postoffice.node, self._backend)
        self._ctr_mu = threading.Lock()  # leaf lock for shared counters
        #                                  bumped from parallel lanes
        from geomx_tpu.trace.recorder import get_tracer
        from geomx_tpu.utils import get_profiler

        self._prof = get_profiler(str(postoffice.node))
        self._tr = get_tracer(str(postoffice.node))
        # flight recorder (obs/flight.py): fence/fold/round events +
        # this server's merge-pressure sources; None when disabled
        self._flight = postoffice.flight
        attach_server_pressure(self._flight, self._mu, self._shards)
        if self._flight is not None:
            self._flight.record(FlightEv.MERGE_BACKEND, a=self._mu.n,
                                note=self._backend.name)
        self._recent = RecentRequests()  # replayed-push dedup
        self.server = KVServer(APP_PS, 0, postoffice, self._handle)
        self.server.cmd_handler = self._on_cmd
        postoffice.add_control_hook(self._on_add_node)
        # crash-tolerant membership: forced leaves from the party
        # scheduler's eviction monitor + warm-boot rejoin after a crash
        postoffice.add_control_hook(self._on_evict)
        postoffice.add_control_hook(self._on_rejoin)
        if self.config.enable_preempt:
            postoffice.add_control_hook(self._on_preempt)
        # global-tier failover: the scheduler's NEW_PRIMARY broadcast
        # retargets the up-link and replays un-ACKed WAN requests
        self.failover_events = 0
        self._primary_terms: Dict[int, int] = {}
        postoffice.add_control_hook(self._on_new_primary)
        # warm the axpy-vs-numpy calibration OFF the locked merge path
        from geomx_tpu.native.bindings import calibrate_async

        calibrate_async(self.config.server_merge_threads)
        # the "global worker" half (ref: kvstore_dist_server.h uses the
        # server's own KVWorker toward tier 2)
        self.up = KVWorker(
            APP_PS, 1, postoffice,
            targets=topo.global_servers(),
            key_ranges=split_range(topo.num_global_servers),
            domain=Domain.GLOBAL,
        )
        self.sync_mode = self.config.sync_mode
        # HFA (ref: kvstore_dist_server.h:185-187,1324-1343).  In HFA mode
        # workers push *mean weights* (not gradients); every k2-th round the
        # milestone delta (merged - milestone)/num_global_workers crosses
        # the WAN and is applied additively at tier 2.
        self.hfa_enabled = self.config.use_hfa
        self.hfa_k2 = self.config.hfa_k2
        self._milestone: Dict[int, np.ndarray] = {}
        self._saw_row_sparse = False
        # per-key pull-view version, echoed to the global tier on every
        # pull-down so compressed (BSC) responses can detect a desynced
        # tracked view and resync dense (BroadcastCompressor.compress)
        self._pull_ver: Dict[int, int] = {}
        # per-key weight version of the last APPLIED pull-down ("wv"
        # stamp from GlobalServer._weight_wv); a strictly-older late
        # response is dropped instead of rolling the replica back
        self._weight_ver: Dict[int, int] = {}
        # feature observability (acceptance runs + QUERY_STATS)
        self.hfa_gated_key_rounds = 0  # K2-gated (key, round) pairs
        self.ts_deliveries = 0      # inter-party overlay deliveries adopted
        self.stale_pull_skips = 0   # out-of-order pull responses skipped
        self._esync = None  # EsyncState, lazily built on first Ctrl.ESYNC
        self.compression: dict = {"type": "none"}
        self.push_codec = None  # set by Ctrl.SET_COMPRESSION
        # adaptive WAN control plane (geomx_tpu/control).  This server
        # is the SENDER side of the epoch protocol: SET_WAN_POLICY lands
        # as _policy_pending and is applied atomically at the next WAN
        # round boundary (_push_up_send), every gradient push is stamped
        # with the current epoch, and a receiver's policy fence is
        # answered by re-encoding the stashed raw gradients under the
        # newer policy and retrying.  Off (default): one flag check per
        # round, no stash, no stamping.
        self._adaptive = bool(self.config.adaptive_wan)
        self._policy_epoch = 0
        self._policy_pending: Optional[dict] = None
        self.wan_push_rounds = 0      # WAN push-up batches (controller's
        #                               round-rate signal, via QUERY_STATS)
        self.policy_fence_retries = 0  # fenced pushes re-encoded+retried
        self.policy_drops = 0          # fence retries abandoned (loud)
        if self._adaptive:
            self._policy_stash: Dict[int, dict] = {}  # up-ts -> entry
            self.up.error_handler = self._on_up_error
        # TSEngine intra-party dissemination (ref: DefaultAutoPull
        # kvstore_dist_server.h:1368-1384)
        self.ts_client = None
        self._ts_iter = 0
        if self.config.enable_intra_ts:
            from geomx_tpu.sched.tsengine import TsClient

            self.ts_client = TsClient(
                postoffice, topo.scheduler(postoffice.node.party))
        # inter-party TSEngine: the WAN pull-down is replaced by overlay
        # dissemination from the global servers; this client relays onward
        # to sibling local servers (ref: inter-DC TS — server-side
        # WorkersMerge/AutoPullUpdate, kvstore_dist_server.h:228-310)
        self.ts_inter = None
        if self.config.enable_inter_ts:
            from geomx_tpu.sched.tsengine import TsClient

            self.ts_inter = TsClient(
                postoffice, topo.global_scheduler(), domain=Domain.GLOBAL)
        # inter-party push overlay: pair-merge party gradients over the
        # WAN before one elected server pushes up (ref: global ASK_PUSH
        # van.cc:1254-1310; server-side WorkersMerge :228-310)
        self.ts_push_inter = None
        self._inter_push_round: Dict[int, int] = {}
        if self.config.enable_inter_ts_push:
            import queue as _queue

            from geomx_tpu.sched.ts_push import TsPushWorker

            self.ts_push_inter = TsPushWorker(
                postoffice, topo.global_scheduler(), self.up,
                domain=Domain.GLOBAL)
            # merging blocks on WAN round-trips (ask → maybe wait for a
            # peer's grads); it must run OFF the KVServer handler thread,
            # which processes the incoming relays themselves
            self._merge_q: "_queue.Queue" = _queue.Queue()
            threading.Thread(target=self._inter_merge_loop, daemon=True,
                             name=f"inter-merge-{postoffice.node}").start()
        # WAN-silence watchdog (partition mode only): detects this
        # server's OWN partition — a push-up whose group acks stopped
        # arriving — and flips to degraded-mode rounds so the party
        # keeps training instead of wedging on the dead uplink
        self._degrade_ticker = None
        if self._partition_mode:
            from geomx_tpu.transport.reactor import Periodic

            self._degrade_ticker = Periodic(
                max(self._degrade_window / 4.0, 0.05),
                self._degrade_sweep,
                name=f"degrade-watchdog-{postoffice.node}",
                reactor=getattr(postoffice.van.fabric, "reactor", None))

    # ---- request handling ---------------------------------------------------
    def _handle(self, msg: Message, kvs: Optional[KVPairs], server: KVServer):
        prof = self._prof
        if msg.cmd == Cmd.INIT:
            with prof.span("local.init"):
                self._handle_init(msg, kvs)
        elif msg.cmd == Cmd.ROW_SPARSE_PUSH:
            with prof.span("local.push_rs"):
                self._handle_push_row_sparse(msg, kvs)
        elif msg.cmd == Cmd.ROW_SPARSE_PULL:
            with prof.span("local.pull_rs"):
                self._try_serve_pull(msg)
        elif msg.cmd == Cmd.TS_AUTOPULL:
            with prof.span("local.ts_inter"):
                self._on_inter_ts_delivery(msg, kvs)
        elif self.ts_push_inter is not None and self._is_merge_relay(msg):
            # a peer local server's contribution for the push overlay —
            # routed here because the KVServer owns the PS app id
            self.ts_push_inter._on_merge_msg(msg)
        elif msg.push:
            # the tracer span nests inside the profiler span: same
            # buffer, but the tracer one carries the causal ids and is
            # gated on the round's sampling, not on profiler.running
            with prof.span("local.push"), self._tr.span("local.push"):
                self._handle_push(msg, kvs)
            if prof.running:
                prof.count("push_bytes", float(msg.nbytes))
        elif msg.pull:
            with prof.span("local.pull"), self._tr.span("local.pull"):
                self._handle_pull(msg, kvs)

    def _handle_init(self, msg: Message, kvs: KVPairs):
        # program order vs. the sharded merge: an overwrite-INIT that
        # arrived after earlier pushes must not be applied while those
        # pushes still sit queued on merge lanes (they would merge into
        # the restored state); quiesce the lanes first
        self._shards.drain()
        # replay dedup: a replayed overwrite-init re-applied after
        # training resumed would silently revert the store (plain init
        # replay was idempotent; overwrite replay is destructive)
        state = self._recent.check(msg)
        if state == "pending":
            return
        if state == "done":
            self.server.response(msg, body=self._recent.done_body(msg))
            return
        overwrite = bool(isinstance(msg.body, dict)
                         and msg.body.get("overwrite"))
        with self._mu:
            fresh = []
            for k, v in kvs.slices():
                if k not in self.store or overwrite:
                    self.store[k] = np.array(v, copy=True)
                    self._milestone[k] = np.array(v, copy=True)
                    st = self._keys.setdefault(k, _KeyState())
                    if overwrite:
                        # abort THIS key's in-flight round: drop the
                        # aggregation state, and invalidate any pull-down
                        # still in flight for the old weights (epoch)
                        st.accum = None
                        st.count = 0
                        st.in_flight = 0
                        st.epoch += 1
                        # the global tier rebuilds its pull compressor on
                        # overwrite (tracked vers → 0) with this value as
                        # the INIT base; echo 0 re-enters the
                        # sparse-from-INIT path consistently
                        self._pull_ver[k] = 0
                        self._weight_ver.pop(k, None)
                    fresh.append((k, v))
            # pulls that raced ahead of init can be servable now
            for k, _ in fresh:
                self._drain_parked_locked(self._keys[k])
        if fresh:
            # forward first-seen (or overwritten) inits up; ack the
            # worker once tier 2 has them
            ks = np.array([k for k, _ in fresh], dtype=np.int64)
            vals = np.concatenate([v for _, v in fresh])
            lens = np.array([len(v) for _, v in fresh], dtype=np.int64)
            def ack():
                self._recent.mark_done(msg)
                self.server.response(msg)

            self.up.zpush(
                KVPairs(ks, vals, lens), cmd=Cmd.INIT,
                on_complete=ack,
                body=msg.body if overwrite else None,
            )
        else:
            self._recent.mark_done(msg)
            self.server.response(msg)

    def _on_add_node(self, msg: Message) -> bool:
        """Dynamic worker join (ref: ProcessAddNodeCommandAtScheduler
        van.cc:41-112).  A new worker registers mid-training; the server
        assigns the next free rank and raises the aggregation target,
        which every key adopts at its NEXT fresh round (open rounds'
        targets are raised too, so a racing static push can't complete
        them early).  The joiner's bootstrap pulls are safe because
        pulls from non-contributors are served from the last completed
        round (_try_serve_pull_locked) — they never park behind rounds
        that only the joiner's own push can complete.  Works under the
        intra-party TS overlay (the membership broadcast updates the
        schedulers' member sets) and under HFA (the per-push ``hfa_n``
        denominator lets the round renormalize a mixed-scale weight
        mean; see _KeyState.hfa_inv) — the reference's ADD_NODE is
        likewise uniform across modes (van.cc:41-112)."""
        if msg.control is not Control.ADD_NODE or not msg.request:
            return False
        body = msg.body or {}
        node_s = str(body.get("node", msg.sender))
        if body.get("action") == "leave":
            # graceful leave (the inverse fold): the worker promises no
            # further pushes.  Mid-flight rounds get their target
            # lowered; ones already satisfied complete NOW — they would
            # otherwise stall forever waiting for the leaver.  Honest
            # caveat: counting has no per-worker attribution, so if the
            # leaver HAD contributed to a mid-flight round, one later
            # push leaks into the next round (one stale gradient, the
            # same staleness class the async tier tolerates).
            with self._mu:
                if self._fold_member_out_locked(node_s):
                    self.left_workers += 1
                # replayed leave (or never-joined): idempotent no-op —
                # the reply still carries the current (total, seq) pair
                total = self._workers_target
                seq = self._membership_seq
            self._broadcast_membership()
            # the reply carries the SAME (total, seq) pair as broadcasts
            # — the client applies it through the same stale-guard, so a
            # reply built before a racing membership change cannot roll
            # the pre-scale back after the newer broadcast landed
            self.po.van.send(msg.reply_to(control=Control.ADD_NODE, body={
                "num_workers": total, "seq": seq,
                "token": body.get("token")}))
            return True
        with self._mu:
            # a rejoin through the join door lifts the eviction fence —
            # the node re-enters the count under a FRESH rank (its old
            # membership entry was deleted at eviction), so there is no
            # double count to fear
            self._evicted.pop(node_s, None)
            if node_s in self._members:
                # replayed join (client retry after a lost reply): same
                # rank, no double count
                rank = self._members[node_s]
                total = self._workers_target
                seq = self._membership_seq
            else:
                rank = self._join_next_rank
                self._join_next_rank += 1
                self._workers_target += 1
                self._membership_seq += 1
                self._members[node_s] = rank
                total = self._workers_target
                seq = self._membership_seq
                self.joined_workers += 1
                # until its first push lands, this joiner's pulls are
                # BOOTSTRAP pulls: served from the last completed round
                # even mid-partial-merge (see _try_serve_pull)
                self._bootstrapping.add(node_s)
                # mid-flight rounds must ALSO wait for the joiner: its
                # first pushes land in whatever round is open, and with
                # the old target a static worker's push would complete
                # the round early and leak a contribution forward.  The
                # joiner's own BOOTSTRAP pulls do not park behind those
                # now-waiting rounds — _try_serve_pull_locked serves
                # non-contributors from the last completed round, which
                # is what breaks the advisor-r4 join deadlock (pull
                # before first push).  Honest transition caveat:
                # contributions already in the open round were
                # pre-scaled by the OLD 1/num_workers, the joiner's by
                # the new one, so that single round's applied update is
                # up to (1 + 1/old_n - 1/new_n)x the true mean — the
                # same one-round transient class as the leave-side push
                # leak and async staleness
                for st in self._keys.values():
                    if (st.accum is not None and st.expected
                            and not st.completing):
                        st.expected += 1
        # TCP deployments announce the joiner's bind address alongside;
        # add_address inserts the OUT-OF-PLAN slot (update_address would
        # ignore an unknown node as a stale broadcast, so it is no
        # fallback here).  The address is also recorded for membership
        # broadcasts: under the TS overlay PEERS relay to the joiner and
        # the SCHEDULER replies to its asks, so every party node's
        # fabric needs the out-of-plan slot, not just this server's
        if "host" in body and "node" in body:
            addr = (body["host"], int(body["port"]))
            with self._mu:
                self._member_addrs[str(body["node"])] = addr
            add = getattr(self.po.van.fabric, "add_address", None)
            if add is not None:
                add(body["node"], addr)
        self._broadcast_membership()
        # seq rides the reply for the same reason as on leave replies
        self.po.van.send(msg.reply_to(control=Control.ADD_NODE, body={
            "rank": rank, "num_workers": total, "seq": seq,
            "token": body.get("token")}))
        return True

    def _fold_member_out_locked(self, node_s: str) -> bool:
        """Remove ``node_s`` from the aggregation group and fold
        mid-flight rounds down to the survivor set: lower each open
        round's target, complete rounds the fold made decidable (they
        would otherwise stall forever waiting for the gone member).
        The shared core of graceful leave and heartbeat eviction.
        Caller holds ``_mu``; returns False for a non-member (replayed
        leave / double eviction)."""
        if node_s not in self._members:
            return False
        del self._members[node_s]
        self._member_addrs.pop(node_s, None)
        self._bootstrapping.discard(node_s)
        # ESync planner hygiene: forget the departed worker's step/comm
        # estimates — a slow leaver's stale step_s would otherwise stay
        # in the max reach-time target forever, permanently inflating
        # every survivor's assignment (the fold IS the replan trigger;
        # a joiner is seeded at min_steps until its first report)
        if self._esync is not None:
            self._esync.drop(node_s)
        if self._flight is not None:
            self._flight.record(FlightEv.FOLD, peer=node_s,
                                note="member_fold")
        self._workers_target = max(1, self._workers_target - 1)
        self._membership_seq += 1
        completed = []
        for k, st in self._keys.items():
            if st.accum is not None and st.expected:
                st.expected = max(1, st.expected - 1)
                if st.count >= st.expected and not st.completing:
                    st.completing = True
                    completed.append(k)
        if completed:
            # complete UNDER the lock (RLock re-entry); keys a
            # concurrent push already slated (st.completing) were
            # skipped above — without the flag both paths would
            # run _round_complete for one key and the second
            # would crash on the already-taken accumulator
            self._round_complete(completed)
        return True

    def _on_evict(self, msg: Message) -> bool:
        """Control.EVICT from the party scheduler's eviction monitor: a
        worker's heartbeats expired, so synthesize the leave it never
        sent (same fold as a graceful leave), then FENCE the evicted
        identity — the scheduler recorded the corpse's last ``boot``
        incarnation, and any later push from it (zombie resume, or a
        silent restart that skipped the join door) is rejected with a
        rejoin hint instead of corrupting the lowered round counts.
        ``join_party`` lifts the fence with a fresh rank.  Idempotent."""
        if msg.control is not Control.EVICT or not msg.request:
            return False
        body = msg.body if isinstance(msg.body, dict) else {}
        action = body.get("action")
        if action in ("quarantine", "unquarantine") and "node" in body:
            return self._on_quarantine(msg, body, action)
        if "node" not in body or action:
            return False  # party_fold/unfold belong to the global tier
        node_s = str(body["node"])
        boot = int(body.get("boot", 0))
        with self._mu:
            folded = self._fold_member_out_locked(node_s)
            if folded:
                self.evicted_workers += 1
            self._evicted.setdefault(node_s, boot)
            # a quarantine that escalated to an eviction: the reversible
            # fold already happened, the fence above makes it final
            self._quarantined_members.pop(node_s, None)
            total = self._workers_target
        if folded:
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.evicted_workers").inc()
            print(f"{self.po.node}: evicted {node_s} (forced leave, "
                  f"boot={boot}) — pushes fenced until it rejoins",
                  flush=True)
            self._broadcast_membership()
        self.po.van.send(msg.reply_to(control=Control.EVICT, body={
            "evicted": folded, "num_workers": total,
            "token": body.get("token")}))
        return True

    def _on_quarantine(self, msg: Message, body: dict, action: str) -> bool:
        """Control.EVICT {action: quarantine|unquarantine} from the
        party scheduler's monitor: the member is unreachable from the
        scheduler but an indirect probe still hears it — fold it out of
        round targets REVERSIBLY (its rank is stashed, its incarnation
        is NOT fenced; a LAN-reachable quarantined member's pushes
        still accumulate, at worst completing a lowered-target round
        early) and restore it verbatim when heartbeats resume.
        Idempotent both ways."""
        node_s = str(body["node"])
        with self._mu:
            if action == "quarantine":
                rank = self._members.get(node_s)
                changed = self._fold_member_out_locked(node_s)
                if changed and rank is not None:
                    self._quarantined_members[node_s] = rank
                ok = changed or node_s in self._quarantined_members
            else:
                rank = self._quarantined_members.pop(node_s, None)
                changed = (rank is not None
                           and node_s not in self._members)
                if changed:
                    self._members[node_s] = rank
                    self._workers_target += 1
                    self._membership_seq += 1
                ok = changed or node_s in self._members
            total = self._workers_target
        if changed:
            if self._flight is not None:
                self._flight.record(FlightEv.NETFAULT, peer=node_s,
                                    note=f"member_{action}")
            print(f"{self.po.node}: {action}d {node_s} — "
                  f"{total} workers count toward fresh rounds, "
                  "incarnation not fenced", flush=True)
            self._broadcast_membership()
        self.po.van.send(msg.reply_to(control=Control.EVICT, body={
            "ok": ok, "num_workers": total,
            "token": body.get("token")}))
        return True

    def _fence_evicted_push(self, msg: Message, sender_s: str) -> bool:
        """Reject a push from an evicted identity (caller already passed
        the replay-dedup check, so pre-eviction pushes re-ack normally).
        Returns True when the push was fenced and answered.

        Lock-free fast path: membership transitions are rare, dict
        lookups are GIL-atomic, and a push racing an eviction lands as
        if ordered before or after it either way — only a positive
        sighting re-checks under the barrier (the all-stripes
        acquisition here per push would otherwise re-serialize the
        sharded merge)."""
        if sender_s not in self._evicted or sender_s in self._members:
            return False
        with self._mu:
            if sender_s not in self._evicted or sender_s in self._members:
                return False
            boot = self._evicted[sender_s]
            self.eviction_fenced_pushes += 1
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.eviction_fenced_pushes").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.FENCE, d=boot, peer=sender_s,
                                note="evicted_push")
        err = {"error": f"evicted: {sender_s} was declared dead "
                        f"(boot={boot}) and folded out of the "
                        "aggregation group; rejoin via join_party for a "
                        "fresh rank"}
        self._recent.mark_done(msg, err)
        self.server.response(msg, body=err)
        return True

    def _poison_strike(self, sender_s: str) -> dict:
        """Record one poison strike against ``sender_s``; quarantine it
        (reversible fold, PR-16 machinery) once the strike count
        crosses ``poison_quarantine_n``.  Returns the typed error body
        the push's ack path sends instead of a clean ack."""
        quarantined = False
        with self._mu:
            self.integrity_poison_rejects += 1
            strikes = self._poison_strikes.get(sender_s, 0) + 1
            self._poison_strikes[sender_s] = strikes
            n = self.config.poison_quarantine_n
            if n and strikes >= n and sender_s in self._members:
                rank = self._members.get(sender_s)
                if self._fold_member_out_locked(sender_s):
                    if rank is not None:
                        self._quarantined_members[sender_s] = rank
                    self.poison_quarantines += 1
                    quarantined = True
            quarantined_total = len(self._quarantined_members)
        from geomx_tpu.utils.metrics import system_counter, system_gauge

        system_counter(f"{self.po.node}.integrity_poison_rejects").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.CORRUPT, a=strikes,
                                peer=sender_s, note="poison_push")
        if quarantined:
            system_counter(f"{self.po.node}.poison_quarantines").inc()
            system_gauge(f"{self.po.node}.quarantined_nodes").set(
                quarantined_total)
            if self._flight is not None:
                self._flight.record(FlightEv.CORRUPT, a=strikes,
                                    peer=sender_s,
                                    note="poison_quarantine")
            print(f"{self.po.node}: quarantined {sender_s} after "
                  f"{strikes} poisoned pushes — folded out reversibly, "
                  "unquarantine heals it back in", flush=True)
            self._broadcast_membership()
        return {"error": f"poisoned push rejected: payload failed the "
                         f"finiteness/magnitude screen (strike "
                         f"{strikes}); contribution zeroed"
                         + (", sender quarantined" if quarantined
                            else "")}

    def _screen_push(self, msg: Message, kvs: KVPairs) -> KVPairs:
        """Gradient-hygiene gate on the push ingest path (one fused
        backend reduction; the jax backend syncs a single device
        scalar).  A clean payload passes through untouched; a poisoned
        one is replaced with zeros — zero contribution keeps the sync
        round's completion accounting intact — and the typed error body
        rides to the ack via ``msg._gx_poisoned``."""
        if not self.config.integrity_push_screen:
            return kvs
        if self._backend.screen_finite(kvs.vals,
                                       self.config.poison_mag_max):
            return kvs
        msg._gx_poisoned = self._poison_strike(str(msg.sender))
        return KVPairs(kvs.keys, np.zeros(len(kvs.vals), np.float32),
                       kvs.lens)

    def _on_rejoin(self, msg: Message) -> bool:
        """Control.REJOIN request from the global scheduler's recovery
        monitor: this (replacement or revived) local server must adopt
        the global tier's current model state before its party folds
        back into global rounds.  The pull blocks on WAN round-trips, so
        it runs off the hook thread; the reply is sent on completion —
        the monitor retries until it hears one, and retries while a boot
        is in flight just queue behind it (idempotent)."""
        if msg.control is not Control.REJOIN or not msg.request:
            return False
        with self._mu:
            self._rejoin_waiters.append(msg)
            if self._warm_boot_busy:
                return True
            self._warm_boot_busy = True
        threading.Thread(target=self._warm_boot_thread, daemon=True,
                         name=f"warm-boot-{self.po.node}").start()
        return True

    def _warm_boot_thread(self):
        mode = "dense"
        try:
            n = None
            if self._partition_mode and (self._degraded or self._catchup
                                         or self._catchup_rounds):
                # this process SURVIVED the partition with live state — a
                # bounded catch-up delta re-merges it; a genuinely crashed
                # replacement has neither flag set and dense-boots below
                n = self._ship_catchup()
                if n is not None:
                    mode = "catchup"
            if n is None:
                n = self.warm_boot()
            ok = True
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "%s: warm boot failed", self.po.node)
            n, ok = 0, False
        with self._mu:
            waiters, self._rejoin_waiters = self._rejoin_waiters, []
            self._warm_boot_busy = False
        for m in waiters:
            try:
                self.po.van.send(m.reply_to(control=Control.REJOIN, body={
                    "ok": ok, "keys": n, "mode": mode,
                    "token": (m.body or {}).get("token")}))
            except (KeyError, OSError):
                pass  # the monitor re-asks

    def warm_boot(self) -> int:
        """Adopt the global tier's full model state: ask each shard for
        its hosted key set (Ctrl.LIST_KEYS), pull those keys DENSE (a
        fresh replica has no view for a compressed delta to apply to),
        and install them — aborting any stale in-flight aggregation
        state (a revived zombie's open rounds refer to a world that
        moved on).  Returns the number of keys adopted."""
        self._shards.drain()  # stale pre-crash merges must not land on
        #                       the adopted state
        keys = set()
        for gs in list(self.up.targets):
            # retried + timeout-bounded: control commands have no
            # replay layer, and a RELAUNCHED process's first sends can
            # race the peers' stale half-open conns to its dead
            # predecessor — a reply lost to a broken-then-redialed
            # socket would wedge the warm boot (and with it every
            # queued REJOIN) forever.  LIST_KEYS is read-only, so the
            # re-send is harmless; the fresh send also forces the
            # fabric's redial to the live incarnation.
            reply = None
            for _ in range(8):
                ts = self.up.send_cmd(gs, Ctrl.LIST_KEYS,
                                      domain=Domain.GLOBAL, wait=False)
                try:
                    self.up.customer.wait(ts, timeout=2.5)
                    reply = self.up.cmd_response(ts)
                    break
                except TimeoutError:
                    continue
            if reply is None:
                # this shard is dark (mid-failover?) — adopt what the
                # others have; the monitor's next sweep re-warm-boots
                continue
            keys.update(int(k) for k in reply.get("keys", ()))
        got: Dict[int, np.ndarray] = {}
        if keys:
            def adopt(kvs):
                for k, v in kvs.slices():
                    got[int(k)] = np.array(v, dtype=np.float32, copy=True)

            self.up.zpull(sorted(keys), cb=adopt, wait=True,
                          body={"dense": True})
        with self._mu:
            for k, v in got.items():
                self.store[k] = v
                self._milestone[k] = np.array(v, copy=True)
                st = self._keys.setdefault(k, _KeyState())
                st.accum = None
                st.count = 0
                st.in_flight = 0
                st.completing = False
                st.contributors = set()
                st.hfa_inv = 0.0
                st.epoch += 1  # invalidate pre-crash pull-downs
                # the global tier's tracked subscriber view (BSC) no
                # longer matches this replica; -1 never equals a tracked
                # version, so the next compressed pull resyncs dense
                self._pull_ver[k] = -1
                # the global tier may have restarted too — accept any
                # weight-version stamp after a warm boot
                self._weight_ver.pop(k, None)
                self._drain_parked_locked(st)
            self.warm_boots += 1
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.warm_boots").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.WARM_BOOT, a=len(got))
        # re-sync the party's 1/num_workers pre-scale and membership (a
        # replacement process restarted the count at the static plan)
        self._broadcast_membership()
        print(f"{self.po.node}: warm boot adopted {len(got)} keys from "
              "the global tier", flush=True)
        return len(got)

    # ---- degraded-mode rounds & catch-up (partition tolerance) -------------
    def _degrade_sweep(self):
        """Periodic watchdog (partition mode only): a WAN push batch
        whose group acks have made no progress for the degrade window
        means the uplink is dark — switch to degraded rounds instead of
        letting every subsequent party round wedge behind it."""
        if self._degraded or not self._partition_mode:
            return
        with self._ctr_mu:
            inflight = self._wan_inflight
            last = self._wan_progress_t
        if (inflight > 0
                and time.monotonic() - last > self._degrade_window
                and self._wan_heartbeat_silent()):
            self._enter_degraded()

    def _wan_heartbeat_silent(self) -> bool:
        """Second opinion before degrading: a stalled WAN push ack can
        be LEGITIMATE (a sync-mode global round parks this party's push
        until every other party contributes), but a genuinely dark
        uplink also starves this server's own heartbeat echoes from the
        global scheduler — require both before abandoning the round.
        Heartbeats off → no echo evidence either way → the ack stall
        alone decides."""
        if self.config.heartbeat_interval_s <= 0:
            return True
        age = self.po.heartbeat_echo_age(
            self.po.topology.global_scheduler())
        return age > self._degrade_window

    def _enter_degraded(self):
        """Abandon the stuck WAN round(s) and start accumulating.  The
        stuck keys' epochs are bumped FIRST so a late pull-down from the
        abandoned batch (delivered after a partial partition heals)
        cannot clobber weights the degraded rounds moved past; the
        merged-but-unacked push gradients are NOT folded into the
        catch-up delta — the van's replay layer re-delivers the push
        itself once the fabric heals (request_retry_s > 0), and
        double-counting them here would apply them twice."""
        with self._mu:
            if self._degraded:
                return
            self._degraded = True
            self._catchup_since = time.monotonic()
            stuck = [k for k, st in self._keys.items()
                     if st.in_flight > 0]
            for k in stuck:
                self._keys[k].epoch += 1
        while True:
            open_keys = []
            with self._mu:
                open_keys = [k for k in stuck
                             if self._keys[k].in_flight > 0]
            if not open_keys:
                break
            self._finish_round(open_keys)
        with self._ctr_mu:
            self._wan_inflight = 0  # abandoned; the ack-side clamp
            #                         absorbs any late arrivals
        if self._flight is not None:
            self._flight.record(FlightEv.NETFAULT, a=len(stuck),
                                note="netfault_degraded")
        print(f"{self.po.node}: entered degraded mode — WAN uplink "
              f"silent for {self._degrade_window:.1f}s, party rounds "
              "continue against frozen weights and accumulate a "
              "catch-up delta", flush=True)

    def _host_kvs(self, kvs: KVPairs) -> KVPairs:
        """Materialize a device-resident round for the host fallback
        paths (degraded absorb, anything that does numpy arithmetic on
        the values) — billed by the codec stage as a codec host copy
        so the steady-state zero-host-traffic contract stays auditable.
        The identity for host rounds."""
        if (self._codec_stage is None
                or not self._codec_stage.is_device(kvs.vals)):
            return kvs
        return KVPairs(kvs.keys, self._codec_stage.to_host(kvs.vals),
                       kvs.lens)

    def _make_push_codec(self, body: dict):
        """Build the push codec for a SET_COMPRESSION / WAN-policy body:
        the device family when the codec stage is active (encode reads
        the device accumulator, ships wire-identical frames), else the
        numpy reference.  Both raise ValueError on malformed bodies."""
        from geomx_tpu.compression import make_push_codec

        if self._codec_stage is not None:
            return self._codec_stage.make_push_codec(body)
        return make_push_codec(body)

    def _absorb_degraded_round(self, kvs: KVPairs, keys: List[int]):
        """A party round completed while the WAN uplink is dark: fold
        the merged gradient into the bounded per-key catch-up delta and
        close the round against the frozen weights.  Under HFA the
        push-up carries party-mean WEIGHTS, not a gradient — summing
        those is meaningless, so the accumulator is poisoned and the
        heal falls back to a dense resync."""
        with self._ctr_mu:
            self.degraded_rounds += 1
            self._catchup_rounds += 1
            rounds = self._catchup_rounds
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.degraded_rounds").inc()
        if self.hfa_enabled:
            self._catchup_invalid = True
        else:
            with self._mu:
                for k, v in kvs.slices():
                    k = int(k)
                    prev = self._catchup.get(k)
                    if prev is None:
                        self._catchup[k] = np.array(v, dtype=np.float32,
                                                    copy=True)
                    else:
                        prev += v.astype(np.float32)
        if self._flight is not None:
            self._flight.record(FlightEv.ROUND_COMPLETE, a=len(keys),
                                b=rounds, note="degraded")
        self._finish_round(keys)

    def _ship_catchup(self) -> Optional[int]:
        """Heal path (REJOIN with surviving state): ship the
        accumulated delta as ONE staleness-stamped Cmd.CATCHUP push —
        the global tier merges it through the normal optimizer path
        (DC-ASGD compensates the staleness) — and return the key
        count.  Returns None when the delta is not trustworthy (HFA
        rounds, or more degraded rounds than
        Config.partition_catchup_bound): the caller dense-boots
        instead.  Fresh weights are NOT pulled here; the next normal
        round's pull-down refreshes them as ordinary training traffic,
        which is what keeps the heal cost at a fraction of a dense
        resync."""
        with self._mu:
            delta = self._catchup
            rounds = self._catchup_rounds
            since = self._catchup_since
            invalid = self._catchup_invalid
            self._catchup = {}
            self._catchup_rounds = 0
            self._catchup_since = None
            self._catchup_invalid = False
            self._degraded = False  # cleared BEFORE shipping so the
            #                         catch-up push is not diverted
        if not delta and rounds == 0:
            return 0
        bound = int(self.config.partition_catchup_bound)
        from geomx_tpu.utils.metrics import system_counter

        if invalid or rounds > bound:
            self.catchup_fallbacks += 1
            system_counter(
                f"{self.po.node}.partition_catchup_fallbacks").inc()
            if self._flight is not None:
                self._flight.record(FlightEv.NETFAULT, a=len(delta),
                                    b=rounds,
                                    note="netfault_catchup_fallback")
            why = ("HFA weight-mean rounds" if invalid else
                   f"{rounds} degraded rounds > bound {bound}")
            print(f"{self.po.node}: catch-up delta not trustworthy "
                  f"({why}) — dense resync instead", flush=True)
            return None
        ks = sorted(delta)
        kvs = KVPairs(np.array(ks, dtype=np.int64),
                      np.concatenate([delta[k] for k in ks]),
                      np.array([len(delta[k]) for k in ks],
                               dtype=np.int64))
        age = time.monotonic() - since if since is not None else 0.0
        body = {"catchup": {"rounds": rounds, "age_s": round(age, 3)}}
        groups = self._encode_wan_groups(kvs)
        remaining = [len(groups)]
        done = threading.Event()
        lock = threading.Lock()

        def acked():
            with lock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()

        for tag, pairs in groups.items():
            ks2 = np.array([k for k, _ in pairs], dtype=np.int64)
            vals2 = (pairs[0][1] if len(pairs) == 1
                     else np.concatenate([p for _, p in pairs]))
            lens2 = np.array([len(p) for _, p in pairs], dtype=np.int64)
            self.up.zpush(KVPairs(ks2, vals2, lens2), cmd=Cmd.CATCHUP,
                          on_complete=acked, compr=tag, body=dict(body),
                          donated=True)
        if not done.wait(60.0):
            raise TimeoutError(
                f"{self.po.node}: catch-up push not acked; the "
                "recovery monitor re-asks")
        self.catchup_pushes += 1
        system_counter(f"{self.po.node}.partition_catchup_pushes").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.NETFAULT, a=len(ks), b=rounds,
                                note="netfault_catchup_push")
        print(f"{self.po.node}: healed — shipped catch-up delta "
              f"({len(ks)} keys, {rounds} degraded rounds, "
              f"{age:.1f}s stale); fresh weights ride the next round's "
              "pull-down", flush=True)
        return len(ks)

    def _on_preempt(self, msg: Message) -> bool:
        """Control.PREEMPT_NOTICE request: this local server's host is
        about to be preempted.  Drain off the hook thread (the fold
        RPCs block on WAN round trips); repeat notices queue behind the
        running drain like REJOIN retries do and are answered when it
        finishes."""
        if msg.control is not Control.PREEMPT_NOTICE or not msg.request:
            return False
        with self._mu:
            self._preempt_waiters.append(msg)
            if self._preempt_busy:
                return True
            self._preempt_busy = True
        threading.Thread(target=self._preempt_thread, daemon=True,
                         name=f"preempt-drain-{self.po.node}").start()
        return True

    def _preempt_thread(self):
        try:
            self.preempt_drain()
            ok = True
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "%s: preempt drain failed (the eviction path covers "
                "the crash)", self.po.node)
            ok = False
        with self._mu:
            waiters, self._preempt_waiters = self._preempt_waiters, []
            self._preempt_busy = False
        for m in waiters:
            try:
                self.po.van.send(m.reply_to(
                    control=Control.PREEMPT_NOTICE, body={
                        "ok": ok, "drain_s": self.last_drain_s,
                        "node": str(self.po.node),
                        "token": (m.body or {}).get("token")}))
            except (KeyError, OSError):
                pass  # the notifier vanished; the drain still happened

    def preempt_drain(self, timeout: Optional[float] = None) -> float:
        """Graceful spot-preemption drain: let the in-flight WAN push
        round flush its acks, then hand this party's fold to the global
        tier PROACTIVELY (the reversible ``party_fold`` — the same fold
        the recovery monitor would synthesize a heartbeat-timeout
        later) and tell the recovery monitor the fold happened, so the
        replacement's resumed heartbeats drive the normal warm-boot /
        unfold / worker-replay rejoin.  Returns the drain seconds."""
        import uuid

        t0 = time.monotonic()
        budget = timeout if timeout is not None \
            else self.config.preempt_drain_s
        deadline = t0 + budget
        # 1. flush: wait for open WAN push batches to collect their acks
        #    (bounded — a dark global tier must not eat the whole notice)
        while time.monotonic() < deadline:
            with self._ctr_mu:
                inflight = self._wan_inflight
            if inflight <= 0:
                break
            time.sleep(0.02)
        # 2. reversible fold at every shard's CURRENT holder (the
        #    up-link targets track NEW_PRIMARY retargets)
        node_s = str(self.po.node)
        for gs in list(self.up.targets):
            token = f"{node_s}#{uuid.uuid4().hex[:8]}"
            cv = threading.Condition()
            reply: dict = {}

            def hook(m, _token=token, _cv=cv, _reply=reply) -> bool:
                b = m.body if isinstance(m.body, dict) else {}
                if (m.control is Control.EVICT and not m.request
                        and b.get("token") == _token):
                    with _cv:
                        _reply.update(b)
                        _cv.notify_all()
                    return True
                return False

            self.po.add_control_hook(hook)
            try:
                for _ in range(3):
                    try:
                        self.po.van.send(Message(
                            recipient=gs, control=Control.EVICT,
                            domain=Domain.GLOBAL, request=True,
                            body={"action": "party_fold", "node": node_s,
                                  "token": token}))
                    except (KeyError, OSError):
                        pass  # shard dark — the eviction path covers it
                    with cv:
                        if cv.wait_for(lambda: bool(reply), timeout=max(
                                0.1, min(2.0, deadline
                                         - time.monotonic()))):
                            break
            finally:
                self.po.remove_control_hook(hook)
        # 3. arm the rejoin path: the recovery monitor records the fold
        #    (with our boot incarnation) so the REPLACEMENT's resumed
        #    heartbeats trigger warm boot + unfold + worker replay
        try:
            self.po.van.send(Message(
                recipient=self.po.topology.global_scheduler(),
                control=Control.PREEMPT_NOTICE, domain=Domain.GLOBAL,
                request=False,
                body={"event": "server_drained", "node": node_s,
                      "party": self.po.node.party,
                      "boot": self.po.van.boot}))
        except (KeyError, OSError):
            pass  # monitor dark: heartbeat expiry re-folds idempotently
        self.last_drain_s = round(time.monotonic() - t0, 4)
        self.preempt_server_drains += 1
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.preempt_server_drains").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.FOLD,
                                a=int(self.last_drain_s * 1e6),
                                peer=node_s, note="preempt_drain")
        print(f"{self.po.node}: preempt drain complete — party handed "
              f"to the global tier in {self.last_drain_s:.3f}s "
              "(workers park until the replacement rejoins)", flush=True)
        return self.last_drain_s

    def _on_new_primary(self, msg: Message) -> bool:
        """Global-tier failover (Control.NEW_PRIMARY from the global
        scheduler): shard ``rank``'s primary died and its hot standby
        was promoted under ``term``.  Retarget the up-link worker and
        REPLAY its un-ACKed requests against the new primary
        (KVWorker.retarget) — the standby's replicated replay-dedup
        window keeps the replay exactly-once.  Term-guarded per shard:
        rebroadcasts and out-of-order duplicates are no-ops."""
        if msg.control is not Control.NEW_PRIMARY or msg.request:
            return False
        b = msg.body if isinstance(msg.body, dict) else {}
        rank, term = int(b.get("rank", -1)), int(b.get("term", 0))
        with self._mu:
            if term <= self._primary_terms.get(rank, 0):
                return True  # stale or repeated broadcast
            self._primary_terms[rank] = term
        replayed = self.up.retarget(NodeId.parse(b["old"]),
                                    NodeId.parse(b["new"]))
        self.failover_events += 1
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.failover_events").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.PROMOTE, a=term, c=replayed,
                                peer=b.get("new"), note="retarget")
        print(f"{self.po.node}: global shard {rank} failed over to "
              f"{b['new']} (term={term}, replayed={replayed} requests)",
              flush=True)
        return True

    def _broadcast_membership(self):
        """Tell every party worker the new aggregation size — their
        1/num_workers gradient pre-scale must track membership or the
        post-join update stops being a mean (static plan workers +
        joined members).  The (total, seq) pair is read atomically under
        ``_mu``: concurrent join/leave broadcasts may be sent out of
        order, and the client hook drops any stamp older than one it has
        applied, so the pre-scale converges to the server's latest
        target rather than whichever send raced last."""
        with self._mu:
            total = self._workers_target
            seq = self._membership_seq
            extra = list(self._members)
            addrs = {n: list(a) for n, a in self._member_addrs.items()
                     if n in self._members}
        targets = {str(w): w for w in self.po.topology.workers(
            self.po.node.party)}
        for n in extra:
            targets.setdefault(n, NodeId.parse(n))
        # the party scheduler tracks membership too: the TS overlay's
        # dissemination targets and the push-pairing "holder has all"
        # threshold live there (TsScheduler/TsPushScheduler hooks)
        sched = self.po.topology.scheduler(self.po.node.party)
        body = {"event": "membership", "num_workers": total, "seq": seq,
                "members": sorted(extra), "addrs": addrs}
        for n in list(targets.values()) + [sched]:
            try:
                self.po.van.send(Message(
                    recipient=n, control=Control.ADD_NODE,
                    domain=Domain.LOCAL, request=False, body=body))
            except (KeyError, OSError):
                pass  # a down/unknown worker learns on its next join

    def _handle_push(self, msg: Message, kvs: KVPairs):
        state = self._recent.check(msg)
        if state == "pending":
            return  # replay of a push we're still aggregating
        if state == "done":
            # already applied; the ACK (or piggybacked values) was lost
            if msg.pull:
                self._try_serve_pull(msg)
            else:
                self.server.response(msg, body=self._recent.done_body(msg))
            return
        sender_s = str(msg.sender)
        if self._fence_evicted_push(msg, sender_s):
            return  # evicted identity: rejected, told to rejoin
        # first push from a dynamic joiner: it is established now — its
        # later pulls park during partial merges like everyone else's
        self._bootstrapping.discard(sender_s)
        kvs = self._screen_push(msg, kvs)
        # a TS-merged push carries several workers' contributions at once
        # (ref: num_merge counting van.cc:1197-1252)
        num_merge = 1
        if isinstance(msg.body, dict):
            num_merge = int(msg.body.get("num_merge", 1))
        hfa_n = None
        if self.hfa_enabled:
            # each HFA push announces the denominator it pre-scaled its
            # weights by; missing (old client) = assume current target
            hfa_n = float((msg.body or {}).get("hfa_n",
                                               self._workers_target))
        slices = list(kvs.slices())
        if not slices:
            self._recent.mark_done(msg)
            self.server.response(msg)
            return
        # key-sharded merge: each key's accumulate runs on its stripe's
        # serial lane, so per-key arrival order is preserved while
        # pushes touching disjoint keys merge in parallel.  The ack —
        # and any completed rounds — dispatch from whichever lane
        # finishes the message's last slice (ordering vs. the parked
        # piggyback pull is identical to the single-lock path).  With
        # server_shards=1 the lanes are inline and this is bit-for-bit
        # the old serial handler.
        pending = [len(slices)]
        bundles: List[dict] = []
        done_mu = threading.Lock()

        def merge_one(k: int, v: np.ndarray):
            bundle = None
            with self._mu.stripe(k):
                st = self._keys.setdefault(k, _KeyState())
                st.contributors.add(sender_s)
                if hfa_n:
                    st.hfa_inv += num_merge / hfa_n
                if st.accum is None:
                    st.accum = self._backend.seed(v, msg.donated, key=k)
                    # fold joins in at the round boundary
                    st.expected = self._workers_target
                else:
                    st.accum = self._backend.accumulate(st.accum, v)
                st.count += num_merge
                st.priority = msg.priority
                if (self.sync_mode
                        and st.count >= (st.expected or self.num_workers)
                        and not st.completing):
                    # take-at-decide, still under the stripe: detaching
                    # the accumulator AT the decision point closes the
                    # decide→retake window a parallel lane could
                    # otherwise merge the next round's gradient into
                    bundle = self._take_completed_locked(k)
            with done_mu:
                if bundle is not None:
                    bundles.append(bundle)
                pending[0] -= 1
                last = pending[0] == 0
            if last:
                self._push_merged(msg, kvs, bundles)

        for k, v in slices:
            self._shards.submit(k, _ctx_bound(lambda k=k, v=v: merge_one(k, v)))

    def _push_merged(self, msg: Message, kvs: KVPairs,
                     bundles: List[dict]):
        """Post-merge step of one push message, on the lane that
        finished its last slice: ack (or park the piggyback pull), then
        dispatch any rounds the message completed.  Runs with no
        stripes held."""
        poisoned = getattr(msg, "_gx_poisoned", None)
        if not self.sync_mode:
            # async local tier: no rounds — clear the aggregation state
            # FIRST (the accumulate lanes raised st.count, which blocks
            # pull serving), then serve any piggybacked pull from the
            # current store and forward the push upward immediately
            with self._mu:
                for k in kvs.keys:
                    st = self._keys[int(k)]
                    st.accum = None
                    st.count = 0
                    st.in_flight = 0
                    st.completing = False  # no round to complete async
                    st.contributors.clear()
                    st.hfa_inv = 0.0
                if msg.pull and poisoned is None:
                    self._try_serve_pull(msg)
            if poisoned is not None:
                # typed reject in place of the ack (the piggyback pull
                # gets the error too, like a fence); nothing useful to
                # forward — the payload was zeroed
                self._recent.mark_done(msg, poisoned)
                self.server.response(msg, body=poisoned)
                return
            if not msg.pull:
                self._recent.mark_done(msg)
                self.server.response(msg)
            self._push_up(KVPairs(kvs.keys, kvs.vals.astype(np.float32),
                                  kvs.lens))
            return
        if poisoned is not None:
            # sync tier: the zeroed contribution already counted toward
            # the round barrier on the lanes; the sender is told loudly
            # instead of acked (a piggyback pull is NOT parked — the
            # error rides the push response, exactly like a fence)
            self._recent.mark_done(msg, poisoned)
            self.server.response(msg, body=poisoned)
        elif msg.pull:
            # P3 piggyback: the push response carries the updated values
            # once the round completes (ref: server replies with values in
            # the push-response when enable_p3, kvstore_dist_server.h:
            # 1149-1165,1255-1267) — park it like a pull
            k0 = int(msg.keys[0])
            with self._mu.stripe(k0):
                self._keys[k0].parked_pulls.append(msg)
        else:
            # ack the push immediately — workers overlap next layers
            self._recent.mark_done(msg)
            self.server.response(msg)
        if bundles:
            self._dispatch_rounds(bundles)

    def _handle_push_row_sparse(self, msg: Message, kvs: KVPairs):
        """Scatter-accumulate active rows; the merged round rides the
        push-up path, sparsified for the WAN when that is smaller
        (ref: row-sparse server merge kvstore_dist_server.h row_sparse
        handlers).  The client rejects HFA×row-sparse, but guard here too
        — adopting a gradient sum as HFA weights would corrupt training."""
        from geomx_tpu.compression import codecs as codecs_mod
        from geomx_tpu.compression.codecs import unpack_rows

        state = self._recent.check(msg)
        if state == "pending":
            return
        if state == "done":
            self.server.response(msg, body=self._recent.done_body(msg))
            return
        if self._fence_evicted_push(msg, str(msg.sender)):
            return  # evicted identity: rejected, told to rejoin
        if self.hfa_enabled:
            # reject with an error body the client surfaces on wait_all()
            # — a bare ACK would let training silently diverge
            err = {"error": "row-sparse push rejected: server is in HFA mode"}
            self._recent.mark_done(msg, err)
            self.server.response(msg, body=err)
            return
        cols = int(msg.body["rs_cols"])
        key = int(kvs.keys[0])
        try:
            row_ids, rows = unpack_rows(kvs.vals, cols)
            # bounds BEFORE the merge lane: a corrupt negative row id
            # would silently wrap through np.add.at into the wrong row
            with self._mu.stripe(key):
                nrows = (len(self.store[key]) // cols
                         if key in self.store and cols else None)
            if nrows is not None:
                codecs_mod._check_index_bounds(row_ids, nrows, "rows", key)
        except codecs_mod.CodecError as e:
            self.integrity_codec_rejects += 1
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.integrity_codec_rejects").inc()
            if self._flight is not None:
                self._flight.record(FlightEv.CORRUPT, peer=msg.sender,
                                    note="corrupt_codec_payload")
            err = {"error": f"row-sparse push rejected before merge: {e}"}
            self._recent.mark_done(msg, err)
            self.server.response(msg, body=err)
            return
        sender_s = str(msg.sender)
        self._bootstrapping.discard(sender_s)
        self._saw_row_sparse = True
        # gradient hygiene on the unpacked rows only — the packed
        # row-id halves are bit-cast integers and may legitimately look
        # non-finite as floats
        if (self.config.integrity_push_screen
                and not self._backend.screen_finite(
                    rows, self.config.poison_mag_max)):
            msg._gx_poisoned = self._poison_strike(sender_s)
            rows = np.zeros_like(rows)

        # rides the key's merge lane like every other mutation of this
        # key, so row-sparse and dense pushes of one key keep their
        # arrival order under sharding
        def merge_rs():
            if not self.sync_mode:
                # async: no accumulation round — densify once and forward
                with self._mu:
                    st = self._keys.setdefault(key, _KeyState())
                    st.in_flight = 0
                    dense = np.zeros_like(self.store[key], dtype=np.float32)
                    np.add.at(dense.reshape(-1, cols), row_ids, rows)
                    self._drain_parked_locked(st)
                err = getattr(msg, "_gx_poisoned", None)
                self._recent.mark_done(msg, err)
                self.server.response(msg, body=err)
                if err is None:
                    self._push_up(KVPairs(
                        kvs.keys, dense,
                        np.array([len(dense)], np.int64)),
                        rs_keys={key})
                return
            bundle = None
            with self._mu.stripe(key):
                st = self._keys.setdefault(key, _KeyState())
                st.contributors.add(sender_s)
                if st.accum is None:
                    st.accum = np.zeros_like(self.store[key],
                                             dtype=np.float32)
                    st.expected = self._workers_target
                else:
                    # a dense push may have seeded this key on a device
                    # backend; the scatter-add is host-side by design
                    st.accum = self._backend.materialize(st.accum)
                np.add.at(st.accum.reshape(-1, cols), row_ids, rows)
                st.count += 1
                st.row_sparse = True
                if (st.count >= (st.expected or self.num_workers)
                        and not st.completing):
                    bundle = self._take_completed_locked(key)
            err = getattr(msg, "_gx_poisoned", None)
            self._recent.mark_done(msg, err)
            self.server.response(msg, body=err)
            if bundle is not None:
                self._dispatch_rounds([bundle])

        self._shards.submit(key, _ctx_bound(merge_rs))

    def _on_inter_ts_delivery(self, msg: Message, kvs: KVPairs):
        """Updated weights arrived via the WAN overlay instead of a pull
        (inter-party TSEngine): adopt them, confirm delivery, and relay
        onward to sibling local servers.  Under the sync tier a delivery
        IS the round completion, so it finishes the round; under the
        async tier rounds complete via the push ACK instead, and a
        delivery decoupled from any round must only refresh the replica
        — force-finishing would break the intra-party BSP barrier
        (serving parked pulls before every party worker pushed)."""
        it = str(msg.body["iter"])
        with self._mu:
            self.ts_deliveries += 1
            for k, v in kvs.slices():
                # fp16 relay payloads decode back to f32 replicas
                self.store[k] = np.asarray(v, dtype=np.float32).copy()
            if self.config.sync_global_mode:
                self._finish_round([int(k) for k in kvs.keys
                                    if int(k) in self._keys])
        self.ts_inter.send_reply(msg.sender, it)
        self.ts_inter.disseminate_async(msg.keys, msg.vals, msg.lens, it,
                                        Cmd.TS_AUTOPULL)

    def _take_completed_locked(self, k: int) -> dict:
        """Detach key ``k``'s completed round (caller holds stripe(k);
        completion was just decided).  Bumps the round counter, applies
        the HFA convex renormalization — accum = Σ w_i/n_i with
        possibly-mixed n_i (membership transition) or count < n (leave
        completed the round short): dividing by Σ 1/n_i keeps the
        result a weighted MEAN of weight vectors, never
        scale-inflated/shrunk — resets the per-round state, and returns
        the round bundle :meth:`_dispatch_rounds` ships."""
        st = self._keys[k]
        st.round += 1
        gated = self.hfa_enabled and st.round % self.hfa_k2 != 0
        if gated:
            with self._ctr_mu:
                self.hfa_gated_key_rounds += 1
        if (self.hfa_enabled and st.hfa_inv > 0.0
                and abs(st.hfa_inv - 1.0) > 1e-9):
            st.accum = self._backend.scale(st.accum, 1.0 / st.hfa_inv)
        # device-resident handoff (ISSUE 20): when a device push codec
        # will consume this round, skip the host materialization — the
        # encoder reads the device accumulator and the only D2H is the
        # compressed wire payload.  Every path that still needs host
        # bytes is excluded here: HFA (local applies + weight pushes),
        # row-sparse rounds (host-seeded scatter), the inter-TS merge
        # relay, adaptive WAN (raw host stash for fence retries), and a
        # dark uplink (degraded absorb; re-checked race-safely in
        # _push_up_send via _host_kvs).
        keep_device = (self._codec_stage is not None
                       and getattr(self.push_codec, "device", False)
                       and not gated and not st.row_sparse
                       and not self.hfa_enabled
                       and self.ts_push_inter is None
                       and not self._adaptive and not self._degraded
                       and not isinstance(st.accum, np.ndarray))
        v = (self._codec_stage.round_value(st.accum) if keep_device
             else self._backend.materialize(st.accum))
        bundle = {"k": k, "v": v, "gated": gated, "rs": st.row_sparse}
        st.hfa_inv = 0.0
        st.accum = None
        st.count = 0
        st.completing = False  # slate consumed; next round may be
        #                        decided again
        st.contributors = set()
        st.in_flight += 1  # round launched; finish decrements
        st.row_sparse = False  # describes this round only
        return bundle

    def _dispatch_rounds(self, bundles: List[dict]):
        """Ship completed rounds whose accumulators were already
        detached at the decision point.  HFA: each key counts its own
        aggregation rounds; only every k2-th round of a key crosses the
        WAN (ref: kvstore_dist_server.h:1324-1343).  Runs with no
        stripes held (or under the all-stripes barrier on the fold
        path)."""
        bundles = sorted(bundles, key=lambda b: b["k"])
        rs_keys = {b["k"] for b in bundles if b["rs"] and not b["gated"]}

        def pack(bs):
            vs = [b["v"] for b in bs]
            # single-key rounds (the big-tensor regime) hand the
            # accumulator over as-is — concatenate([one]) is a full
            # copy (~0.27 s at 200 MB on this host)
            if len(vs) == 1:
                vals = vs[0]
            elif (self._codec_stage is not None
                  and any(self._codec_stage.is_device(v) for v in vs)):
                # device rounds stay device: np.concatenate would
                # silently round-trip every value through the host
                vals = self._codec_stage.concat(vs)
            else:
                vals = np.concatenate(vs)
            return KVPairs(np.array([b["k"] for b in bs], dtype=np.int64),
                           vals,
                           np.array([len(v) for v in vs], dtype=np.int64))

        local = [b for b in bundles if b["gated"]]
        up = [b for b in bundles if not b["gated"]]
        if local:
            self._apply_local(pack(local))
        if up:
            kvs_up = pack(up)
            if self.hfa_enabled:
                self._push_up_hfa(kvs_up)
            elif rs_keys:
                self._push_up(kvs_up, rs_keys=rs_keys)
            else:
                self._push_up(kvs_up)

    def _round_complete(self, keys: List[int]):
        """Complete rounds already decided for ``keys`` — the
        membership-fold path (caller holds the all-stripes barrier, so
        the per-key takes below just re-enter their stripes)."""
        self._dispatch_rounds(
            [self._take_completed_locked(k) for k in sorted(keys)])

    def _apply_local(self, kvs: KVPairs):
        """HFA off-round: the merged push is already the party-mean weight
        vector (workers push weight/num_workers, ref: examples/cnn_hfa.py) —
        adopt it and serve pulls without touching the WAN."""
        for k, v in kvs.slices():
            with self._mu.stripe(k):
                self.store[k] = np.array(v, copy=True)
        self._finish_round([int(k) for k in kvs.keys])

    @staticmethod
    def _is_merge_relay(msg: Message) -> bool:
        from geomx_tpu.sched.ts_push import TS_PUSH_MERGE_CMD

        return msg.cmd == TS_PUSH_MERGE_CMD

    def _inter_merge_loop(self):
        """Dispatch per-key inter-party merges, each on its own thread.

        Concurrency is load-bearing, not an optimization: parties'
        rounds complete in different key orders, so ANY cap below the
        number of keys in flight can fill with disjoint key sets across
        parties and head-of-line-deadlock (the reason a bounded pool is
        wrong here).  Threads are bounded naturally by the model's key
        count — each key has at most one merge in flight because rounds
        of one key complete serially.  Per-key round tokens route each
        thread's scheduler replies and relays (ref: the per-key ASK_PUSH
        pairing of the global scheduler, van.cc:1254-1310)."""

        def one_key(k: int, v: np.ndarray, rs: bool, token: str):
            res = self.ts_push_inter.merge_push(
                {k: np.asarray(v, np.float32)}, it=token)
            if res is not None:
                # elected (or degraded-to-direct on overlay failure) —
                # push with however many contributions we actually hold;
                # the global server accumulates counts across pushes
                merged, nm = res
                self._push_up_send(
                    KVPairs(np.array([k], dtype=np.int64), merged[k],
                            np.array([len(merged[k])], dtype=np.int64)),
                    frozenset({k}) if rs else frozenset(),
                    {"num_merge": nm})

        while True:
            job = self._merge_q.get()
            if job is None:
                return
            kvs, rs_keys = job
            for k, v in kvs.slices():
                r = self._inter_push_round.get(k, 0) + 1
                self._inter_push_round[k] = r
                threading.Thread(
                    target=one_key, args=(k, v.copy(), k in rs_keys,
                                          f"{k}:{r}"),
                    daemon=True, name=f"inter-merge-{self.po.node}-{k}",
                ).start()

    def _push_up(self, kvs: KVPairs, rs_keys=frozenset()):
        if self.ts_push_inter is not None:
            # hand off to the merge thread (blocking WAN round-trips must
            # not stall the handler thread that feeds the merge relays)
            self._merge_q.put((kvs, rs_keys))
            return
        self._push_up_send(kvs, rs_keys, None)

    def _push_up_send(self, kvs: KVPairs, rs_keys=frozenset(),
                      push_body=None):
        keys = [int(k) for k in kvs.keys]
        if self._degraded:
            # the WAN uplink is dark (partition mode): the round stays
            # in the party — accumulate the merged gradient into the
            # catch-up delta and finish against the frozen weights.
            # A device-resident round materializes here (the absorb is
            # host arithmetic by design; _degraded may have flipped
            # after the round-close decision kept it on device).
            self._absorb_degraded_round(self._host_kvs(kvs), keys)
            return
        if self._prof.running:
            self._prof.count("wan_rounds", 1.0)
        raw = None
        if self._adaptive:
            with self._mu:
                # the WAN round boundary: a pending policy applies HERE,
                # so the whole batch below is encoded under one epoch
                self._apply_policy_locked()
            # stash the raw merged gradients until the round is acked —
            # a receiver's policy fence is answered by re-encoding them
            # under the newer codec (one extra copy per round, paid only
            # with adaptive WAN on)
            raw = {int(k): np.array(v, copy=True) for k, v in kvs.slices()}
        with self._ctr_mu:  # rounds of disjoint keys dispatch from
            self.wan_push_rounds += 1  # parallel lanes
            wan_round = self.wan_push_rounds
            if self._wan_inflight == 0:
                # degrade watchdog: the window opens at the FIRST
                # outstanding batch only — later dispatches piling up
                # behind a dark uplink must not keep resetting it
                self._wan_progress_t = time.monotonic()
            self._wan_inflight += 1  # decremented when the batch's
            #                          groups are all acked (the
            #                          preempt drain waits on zero)
        if self._flight is not None:
            # the WAN round boundary: the stall forensic's "this party
            # pushed up and is now owed a pull-down"
            self._flight.record(FlightEv.ROUND_OPEN, a=wan_round,
                                c=len(keys), note="wan_push")

        with self._mu:
            epochs = {k: self._keys[k].epoch for k in keys
                      if k in self._keys}
            # P3: the WAN hops inherit the workers' per-layer priority
            prio = max((self._keys[k].priority for k in keys
                        if k in self._keys), default=0)

        def pull_down():
            # all global shards applied the update → pull fresh weights
            # (ref: DataHandlePushResponseDefault :941-957).  Under
            # inter-party TS the overlay delivers them instead.
            if self.ts_inter is not None:
                if not self.config.sync_global_mode:
                    # async tier: the overlay disseminates at its own
                    # (rate-limited) pace — finish the round from the
                    # current replica instead of gating on a delivery
                    self._finish_round(keys)
                return
            self.up.zpull(keys,
                          cb=lambda kvs: self._on_pull_down(kvs, epochs),
                          priority=prio, body=self._pull_echo(keys))

        # group keys by wire codec so each message has a uniform payload
        # dtype + compr tag (ref: PushCompressed kvstore_dist.h:530-563)
        groups = self._encode_wan_groups(kvs, rs_keys)
        # P3 piggyback on the WAN tier: combined push_pull saves the
        # per-round ack -> pull-request chain (2 messages + 2 latencies
        # per key per round); the global server replies with the updated
        # values once the round completes.  Not combinable with the
        # inter-TS overlay (which replaces the pull-down entirely),
        # merged pushes (num_merge body), or the adaptive epoch
        # protocol (a fenced piggyback would eat the pull's response
        # slot; the split push + pull path retries cleanly).
        use_piggyback = (self.config.enable_p3 and push_body is None
                         and self.ts_inter is None and not self._adaptive)
        if use_piggyback:
            # the piggybacked round has no separate push-ack chain; the
            # drain's flush reading can't observe it — release now
            with self._ctr_mu:
                self._wan_inflight -= 1
            for tag, pairs in groups.items():
                ks = np.array([k for k, _ in pairs], dtype=np.int64)
                vals = (pairs[0][1] if len(pairs) == 1
                        else np.concatenate([p for _, p in pairs]))
                lens = np.array([len(p) for _, p in pairs], dtype=np.int64)
                self.up.push_pull(
                    KVPairs(ks, vals, lens), cmd=Cmd.DEFAULT,
                    cb=lambda kvs: self._on_pull_down(kvs, epochs),
                    compr=tag, priority=prio, donated=True,
                    body=self._pull_echo([int(k) for k in ks]))
            return

        remaining = [len(groups)]
        lock = threading.Lock()

        def one_group_acked():
            with lock:
                remaining[0] -= 1
                done = remaining[0] == 0
            with self._ctr_mu:
                # every group ack is WAN progress for the degrade
                # watchdog; the clamp absorbs acks from batches a
                # degrade entry already abandoned
                self._wan_progress_t = time.monotonic()
                if done:
                    self._wan_inflight = max(0, self._wan_inflight - 1)
            if done:
                pull_down()

        for tag, pairs in groups.items():
            self._send_wan_group(tag, pairs, one_group_acked, push_body,
                                 prio, rs_keys, raw)

    def _encode_wan_groups(self, kvs: KVPairs,
                           rs_keys=frozenset()) -> Dict[str, list]:
        """Group a push-up batch by wire codec (shared by the round path
        and the adaptive fence-retry re-encode).

        Multi-key batches fan the per-key compress calls across the
        shared codec pool (sized like ``server_merge_threads``) instead
        of encoding serially on the round-completion thread; codec
        SELECTION stays serial (MPQ's pick counters), and per-key codec
        state (residuals, velocities) is key-partitioned so parallel
        keys never share an entry.  Single-key rounds (the big-tensor
        regime) and 1-lane hosts keep the exact serial path."""
        groups: Dict[str, list] = {}
        if self.push_codec is None:
            # uncompressed mode — except row-sparse rounds, whose merged
            # gradient is mostly zeros: ship [values ‖ indices] when
            # that is smaller (the WAN half of the row-sparse path)
            from geomx_tpu.compression.codecs import pack_sparse

            for k, v in kvs.slices():
                if int(k) in rs_keys:
                    idx = np.nonzero(v)[0]
                    if 2 * len(idx) < len(v):
                        groups.setdefault("bsc", []).append(
                            (k, pack_sparse(v[idx], idx)))
                        continue
                groups.setdefault("", []).append((k, v))
            return groups
        from geomx_tpu.compression import MpqSelector

        sel = [(k, v, (self.push_codec.select(len(v))
                       if isinstance(self.push_codec, MpqSelector)
                       else self.push_codec)) for k, v in kvs.slices()]
        pool = codec_pool(self.config) if len(sel) > 1 else None
        with self._tr.span("codec.encode"):
            if pool is None:
                enc = [(k, c.name, c.compress(k, v)) for k, v, c in sel]
            else:
                futs = [pool.submit(c.compress, k, v) for k, v, c in sel]
                enc = [(k, c.name, f.result())
                       for (k, v, c), f in zip(sel, futs)]
        for k, name, payload in enc:
            groups.setdefault(name, []).append((k, payload))
        return groups

    def _send_wan_group(self, tag: str, pairs: list, done_cb,
                        push_body, prio: int, rs_keys, raw,
                        attempts: int = 0):
        """Push one codec group up.  Under adaptive WAN the push is
        stamped with the current policy epoch and stashed so a receiver
        fence can re-encode + retry it; ``done_cb`` fires exactly once —
        on the successful (possibly retried) ack, or on a loudly-logged
        give-up."""
        ks = np.array([k for k, _ in pairs], dtype=np.int64)
        vals = (pairs[0][1] if len(pairs) == 1
                else np.concatenate([p for _, p in pairs]))
        lens = np.array([len(p) for _, p in pairs], dtype=np.int64)
        kvp = KVPairs(ks, vals, lens)
        if not self._adaptive:
            # donated: every push-up payload is server-owned (the round's
            # aggregation buffer, a codec output, or a fresh delta) and
            # never touched again — the receiving tier may adopt it
            self.up.zpush(kvp, cmd=Cmd.DEFAULT, on_complete=done_cb,
                          compr=tag, body=push_body, priority=prio,
                          donated=True)
            return
        # a retried "" (vanilla) payload IS the stashed raw copy — the
        # receiver must not adopt+mutate the buffer a further retry may
        # need, so only first sends donate it
        donate = not (tag == "" and attempts > 0)
        ent = {"raw": {int(k): raw[int(k)] for k, _ in pairs},
               "rs": frozenset(rs_keys), "body": push_body, "prio": prio,
               "done": done_cb, "attempts": attempts, "fenced": False,
               "ts": None}

        def guard():
            # ordering contract: the fence error-handler runs BEFORE the
            # completion fires (same response-processing thread), so
            # "fenced" is authoritative here; a fenced ack means the
            # retry owns done_cb now
            with self._mu:
                fenced = ent["fenced"]
                ent["fenced"] = False
                if not fenced:
                    self._policy_stash.pop(ent["ts"], None)
            if not fenced:
                done_cb()

        # hold the lock across send + stash insert: the response (and
        # with it the fence handler / guard) can race zpush's return,
        # and both take this lock before touching the stash
        with self._mu:
            ts = self.up.zpush(kvp, cmd=Cmd.DEFAULT, on_complete=guard,
                               compr=tag, body=push_body, priority=prio,
                               donated=donate,
                               policy_epoch=self._policy_epoch)
            ent["ts"] = ts
            self._policy_stash[ts] = ent

    # ---- adaptive WAN: policy application + fence retry ---------------------
    def _on_set_wan_policy(self, msg: Message, body: dict):
        """Ctrl.SET_WAN_POLICY from the controller (sender side): store
        as pending; the next WAN round boundary applies it atomically.
        Constraint-gated by the SAME predicate as static config."""
        if not self._adaptive:
            self.server.reply_cmd(msg, body={
                "error": "adaptive WAN is disabled on this server "
                         "(Config.adaptive_wan / --adaptive-wan)"})
            return
        from geomx_tpu.compression import compression_allowed

        comp = dict(body.get("compression") or {})
        ok, why = compression_allowed(
            comp.get("type", "none"),
            inter_ts=self.config.enable_inter_ts, hfa=self.hfa_enabled)
        if not ok:
            self.server.reply_cmd(msg, body={"error": why})
            return
        with self._mu:
            epoch = int(body.get("epoch", 0))
            if epoch > self._policy_epoch and (
                    self._policy_pending is None
                    or epoch > int(self._policy_pending["epoch"])):
                self._policy_pending = {"epoch": epoch,
                                        "compression": comp}
            cur = self._policy_epoch
        self.server.reply_cmd(msg, body={"epoch": cur, "pending": epoch})

    def _apply_policy_locked(self):
        """Install a pending SET_WAN_POLICY (caller holds ``_mu``).
        Replacing the push codec drops its residual/velocity state by
        design — the unsent mass belongs to the old epoch's stream."""
        p = self._policy_pending
        if p is None:
            return
        self._policy_pending = None
        epoch = int(p["epoch"])
        if epoch <= self._policy_epoch:
            return  # stale (an older broadcast raced a fence adoption)
        comp = dict(p["compression"])
        try:
            codec = self._make_push_codec(comp)
        except ValueError:
            import logging

            logging.getLogger(__name__).error(
                "%s: refusing malformed WAN policy %r", self.po.node, comp)
            return
        self.push_codec = codec
        self.compression = comp
        self._policy_epoch = epoch
        from geomx_tpu.utils.metrics import system_gauge

        system_gauge(f"{self.po.node}.wan_policy_epoch").set(epoch)
        self._tr.instant("wanpolicy.apply", epoch=epoch,
                         codec=comp.get("type"))
        print(f"{self.po.node}: WAN policy epoch {epoch} applied at "
              f"round boundary -> {comp.get('type')}", flush=True)

    def _on_up_error(self, msg: Message) -> bool:
        """KVWorker error hook on the up-link: turn a receiver's policy
        fence into re-encode + retry.  Returns True when the error is
        fully handled here (it never reaches ``up.errors``)."""
        b = msg.body if isinstance(msg.body, dict) else {}
        if not b.get("policy_fenced"):
            return False
        retry = None
        with self._mu:
            # self-healing: the fence reply names the receiver's current
            # policy — adopt it NOW (this round must be re-encoded under
            # it anyway) even if the SET_WAN_POLICY broadcast was lost
            ep = int(b.get("policy_epoch", 0))
            comp = b.get("policy")
            adopted = comp is not None and ep > self._policy_epoch
            if adopted:
                self._policy_pending = {"epoch": ep, "compression": comp}
                self._apply_policy_locked()
            ent = self._policy_stash.pop(msg.timestamp, None)
            if ent is not None:
                self.policy_fence_retries += 1
                if ent["attempts"] < self.config.policy_fence_max_retries:
                    ent["fenced"] = True  # guard defers done to the retry
                    retry = ent
                else:
                    # give up LOUDLY: guard fires done_cb so the round
                    # completes; this round's gradient for these keys is
                    # dropped — the same staleness class as an async-tier
                    # lost push, and far better than a wedged FSA round
                    self.policy_drops += 1
                    import logging

                    logging.getLogger(__name__).error(
                        "%s: dropping WAN push after %d policy-fence "
                        "retries (keys %s)", self.po.node,
                        ent["attempts"], sorted(ent["raw"]))
        if ent is None:
            return False  # not ours (already handled / unknown ts)
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.policy_fence_retries").inc()
        if retry is not None:
            if adopted or ep >= self._policy_epoch:
                self._repush_fenced(retry)
            else:
                # the RECEIVER is the stale side (a promoted standby the
                # controller has not reached yet): back off so its
                # rebroadcast can land before the retry budget burns
                t = threading.Timer(0.1 * (retry["attempts"] + 1),
                                    self._repush_fenced, args=(retry,))
                t.daemon = True
                t.start()
        return True

    def _repush_fenced(self, ent: dict):
        """Re-encode a fenced group's stashed raw gradients under the
        (now-adopted) policy and push again.  The new policy may split
        the keys into different codec groups (MPQ), so the original
        ``done`` fires once ALL sub-groups ack."""
        raw = ent["raw"]
        ks = sorted(raw)
        vals = [raw[k] for k in ks]
        kvp = KVPairs(np.array(ks, dtype=np.int64),
                      vals[0] if len(vals) == 1 else np.concatenate(vals),
                      np.array([len(v) for v in vals], dtype=np.int64))
        groups = self._encode_wan_groups(kvp, ent["rs"])
        remaining = [len(groups)]
        lock = threading.Lock()

        def sub_done():
            with lock:
                remaining[0] -= 1
                fire = remaining[0] == 0
            if fire:
                ent["done"]()

        for tag, pairs in groups.items():
            self._send_wan_group(tag, pairs, sub_done, ent["body"],
                                 ent["prio"], ent["rs"], raw,
                                 attempts=ent["attempts"] + 1)

    def _push_up_hfa(self, kvs: KVPairs):
        """K2 round: ship (mean_weights - milestone)/num_global_workers
        (ref: milestone delta :1324-1343).

        The matching pull-down requests full (dense) weights — the local
        store was just replaced by the party mean, so it has diverged from
        any pull-compressor's tracked subscriber view; a sparse delta
        against that view would corrupt the replica."""
        topo = self.po.topology
        ks, vs, ls = [], [], []
        for k, v in kvs.slices():
            with self._mu.stripe(k):
                self.store[k] = np.array(v, copy=True)  # adopt party mean
                delta = (v - self._milestone[k]) / topo.num_global_workers
            ks.append(k); vs.append(delta.astype(np.float32)); ls.append(len(v))
        out = KVPairs(np.array(ks, dtype=np.int64), np.concatenate(vs),
                      np.array(ls, dtype=np.int64))
        keys = [int(k) for k in out.keys]
        with self._mu:
            epochs = {k: self._keys[k].epoch for k in keys
                      if k in self._keys}

        def on_acked():
            self.up.zpull(keys,
                          cb=lambda kvs: self._on_pull_down_hfa(kvs, epochs),
                          cmd=Cmd.HFA_DELTA)

        self.up.zpush(out, cmd=Cmd.HFA_DELTA, on_complete=on_acked)

    def _on_pull_down_hfa(self, kvs: KVPairs, epochs: Optional[dict] = None):
        tags = kvs.tags or {}
        live = []
        for k, v in kvs.slices():
            with self._mu.stripe(k):
                if (epochs is not None and k in self._keys
                        and self._keys[k].epoch != epochs.get(k)):
                    continue  # aborted by a restore
                new_w = self._decode_pull_value(k, v, tags.get(k, ""))
                self.store[k] = new_w
                self._milestone[k] = np.array(new_w, copy=True)
                # the K2 pull bypassed the pull compressor (dense by
                # design), so any BSC tracked view upstream is now stale;
                # -1 can never equal a tracked version, forcing the next
                # compressed pull of this key to resync dense
                self._pull_ver[k] = -1
            live.append(k)
        self._finish_round(live)

    def _pull_echo(self, keys) -> dict:
        """Request body for a pull-down: echo the per-key view versions
        so the global tier's BSC compressor can detect desync."""
        with self._mu:
            return {"pv": {str(int(k)): self._pull_ver.get(int(k), 0)
                           for k in keys}}

    def _decode_pull_value(self, k: int, v: np.ndarray, tag: str) -> np.ndarray:
        """Decode one pull-down slab into the new full weight vector.
        Caller holds stripe(k) (or the all-stripes barrier).
        "bsc" payloads are sparse deltas against
        the current replica (ref: BSC decode :310-336); "f32" is a dense
        resync forced by a view-version mismatch (server or subscriber
        restarted, or a pull response was lost)."""
        from geomx_tpu.compression.codecs import unpack_sparse

        if tag == "bsc":
            vals, idx = unpack_sparse(np.ascontiguousarray(v).view(np.float32))
            # COW gate: the current replica may be frozen (aliased by
            # in-flight responses / adopted from upstream) — the delta
            # must not mutate it under those readers
            w = _mutable(self.store[k])
            w[idx] += vals
            return w
        if tag == "fp16":
            return np.ascontiguousarray(v).view(np.float16).astype(np.float32)
        if tag == "f32":
            arr = np.ascontiguousarray(v).view(np.float32)
            # frozen payload = upstream's immutability promise: adopt the
            # alias instead of copying (every local mutation path COWs)
            return arr if not arr.flags.writeable else arr.copy()
        if v.dtype == np.float32 and not v.flags.writeable:
            return v
        return np.array(v, copy=True)

    def _on_pull_down(self, kvs: KVPairs, epochs: Optional[dict] = None):
        """Updated weights arrived from tier 2 — possibly compressed
        (ref: DataHandlePullResponseDefault :974-1169).  Keys whose
        epoch moved since the round started were checkpoint-restored
        mid-flight: skip them (their round was aborted and their parked
        pulls already drained); the rest finish normally."""
        tags = kvs.tags or {}
        pv = kvs.pv or {}
        wv = kvs.wv or {}
        with self._tr.span("local.pull_down"):
            live = []
            for k, v in kvs.slices():
                with self._mu.stripe(k):
                    if (epochs is not None
                            and k in self._keys
                            and self._keys[k].epoch != epochs.get(k)):
                        continue  # aborted by a restore
                    tag = tags.get(k, "")
                    if k in wv and wv[k] < self._weight_ver.get(k, -1):
                        # overlapping rounds flush their responses with
                        # no stripes held, so round N's response can
                        # arrive AFTER round N+1's (its encode races
                        # the next close — widest when the weight
                        # materializes off-device first).  Applying it
                        # would roll the replica back a round and serve
                        # stale weights to every worker until the next
                        # push; dropping it still finishes the round.
                        # Strictly-older only: an equal stamp is the
                        # same weights (re-applying is idempotent)
                        self.stale_pull_skips += 1
                        live.append(k)
                        continue
                    if k in pv:
                        # overlapping rounds can deliver responses out of
                        # order (van delay/priority queues): a bsc delta is
                        # only valid against the exact view it was encoded
                        # for (ver pv-1), and a dense resync must never be
                        # overwritten by an older response.  Skipping still
                        # finishes the round — the replica stays one round
                        # behind and the next echo mismatch heals it dense.
                        cur = self._pull_ver.get(k, 0)
                        if tag == "bsc" and cur != pv[k] - 1:
                            self.stale_pull_skips += 1
                            live.append(k)
                            continue
                        if tag == "f32" and pv[k] <= cur:
                            self.stale_pull_skips += 1
                            live.append(k)
                            continue
                    self.store[k] = self._decode_pull_value(k, v, tag)
                    if k in pv:
                        self._pull_ver[k] = pv[k]
                    if k in wv:
                        self._weight_ver[k] = wv[k]
                live.append(k)
            self._finish_round(live)

    def _finish_round(self, keys: List[int]):
        """Unblock keys and retry their parked pulls.  Takes each key's
        stripe itself (callers holding the all-stripes barrier just
        re-enter); the retries run with no stripe held — a multi-key
        pull re-acquires stripes in its own key order."""
        to_retry: List[Message] = []
        for k in keys:
            with self._mu.stripe(k):
                st = self._keys[k]
                st.in_flight = max(0, st.in_flight - 1)
                st.version += 1
                to_retry.extend(st.parked_pulls)
                st.parked_pulls.clear()
        for req in to_retry:
            self._try_serve_pull(req)
        if self._flight is not None:
            self._flight.record(FlightEv.ROUND_COMPLETE, a=len(keys),
                                b=self.wan_push_rounds, note="local")
        if self.ts_client is not None:
            # hand fresh weights to the overlay dissemination thread;
            # the per-key astype copies happen under the stripe so a
            # concurrent in-place decode cannot tear them
            ks = sorted(keys)
            vs = []
            for k in ks:
                with self._mu.stripe(k):
                    vs.append(self.store[k].astype(np.float32))
            with self._ctr_mu:
                self._ts_iter += 1
                it = self._ts_iter
            self.ts_client.disseminate_async(
                np.array(ks, dtype=np.int64),
                np.concatenate(vs),
                np.array([len(v) for v in vs], dtype=np.int64),
                f"{self.po.node}:{it}", Cmd.TS_AUTOPULL)

    def _drain_parked_locked(self, st: _KeyState):
        """Caller holds the all-stripes barrier (init / warm-boot /
        async paths)."""
        parked, st.parked_pulls = st.parked_pulls, []
        for req in parked:
            self._try_serve_pull(req)

    def _handle_pull(self, msg: Message, kvs: KVPairs):
        self._try_serve_pull(msg)

    def _try_serve_pull(self, req: Message) -> bool:
        """Serve a pull if every key is initialized and not mid-round,
        else re-park it on the first blocking key (the reference spins on
        initialized_, ref :1721-1723 — we park event-driven).  A multi-key
        pull is re-validated against ALL its keys each time it is retried.
        Takes one stripe at a time (never two); safe to call under the
        all-stripes barrier (re-entry), never under a single OTHER
        stripe."""
        sender_s = str(req.sender)
        for k in req.keys:
            k = int(k)
            with self._mu.stripe(k):
                st = self._keys.get(k)
                if st is None:
                    st = self._keys.setdefault(k, _KeyState())
                # blocked while any WAN round is in flight OR a round this
                # sender CONTRIBUTED to is accumulating: both mean fresher
                # weights than the store's are owed to this puller.  A
                # non-contributor's pull is served from the last completed
                # round instead — a dynamic joiner bootstrapping (pull
                # before first push) must not park behind a round that can
                # only complete with its own push (advisor r4 deadlock),
                # and a worker lagging a round behind wants exactly the
                # store's weights, not the open round's future ones.
                # EXCEPT during a TS-MERGED round (count > distinct senders:
                # some push carried num_merge>1): a KNOWN PARTY MEMBER's
                # contribution may be inside the open accumulator even
                # though it never pushed directly — under the TS push
                # overlay non-elected workers NEVER push directly, so any
                # push-history test would serve them stale forever
                # (advisor r5, round-5 refinement) and party replicas
                # would silently diverge for every partial-merge window.
                # Members park; the round completes without their direct
                # push by construction (their contribution rode the
                # merge tree).  Serve-stale stays for out-of-plan
                # BOOTSTRAP pulls — a joiner that has not pushed anything
                # yet (parking those is the r4 deadlock) — and for plain
                # rounds (count == distinct senders), where the open
                # round still NEEDS this sender's own push.
                blocked = (k not in self.store or st.in_flight > 0
                           or (st.count > 0 and sender_s in st.contributors))
                if (not blocked and st.count > len(st.contributors)
                        and sender_s in self._members
                        and sender_s not in self._bootstrapping):
                    blocked = True
                if blocked:
                    st.parked_pulls.append(req)
                    return False
        if req.cmd == Cmd.ROW_SPARSE_PULL:
            # gather the requested rows only (ref: PullRowSparse).
            # Out-of-range ids are clamped defensively (the client
            # validates; an exception here would swallow the request and
            # hang the puller)
            key = int(req.keys[0])
            row_ids = np.asarray(req.body["rows"], dtype=np.int64)
            cols = int(req.body["rs_cols"])
            from geomx_tpu.compression.codecs import pack_rows

            with self._mu.stripe(key):
                table = self.store[key].reshape(-1, cols)
                row_ids = np.clip(row_ids, 0, len(table) - 1)
                payload = pack_rows(row_ids, table[row_ids])
            self.server.response(req, KVPairs(
                np.array([key], np.int64), payload,
                np.array([len(payload)], np.int64)))
            return True
        ks = [int(k) for k in req.keys]
        if len(ks) == 1:
            # single key: freeze-in-place and serve the alias
            # (_store_payload) — zero-copy, in-place decodes COW
            with self._mu.stripe(ks[0]):
                w = self.store[ks[0]]
                payload = (_store_payload([w]) if w.dtype == np.float32
                           else np.array(w, np.float32))
            ls = [len(payload)]
        else:
            # multi-key: the response concatenates anyway (the isolation
            # copy) — copy each slice under ITS stripe straight into the
            # response buffer.  One total copy, exactly the pre-sharding
            # concat; deliberately NO freeze — freezing here would force
            # a full COW on every later in-place decode of these keys
            # (+0.2 s/round at the 50M flagship), and the under-stripe
            # copy already rules out a torn read.
            ls = []
            for k in ks:
                with self._mu.stripe(k):
                    ls.append(len(self.store[k]))
            payload = np.empty(sum(ls), np.float32)
            off = 0
            for k, ln in zip(ks, ls):
                with self._mu.stripe(k):
                    payload[off:off + ln] = self.store[k]
                off += ln
        # P3 piggybacked pushes park here until the round finishes; record
        # the response so a replay re-serves values instead of re-merging
        self._recent.mark_done(req)
        self.server.response(req, KVPairs(
            np.array(ks, dtype=np.int64), payload,
            np.array(ls, dtype=np.int64)))
        return True

    # ---- control ------------------------------------------------------------
    def _on_cmd(self, msg: Message):
        body = msg.body or {}
        if msg.cmd in (Ctrl.SET_SYNC_MODE, Ctrl.SET_COMPRESSION,
                       Ctrl.SET_HFA):
            # these flip how queued merges would be interpreted; keep
            # the handler-thread program order vs. the merge lanes
            self._shards.drain()
        if msg.cmd == Ctrl.SET_SYNC_MODE:
            self.sync_mode = bool(body["sync"])
        elif msg.cmd == Ctrl.SET_COMPRESSION:
            from geomx_tpu.compression import compression_allowed

            if body == self.compression:
                # idempotent: a mid-training recreation would drop the
                # unsent residual/velocity mass held in the old codec
                self.server.reply_cmd(msg)
                return
            # hfa=False: a static/operator SET_COMPRESSION under HFA is
            # the dense-bypass case (predicate docstring); only runtime
            # POLICY retuning restricts to weight-safe codecs
            ok, why = compression_allowed(
                body.get("type", "none"),
                inter_ts=self.config.enable_inter_ts)
            if not ok:
                self.server.reply_cmd(msg, body={"error": why})
                return
            try:
                self.push_codec = self._make_push_codec(body)
                self.compression = body
            except ValueError as e:
                self.server.reply_cmd(msg, body={"error": str(e)})
                return
        elif msg.cmd == Ctrl.SET_WAN_POLICY:
            self._on_set_wan_policy(msg, body)
            return
        elif msg.cmd == Ctrl.SET_HFA:
            if bool(body["enabled"]) and self._saw_row_sparse:
                self.server.reply_cmd(msg, body={
                    "error": "cannot enable HFA: row-sparse tensors are in "
                             "use (HFA exchanges weights, not gradients)"})
                return
            self.hfa_enabled = bool(body["enabled"])
            self.hfa_k2 = int(body.get("k2", 1))
        elif msg.cmd == Ctrl.QUERY_STATS:
            self.server.reply_cmd(msg, body=self.stats())
            return
        elif msg.cmd == Ctrl.ESYNC:
            # state server (ESync, ref README.md:45 "to be integrated"):
            # record this worker's measured times, reply with its next
            # local-step assignment.  Lazily constructed — ESync is
            # opt-in via the worker loop, no config needed server-side.
            if self._esync is None:
                from geomx_tpu.sched.esync import EsyncState

                # generous server ceiling; the effective cap per worker
                # is the max_steps its own loop reports
                self._esync = EsyncState(max_steps=1024)
            self._esync.report(str(body["worker"]),
                               float(body["step_s"]),
                               float(body["comm_s"]),
                               max_steps=int(body.get("max_steps", 0)))
            plan = self._esync.plan()
            self.server.reply_cmd(msg, body={
                "steps": plan.get(str(body["worker"]),
                                  self._esync.min_steps),
                "plan": plan,
            })
            return
        elif msg.cmd == Ctrl.PROFILER:
            _handle_profiler_cmd(self.po, msg, self.server)
            return
        self.server.reply_cmd(msg)

    def stats(self) -> dict:
        """The QUERY_STATS body — also sampled on an interval by the
        telemetry plane's MetricsPump (geomx_tpu/obs), so the wire
        query and the shipped time series can never disagree."""
        van = self.po.van
        with self._mu:
            # memory accounting (the reference profiler's memory
            # stats, ref: src/profiler/profiler.h:256-304): resident
            # weight replicas + in-flight aggregation buffers
            store_b = sum(a.nbytes for a in self.store.values())
            accum_b = sum(st.accum.nbytes for st in self._keys.values()
                          if st.accum is not None)
        return {
            "wan_send_bytes": van.wan_send_bytes,
            "wan_recv_bytes": van.wan_recv_bytes,
            "send_bytes": van.send_bytes,
            "recv_bytes": van.recv_bytes,
            "store_bytes": store_b,
            "accum_bytes": accum_b,
            "hfa_gated_key_rounds": self.hfa_gated_key_rounds,
            "ts_deliveries": self.ts_deliveries,
            "stale_pull_skips": self.stale_pull_skips,
            # crash-tolerant membership observability
            "evicted_workers": self.evicted_workers,
            "eviction_fenced_pushes": self.eviction_fenced_pushes,
            "warm_boots": self.warm_boots,
            # elastic-membership observability: the churn_storm health
            # rule sums these deltas over its collector window
            "joined_workers": self.joined_workers,
            "left_workers": self.left_workers,
            "preempt_server_drains": self.preempt_server_drains,
            # partition-tolerance observability (quarantine-not-evict)
            "degraded": self._degraded,
            "degraded_rounds": self.degraded_rounds,
            "catchup_pending_rounds": self._catchup_rounds,
            "catchup_pushes": self.catchup_pushes,
            "catchup_fallbacks": self.catchup_fallbacks,
            "quarantined_workers": len(self._quarantined_members),
            # data-integrity observability (gradient hygiene)
            "integrity_poison_rejects": self.integrity_poison_rejects,
            "poison_quarantines": self.poison_quarantines,
            "integrity_codec_rejects": self.integrity_codec_rejects,
            "mpq_bsc_picks": getattr(self.push_codec, "bsc_picks", 0),
            "mpq_fp16_picks": getattr(self.push_codec, "fp16_picks", 0),
            "pq_overtakes": van.pq_overtakes,
            # adaptive-WAN controller signals: round rate + link RTT
            # + this sender's applied policy epoch
            "wan_push_rounds": self.wan_push_rounds,
            "policy_epoch": self._policy_epoch,
            "policy_fence_retries": self.policy_fence_retries,
            "policy_drops": self.policy_drops,
            "hb_rtt_s": max(self.po.heartbeat_rtts().values(),
                            default=None),
            # restart discrimination: a warm-booted replacement's zeroed
            # counters carry a fresh boot nonce + near-zero uptime, so a
            # collector can fence its rate windows instead of reading
            # the reset as a rate collapse
            "uptime_s": self.po.uptime_s(),
            "boot": van.boot,
            # merge backend observability (kvstore/backend.py):
            # merge_backend name + the jax path's merge_device_ms /
            # h2d_bytes, mirrored to the registry for the status console
            **self._merge_stats(),
        }

    def _merge_stats(self) -> dict:
        out = self._backend.stats()
        ms, h2d = out.get("merge_device_ms"), out.get("h2d_bytes")
        if ms is not None:
            from geomx_tpu.utils.metrics import system_gauge

            system_gauge(f"{self.po.node}.merge_device_ms").set(ms)
            system_gauge(f"{self.po.node}.h2d_bytes").set(h2d or 0)
            # device->host traffic + optimizer-stage time: the
            # steady-state zero-D2H contract is audited on these
            system_gauge(f"{self.po.node}.d2h_bytes").set(
                out.get("d2h_bytes") or 0)
            system_gauge(f"{self.po.node}.opt_device_ms").set(
                out.get("opt_device_ms") or 0)
            # codec stage (ISSUE 20): encode kernel time + wire-ready
            # compressed D2H — host_copy auditing rides the same stats
            system_gauge(f"{self.po.node}.codec_device_ms").set(
                out.get("codec_device_ms") or 0)
            system_gauge(f"{self.po.node}.codec_d2h_bytes").set(
                out.get("codec_d2h_bytes") or 0)
        return out

    def leave_global(self, timeout: float = 30.0) -> dict:
        """Gracefully withdraw this PARTY from the global tier (VERDICT
        r4 item 6; beyond the reference — its global membership is
        static and recovery a TODO, van.cc:224).  Call once the party is
        done training (all worker rounds drained): every global server
        lowers num_global_workers at the round boundary, so the
        remaining parties' rounds complete without us instead of
        stalling forever.  Idempotent server-side; retried per global
        server on timeout (lossy-WAN safe)."""
        import uuid

        topo = self.po.topology
        results = {}
        for gs in topo.global_servers():
            token = f"{self.po.node}#{uuid.uuid4().hex[:8]}"
            cv = threading.Condition()
            reply: dict = {}

            def hook(msg, _token=token, _cv=cv, _reply=reply) -> bool:
                b = msg.body if isinstance(msg.body, dict) else {}
                if (msg.control is Control.ADD_NODE and not msg.request
                        and b.get("token") == _token):
                    with _cv:
                        _reply.update(b)
                        _cv.notify_all()
                    return True
                return False

            self.po.add_control_hook(hook)
            try:
                deadline = time.monotonic() + timeout
                for _ in range(3):
                    self.po.van.send(Message(
                        recipient=gs, control=Control.ADD_NODE,
                        domain=Domain.GLOBAL, request=True,
                        body={"action": "party_leave",
                              "node": str(self.po.node), "token": token}))
                    with cv:
                        if cv.wait_for(lambda: bool(reply),
                                       timeout=max(0.1, min(
                                           timeout / 3,
                                           deadline - time.monotonic()))):
                            break
                else:
                    raise TimeoutError(
                        f"{self.po.node}: party_leave to {gs} timed out")
            finally:
                self.po.remove_control_hook(hook)
            results[str(gs)] = dict(reply)
        return results

    def stop(self):
        if self._degrade_ticker is not None:
            self._degrade_ticker.stop()
        if self.ts_client is not None:
            self.ts_client.stop()
        if self.ts_inter is not None:
            self.ts_inter.stop()
        if self.ts_push_inter is not None:
            self._merge_q.put(None)
        self._shards.stop()
        self._backend.stop()
        self.server.stop()
        self.up.stop()


class _GlobalKeyState:
    __slots__ = ("accum", "count", "parked_pushes", "parked_pulls", "ver",
                 "contributors", "deferred")

    def __init__(self):
        self.accum: Optional[np.ndarray] = None
        self.count = 0
        # entries are [msg, set-of-keys-not-yet-updated]; a push is acked
        # when its remaining-set empties
        self.parked_pushes: List[list] = []
        self.parked_pulls: List[Message] = []
        # BSP same-sender fence: senders already merged into the OPEN
        # round; a second plain push from one of them belongs to the
        # NEXT round and waits in ``deferred`` (entries
        # ``(sender, value, parked-push entry, donated)``) until this
        # round closes — see the fence comment in _push_sync.merge_one
        self.contributors: set = set()
        self.deferred: List[tuple] = []
        # weight version: bumped with every store update that produces
        # NEW weights (round close / async push / catch-up merge).
        # Stamped onto pull-down responses ("wv" body) so a subscriber
        # can drop a late response that would roll its replica back —
        # responses to overlapping rounds are flushed with no stripes
        # held and CAN reorder in flight (the encode of round N's
        # response races round N+1's close)
        self.ver = 0


class GlobalServer:
    """Tier-2: owns a shard of the key space, runs the optimizer
    (ref: global-server paths of DataHandleSyncDefault :1302-1319 and the
    async handlers :1519-1698).

    ``standby=True`` runs the same server as a HOT STANDBY: it applies
    ``Cmd.REPLICATE`` state snapshots from its primary and parks any
    regular traffic until the global scheduler promotes it
    (``Control.PROMOTE``).  Promotion carries a **term**; a zombie
    ex-primary keeps its stale term and is fenced — its replication is
    rejected and its data path refuses pushes (see
    kvstore/replication.py for the full protocol)."""

    def __init__(self, postoffice: Postoffice, config: Optional[Config] = None,
                 standby: bool = False):
        self.po = postoffice
        self.config = config or postoffice.config
        topo = postoffice.topology
        self.num_contributors = topo.num_global_workers
        # host ndarrays and/or device-resident weight handles; reads
        # through the mapping interface always materialize to host
        self.store: Dict[int, np.ndarray] = WeightStore()
        self._keys: Dict[int, _GlobalKeyState] = {}
        # key-sharded merge (see LocalServer): stripe(k) guards key k,
        # ``with self._mu:`` is the all-stripes barrier for party
        # folds, failover fences, replication snapshots and policy
        # swaps — their atomicity against the data path is unchanged.
        # Lanes are built per merge backend (kvstore/backend.py).
        self._backend = make_merge_backend(self.config,
                                           str(postoffice.node))
        # device-resident WAN codec stage (ISSUE 20): compressed pushes
        # decode through jitted kernels straight into device arrays the
        # merge lanes seed without re-staging (zero full-tensor host
        # traffic on the push→decode→merge→optimize chain)
        self._codec_stage = self._backend.make_codec_stage(self.config)
        self._mu, self._shards = make_merge_lanes(
            self.config, f"g{postoffice.node}", self._backend)
        self._ack_mu = threading.Lock()  # leaf lock: a parked push's
        #                                  remaining-keys set is shared
        #                                  across stripes
        self._pc_mu = threading.RLock()  # leaf lock: the pull
        #                                  compressor's per-subscriber
        #                                  views/caches are not striped
        self._wv_mu = threading.Lock()   # leaf lock: pairs a store
        #                                  write with its ver bump so a
        #                                  responder snapshots (weights,
        #                                  wv) coherently.  May be taken
        #                                  under a stripe or _pc_mu;
        #                                  takes no lock itself
        # ---- failover state (tentpole PR 1) ----
        self.is_standby = bool(standby)
        self.term = 0              # fencing epoch; bumped by promotion
        self.promotions = 0        # times this node was promoted
        self.fenced_rejects = 0    # stale-term replication pushes refused
        self._fenced = False       # this node was deposed: refuse data
        self._fence_reason = ""
        self._repl_seq = 0         # last applied replication snapshot
        self._parked_standby: List[tuple] = []  # (msg, kvs) pre-promotion
        self._repl = None          # Replicator on a primary with a standby
        # live key-range reassignment (shard drain): once this holder
        # ships its final snapshot to the new holder it DROPS data
        # requests silently — to clients it looks exactly like the dead
        # primary of a failover, so the proven retarget+replay path
        # moves their traffic; the fence answers any control stragglers
        self._draining = False
        self._handoff_kw = None    # lazily-built ship endpoint (one per
        #                            lifetime; Customer ids don't recycle)
        self.drains = 0            # completed handoffs (observability)
        self.merged_handoffs = 0   # key ranges adopted from a drain
        self.key_rounds = 0        # completed (key, round) optimizer
        #                            updates — the telemetry plane's
        #                            per-shard round-progress series
        #                            (a stalled shard stops counting)
        self.optimizer: ServerOptimizer = Sgd()
        self._optimizer_configured = False  # flips on SET_OPTIMIZER; a
        #                                     central-worker deployment
        #                                     gates training on it
        # device-resident optimizer stage (kvstore/jax_backend.py):
        # non-None when the merge backend runs the round close on
        # device — weights+moments stay device-resident, host copies
        # only at serve/checkpoint/handoff events.  ``self.optimizer``
        # stays the host-semantics shell (type tag, DCASGD fallback,
        # the pickle format every snapshot round-trips through)
        self._dev_opt = None
        self.sync_mode = self.config.sync_global_mode
        self.compression: dict = {"type": "none"}
        # a run that never configures an optimizer still closes rounds
        # on device under the jax backend (default Sgd is in the family)
        self._activate_dev_opt_locked()
        self.pull_comp = None  # BroadcastCompressor under bsc/mpq
        self.subscriber_prunes = 0  # departed/evicted subscribers whose
        #                             tracked pull-compressor views were
        #                             freed (each view pins a full model
        #                             copy — the PR 8 leak fix)
        # adaptive WAN (geomx_tpu/control), RECEIVER side: SET_WAN_POLICY
        # adopts the new decode parameters + pull compressor immediately
        # (tracked views invalidated through the version handshake —
        # subscribers resync dense), and gradient pushes stamped with a
        # different epoch are fenced with a retryable error carrying the
        # current policy, so the sender re-encodes instead of this server
        # misdecoding.  Off (default): one flag check per push.
        self._adaptive = bool(self.config.adaptive_wan)
        self._policy_epoch = 0
        self.policy_fenced_pushes = 0
        self.rejected_compr_tags = 0
        self.catchup_merges = 0  # healed-party Cmd.CATCHUP deltas merged
        # gradient hygiene at the WAN tier (Config.integrity_push_screen)
        self._poison_strikes: Dict[str, int] = {}
        self.integrity_poison_rejects = 0
        # verified durable state (GEOMX_INTEGRITY_CKPT): corrupt
        # checkpoint generations / replication snapshots rejected
        self.integrity_ckpt_rejects = 0
        # structurally-corrupt compressed payloads fenced at decode time
        self.integrity_codec_rejects = 0
        # per-endpoint stateful-decoder cache (replaces the process-wide
        # _TWOBIT_DECODERS dict two concurrent Simulations used to share)
        from geomx_tpu.compression import DecoderBank

        self._decoders = DecoderBank()
        self._recent = RecentRequests()  # replayed-push dedup
        # automatic periodic checkpoints (mid-round crash recovery; an
        # improvement over the reference, whose server state is RAM-only)
        self._since_ckpt = 0
        self._ckpt_busy = False
        self._ckpt_pending = False
        from geomx_tpu.trace.recorder import get_tracer
        from geomx_tpu.utils import get_profiler

        self._prof = get_profiler(str(postoffice.node))
        self._tr = get_tracer(str(postoffice.node))
        # flight recorder (obs/flight.py): fence/promotion/round events
        # + this shard's merge-pressure sources; None when disabled
        self._flight = postoffice.flight
        attach_server_pressure(self._flight, self._mu, self._shards)
        if self._flight is not None:
            self._flight.record(FlightEv.MERGE_BACKEND, a=self._mu.n,
                                note=self._backend.name)
        # inter-party TSEngine: after a sync round updates, disseminate
        # the fresh weights to the local servers via the WAN overlay
        # instead of serving N pulls (sync tier only)
        self.ts_inter = None
        self._ts_iter = 0
        # async-tier dissemination is rate-limited: per-push relays would
        # flood the overlay, so fresh weights go out at most once per
        # inter_ts_async_every pushes, covering every key updated since
        # the previous dissemination
        self._ts_async_pushes = 0
        self._ts_async_dirty: set = set()
        if self.config.enable_inter_ts:
            from geomx_tpu.sched.tsengine import TsClient

            self.ts_inter = TsClient(
                postoffice, topo.global_scheduler(), domain=Domain.GLOBAL)
        # parties that announced a graceful leave (idempotency set)
        self._left_parties: set = set()
        # parties folded out REVERSIBLY because their local server died
        # (kvstore/eviction.py LocalServerRecoveryMonitor): same fold as
        # a leave, but a warm-booted replacement folds back in
        self._folded_parties: set = set()
        self.party_folds = 0
        self.party_unfolds = 0
        postoffice.add_control_hook(self._on_add_node)
        postoffice.add_control_hook(self._on_evict)
        postoffice.add_control_hook(self._on_promote)
        postoffice.add_control_hook(self._on_new_primary)
        postoffice.add_control_hook(self._on_handoff)
        self.server = KVServer(APP_PS, 0, postoffice, self._handle)
        self.server.cmd_handler = self._on_cmd
        # the axpy-vs-numpy calibration must never run inside the locked
        # merge path — warm the cached verdict at startup instead
        from geomx_tpu.native.bindings import calibrate_async

        calibrate_async(self.config.server_merge_threads)
        if not self.is_standby:
            sb = topo.standby_for(postoffice.node.rank)
            if sb is not None and str(sb) != str(postoffice.node):
                from geomx_tpu.kvstore.replication import Replicator

                self._repl = Replicator(self, sb)

    def _on_add_node(self, msg: Message) -> bool:
        """Graceful PARTY leave at the global tier (VERDICT r4 item 6).
        The reference's global-tier membership is static and its global
        recovery is a TODO (van.cc:224) — this goes beyond it: a local
        server announces its party will push no more, the aggregation
        target drops at the round boundary, and mid-flight rounds
        already satisfied at the lowered target complete NOW instead of
        stalling forever.  Idempotent by party-server node id."""
        if msg.control is not Control.ADD_NODE or not msg.request:
            return False
        body = msg.body if isinstance(msg.body, dict) else {}
        if body.get("action") != "party_leave":
            return False
        node_s = str(body.get("node", msg.sender))
        with self._mu:
            if node_s not in self._left_parties:
                self._left_parties.add(node_s)
                # a crashed party that leaves gracefully later (odd but
                # possible) must not double-decrement
                already_folded = node_s in self._folded_parties
                self._folded_parties.discard(node_s)
                completed = ([] if already_folded
                             else self._fold_party_out_locked(node_s))
            else:
                completed = []  # replayed leave: no double decrement
            # HFA-mode rounds accumulate milestone DELTAS (additive);
            # everything else accumulates gradients for the optimizer
            to_ack, dissem = self._complete_keys_locked(
                completed, hfa_delta=self.config.use_hfa, dissem_ok=True)
            total = self.num_contributors
        self._flush_completions(to_ack, dissem)
        # a departed party's per-subscriber pull-compressor views are
        # dead weight (one full-model copy each) — free them; if the
        # party somehow pulls again, the no-base handshake resyncs dense
        self._prune_subscriber(node_s)
        self.po.van.send(msg.reply_to(control=Control.ADD_NODE, body={
            "num_global_workers": total, "token": body.get("token")}))
        return True

    def _prune_subscriber(self, node_s: str) -> int:
        """Free one subscriber's tracked pull-compressor views (leaves /
        folds / replica evictions).  Safe on live subscribers — a pruned
        pair's next pull resyncs dense through the version handshake."""
        with self._pc_mu:
            if self.pull_comp is None:
                return 0
            n = self.pull_comp.drop_subscriber(node_s)
        if n:
            self.subscriber_prunes += 1
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.subscriber_prunes").inc()
            print(f"{self.po.node}: pruned {n} tracked pull view(s) of "
                  f"departed subscriber {node_s}", flush=True)
        return n

    def _fold_party_out_locked(self, node_s: str) -> List[int]:
        """Lower the aggregation target by one party; returns the keys
        whose mid-flight rounds the fold made decidable (they would
        otherwise stall forever waiting for the gone party).  Shared by
        the graceful party leave and the reversible crash fold.  Caller
        holds ``_mu`` and runs the returned keys through
        ``_complete_keys_locked``."""
        self.num_contributors = max(1, self.num_contributors - 1)
        completed = [k for k, st in self._keys.items()
                     if st.accum is not None
                     and st.count >= self.num_contributors]
        # drop per-sender optimizer bookkeeping (DCASGD's
        # previous-weight backups) — a departed party's full-model
        # snapshots would otherwise stay pinned in RAM
        for st_opt in self.optimizer.state.values():
            prev = st_opt.get("prev")
            if isinstance(prev, dict):
                prev.pop(node_s, None)
        return completed

    def _on_evict(self, msg: Message) -> bool:
        """Reversible party fold (Control.EVICT from the global
        scheduler's LocalServerRecoveryMonitor): a party whose local
        server died stops counting toward global rounds — the graceful
        party-leave fold, but reversible — and counts again once its
        replacement warm-booted (``party_unfold``).  Idempotent per
        party in both directions."""
        if msg.control is not Control.EVICT or not msg.request:
            return False
        body = msg.body if isinstance(msg.body, dict) else {}
        action = body.get("action")
        if action == "subscriber_prune":
            # the replica monitor (geomx_tpu/serve) declared a serve
            # replica dead: free its tracked pull views.  Idempotent;
            # a revived replica resyncs dense on its next refresh.
            node_s = str(body.get("node", msg.sender))
            pruned = self._prune_subscriber(node_s)
            self.po.van.send(msg.reply_to(control=Control.EVICT, body={
                "pruned": pruned, "token": body.get("token")}))
            return True
        if action not in ("party_fold", "party_unfold"):
            return False
        node_s = str(body.get("node", msg.sender))
        to_ack: List[tuple] = []
        dissem = None
        changed = False
        with self._mu:
            if action == "party_fold":
                if (node_s not in self._folded_parties
                        and node_s not in self._left_parties):
                    self._folded_parties.add(node_s)
                    self.party_folds += 1
                    changed = True
                    completed = self._fold_party_out_locked(node_s)
                    to_ack, dissem = self._complete_keys_locked(
                        completed, hfa_delta=self.config.use_hfa,
                        dissem_ok=True)
            else:  # party_unfold
                if node_s in self._folded_parties:
                    self._folded_parties.discard(node_s)
                    self.num_contributors += 1
                    self.party_unfolds += 1
                    changed = True
            total = self.num_contributors
        if changed:
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.{action}s").inc()
            if self._flight is not None:
                self._flight.record(
                    FlightEv.FOLD if action == "party_fold"
                    else FlightEv.UNFOLD, c=total, peer=node_s,
                    note=action)
            print(f"{self.po.node}: {action} {node_s} "
                  f"(num_global_workers={total})", flush=True)
            if action == "party_fold":
                # the folded party's tracked views are freed too: its
                # warm boot pulls dense and echoes -1, so the resync the
                # handshake forces anyway makes the prune free
                self._prune_subscriber(node_s)
        self._flush_completions(to_ack, dissem)
        self.po.van.send(msg.reply_to(control=Control.EVICT, body={
            "num_global_workers": total, "token": body.get("token")}))
        return True

    def _handle(self, msg: Message, kvs: Optional[KVPairs], server: KVServer):
        prof = self._prof
        if prof.running and msg.push and msg.cmd != Cmd.INIT:
            prof.count("push_bytes", float(msg.nbytes))
        span_name = ("global.init" if msg.cmd == Cmd.INIT
                     else "global.push" if msg.push else "global.pull")
        with prof.span(span_name), self._tr.span(span_name):
            self._handle_inner(msg, kvs, server)

    def _handle_inner(self, msg: Message, kvs: Optional[KVPairs],
                      server: KVServer):
        if msg.cmd == Cmd.REPLICATE:
            self._on_replicate(msg, kvs)
            return
        if self._draining and msg.request and (msg.push or msg.pull):
            # drained holder: to the data plane this node is DEAD — the
            # request is dropped without a response so the sender's
            # replay machinery re-issues it at the new holder after the
            # NEW_PRIMARY retarget (an error reply here would surface as
            # a failure instead of riding the proven failover path)
            return
        if self._fenced and msg.request:
            # deposed ex-primary: accepting pushes here would fork the
            # store from the promoted standby's (split brain) — refuse
            # loudly; retargeted clients never come back anyway
            err = {"error": f"fenced: {self._fence_reason} "
                            f"(term {self.term})", "term": self.term}
            server.response(msg, body=err)
            return
        if self.is_standby and msg.request:
            # replayed traffic can race ahead of the PROMOTE command —
            # park it (bounded; the replay layer re-sends on overflow)
            # and re-dispatch at promotion
            with self._mu:
                if len(self._parked_standby) < 4096:
                    self._parked_standby.append((msg, kvs))
            return
        if msg.cmd == Cmd.INIT:
            # overwrite-INITs must not interleave with merges still
            # queued on lanes from earlier-arrived pushes
            self._shards.drain()
            state = self._recent.check(msg)
            if state == "pending":
                return
            if state == "done":
                server.response(msg, body=self._recent.done_body(msg))
                return
            overwrite = bool(isinstance(msg.body, dict)
                             and msg.body.get("overwrite"))
            stale_acks: List[Message] = []
            with self._mu:
                fresh = False
                for k, v in kvs.slices():
                    if k not in self.store or overwrite:
                        fresh = True
                        self.store[k] = np.array(v, copy=True)
                        st = self._keys.setdefault(k, _GlobalKeyState())
                        if overwrite:
                            # a restore ABORTS in-flight rounds: drop the
                            # aggregation state AND the abandoned
                            # optimizer trajectory (momentum/Adam moments
                            # from the discarded run would drag the
                            # restored weights right back), and ack any
                            # parked pushers so no party wedges waiting
                            # for a round that will never complete
                            st.accum = None
                            st.count = 0
                            self._drop_opt_key_locked(k)
                            for ent in st.parked_pushes:
                                ent[1].discard(k)
                                if not ent[1]:
                                    stale_acks.append(ent[0])
                            st.parked_pushes.clear()
                        # init may race ahead of early pulls (under the
                        # barrier, re-parking inline is lock-safe)
                        for m in self._serve_parked_pulls_locked(int(k)):
                            self._park_pull(m)
                if fresh and overwrite and self.pull_comp is not None:
                    # drop ONLY the overwritten keys' tracked views and
                    # re-seed their INIT bases with the propagated value;
                    # a full compressor rebuild would also re-seed
                    # untouched keys' bases from trained weights that
                    # echo-0 subscribers never held
                    for k, v in kvs.slices():
                        self.pull_comp.invalidate_key(int(k), v)
                elif fresh and self.pull_comp is not None:
                    for k, v in kvs.slices():
                        self.pull_comp.ensure_base(int(k), v)
                if fresh:
                    # force a baseline checkpoint: a crash before the
                    # first periodic one must still restore the key set
                    self._auto_ckpt_locked(force=True)
                    if self._repl is not None:
                        self._repl.mark_locked(force=True)
            for req in stale_acks:
                self._recent.mark_done(req)
                self.server.response(req)
            self._recent.mark_done(msg)
            server.response(msg)
            return
        if msg.push and msg.request and self._reject_bad_push(msg):
            return  # fenced at message-decode time, before any merge
        if msg.push and msg.compr and kvs is not None:
            try:
                kvs = self._decompress_push(msg, kvs)
            except CodecError as e:
                # a truncated / bit-rotted payload that slipped past (or
                # never crossed) the wire checksums: fence the one push,
                # never the merge thread.  Like _reject_bad_push this
                # sits ahead of the replay-dedup window, so the sender's
                # retried re-encode is processed fresh.
                self.integrity_codec_rejects += 1
                from geomx_tpu.utils.metrics import system_counter

                system_counter(
                    f"{self.po.node}.integrity_codec_rejects").inc()
                if self._flight is not None:
                    self._flight.record(FlightEv.CORRUPT, d=msg.boot,
                                        peer=msg.sender,
                                        note="corrupt_codec_payload")
                self.server.response(msg, body={
                    "error": f"corrupt compressed push from {msg.sender} "
                             f"refused before merge: {e}"})
                return
        if msg.push:
            if msg.cmd == Cmd.CATCHUP:
                # partition heal: a quarantined party's bounded degraded-
                # round delta — merged through the optimizer, but NEVER
                # part of sync-round accounting (the party was folded
                # out; survivors' rounds already closed without it)
                self._push_catchup(msg, kvs)
            elif self.sync_mode:
                self._push_sync(msg, kvs)
            else:
                self._push_async(msg, kvs)
        elif msg.pull:
            self._pull(msg, kvs)

    def _reject_bad_push(self, msg: Message) -> bool:
        """Fence a push BEFORE it can reach the merge: (a) a malformed /
        foreign compr tag would raise a bare ValueError deep inside
        ``decompress_payload`` and poison the round — answer with an
        error naming the offending node, tag and policy epoch instead;
        (b) under adaptive WAN, a gradient push whose policy epoch
        differs from this server's current one is refused with a
        RETRYABLE error carrying the current policy, so the sender
        re-encodes rather than this server decoding with the wrong
        parameters.  Deliberately ahead of the replay-dedup window: a
        fenced request is never recorded, so its retried re-encode is
        processed fresh.  Returns True when the push was answered."""
        from geomx_tpu.compression.codecs import KNOWN_PUSH_TAGS

        if msg.compr and msg.compr not in KNOWN_PUSH_TAGS:
            self.rejected_compr_tags += 1
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.rejected_compr_tags").inc()
            if self._flight is not None:
                self._flight.record(FlightEv.FENCE, b=msg.policy_epoch,
                                    d=msg.boot, peer=msg.sender,
                                    note="bad_compr_tag")
            self.server.response(msg, body={
                "error": f"unknown compression tag '{msg.compr}' in push "
                         f"from {msg.sender} (policy epoch "
                         f"{msg.policy_epoch}); payload refused before "
                         "merge", "compr": msg.compr})
            return True
        if (self._adaptive and msg.cmd == Cmd.DEFAULT
                and msg.policy_epoch != self._policy_epoch):
            self.policy_fenced_pushes += 1
            from geomx_tpu.utils.metrics import system_counter

            system_counter(f"{self.po.node}.policy_fenced_pushes").inc()
            with self._mu:
                cur_epoch = self._policy_epoch
                cur_policy = dict(self.compression)
            if self._flight is not None:
                self._flight.record(FlightEv.FENCE, a=msg.policy_epoch,
                                    b=cur_epoch, d=msg.boot,
                                    peer=msg.sender, note="policy_epoch")
            self.server.response(msg, body={
                "error": f"policy epoch fenced: push from {msg.sender} "
                         f"carries epoch {msg.policy_epoch}, server is "
                         f"at {cur_epoch}; re-encode under the current "
                         "policy and retry",
                "policy_fenced": True, "policy_epoch": cur_epoch,
                "policy": cur_policy})
            return True
        return False

    def _screen_push(self, msg: Message, kvs: KVPairs) -> KVPairs:
        """Gradient-hygiene screen at the WAN tier — the belt to the
        local tier's suspenders: a party whose local screen is off, or
        whose merged gradient rotted past the wire checksums, must not
        poison the global model.  A poisoned payload is replaced with
        zeros and tagged via ``msg._gx_poisoned``; the sync path merges
        the zero contribution (the round counts parties — a reject
        without a merge would stall survivors) and the parked ack
        carries the typed error, while the async/catch-up paths reject
        outright.  Party-level quarantine deliberately stays the
        scheduler's call — the ``data_corruption`` health rule surfaces
        repeat offenders; folding out a whole party over NaNs is a far
        bigger hammer than the local tier's single-worker quarantine."""
        if not self.config.integrity_push_screen:
            return kvs
        if self._backend.screen_finite(kvs.vals,
                                       self.config.poison_mag_max):
            return kvs
        sender_s = str(msg.sender)
        self.integrity_poison_rejects += 1  # GIL-atomic, as the fences
        strikes = self._poison_strikes.get(sender_s, 0) + 1
        self._poison_strikes[sender_s] = strikes
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.integrity_poison_rejects").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.CORRUPT, a=strikes,
                                peer=sender_s, note="poison_push")
        msg._gx_poisoned = {
            "error": f"poisoned push rejected at the global tier: "
                     f"payload from {sender_s} failed the finiteness/"
                     f"magnitude screen (strike {strikes}); "
                     "contribution zeroed"}
        return KVPairs(kvs.keys, np.zeros(len(kvs.vals), np.float32),
                       kvs.lens)

    def _decompress_push(self, msg: Message, kvs: KVPairs) -> KVPairs:
        """Decode a compressed gradient push to dense before aggregation
        (ref: BSCDecompress gradient_compression.cc:310-336; fp16/2bit
        decode in the server push handlers).  Multi-key payloads fan
        the per-key decodes across the shared codec pool; this server's
        own ``DecoderBank`` keeps per-endpoint decoder affinity (its
        LRU is internally locked), so epoch-fenced clears stay scoped
        to this endpoint."""
        from geomx_tpu.compression import decompress_payload

        thr = float(self.compression.get("threshold", 0.5))
        pairs = [(int(k), p) for k, p in kvs.slices()]
        lens = []
        for k, _ in pairs:
            with self._mu.stripe(k):
                # raw length — reading through __getitem__ would
                # materialize a device-resident weight just to size the
                # decode buffer
                lens.append(self.store.length(k)
                            if isinstance(self.store, WeightStore)
                            else len(self.store[k]))
        if self._codec_stage is not None:
            # device decode (ISSUE 20): structural gates run host-side
            # on the small compressed buffer (same CodecError fencing),
            # then jitted kernels land each gradient as a device array
            # the merge lanes seed with no re-staging.  Device dispatch
            # serializes anyway, so the host codec pool buys nothing.
            with self._tr.span("codec.decode"):
                vs = [self._codec_stage.decode(msg.compr, k, p, ln, thr)
                      for (k, p), ln in zip(pairs, lens)]
                vals = vs[0] if len(vs) == 1 else self._codec_stage.concat(vs)
            return KVPairs(np.array([k for k, _ in pairs], dtype=np.int64),
                           vals, np.array(lens, dtype=np.int64))
        pool = codec_pool(self.config) if len(pairs) > 1 else None
        with self._tr.span("codec.decode"):
            if pool is None:
                vs = [decompress_payload(msg.compr, k, p, ln, thr,
                                         bank=self._decoders)
                      for (k, p), ln in zip(pairs, lens)]
            else:
                futs = [pool.submit(decompress_payload, msg.compr, k, p,
                                    ln, thr, self._decoders)
                        for (k, p), ln in zip(pairs, lens)]
                vs = [f.result() for f in futs]
        return KVPairs(np.array([k for k, _ in pairs], dtype=np.int64),
                       vs[0] if len(vs) == 1 else np.concatenate(vs),
                       np.array(lens, dtype=np.int64))

    # ---- sync tier ----------------------------------------------------------
    def _push_sync(self, msg: Message, kvs: KVPairs):
        """Accumulate; ack each parked push once ALL of its keys have been
        through an optimizer update (the ACK is the "updated" signal the
        local server waits for before pulling, ref: :1312-1316).

        Keys complete independently (message-granular tracking), so pushes
        with asymmetric key batches cannot deadlock or double-apply."""
        if len(kvs.keys) == 0:
            self.server.response(msg)
            return
        state = self._recent.check(msg)
        if state == "pending":
            return  # replay of a push already in this round's accumulator
        if state == "done":
            # the original ACK was lost — repeat it, same body (an error
            # body must not degrade into a clean ACK on the replay).  A
            # piggybacked push_pull re-serves the values: a bare re-ack
            # would leave the puller waiting forever
            body = self._recent.done_body(msg)
            if body is None and msg.pull:
                self._respond_pull(msg)
            else:
                self.server.response(msg, body=body)
            return
        kvs = self._screen_push(msg, kvs)  # after dedup: retries of a
        #                                    poisoned push don't restrike
        # an inter-TS-merged push carries several parties' contributions
        # (ref: num_merge counting in the global ASK_PUSH path)
        num_merge = 1
        if isinstance(msg.body, dict):
            num_merge = int(msg.body.get("num_merge", 1))
        hfa_delta = msg.cmd == Cmd.HFA_DELTA
        dissem_ok = msg.cmd == Cmd.DEFAULT
        slices = [(int(k), v) for k, v in kvs.slices()]
        entry = [msg, {k for k, _ in slices}]
        # key-sharded merge: each key accumulates — and, the moment its
        # round completes, runs its optimizer update — on its stripe's
        # serial lane.  The message-level finish (ack flush, checkpoint
        # / replication marking, overlay dissemination) runs once, on
        # the lane that clears the last slice.
        pending = [len(slices)]
        acks: List[tuple] = []
        reparks: List[Message] = []
        completed_keys: List[int] = []
        done_mu = threading.Lock()

        # BSP same-sender fence: a party's round-N+1 push can arrive
        # while round N is still open (WAN pushes pipeline ahead of the
        # pull-down, and the first device-codec encode JIT-compiles, so
        # one party's two rounds can outrun another party's first).
        # Counting it would close round N from ONE party's two pushes —
        # the global weights still see every gradient, but that party's
        # pull-down serves a close its peers never reached, rolling its
        # replica a round behind.  Defer it to the next round instead.
        # Pre-merged pushes (num_merge > 1) carry several parties under
        # one sender and HFA deltas are milestone-additive — neither is
        # sender-gated.
        sender_s = str(msg.sender)
        gate = num_merge == 1 and not hfa_delta

        def merge_one(k: int, v: np.ndarray):
            k_acks: List[tuple] = []
            k_reparks: List[Message] = []
            completed = False
            opened = False
            with self._mu.stripe(k):
                st = self._keys.setdefault(k, _GlobalKeyState())
                if (gate and st.accum is not None
                        and sender_s in st.contributors):
                    st.deferred.append((sender_s, v, entry, msg.donated))
                else:
                    if st.accum is None:
                        st.accum = self._backend.seed(v, msg.donated,
                                                      key=k)
                        opened = True
                    else:
                        st.accum = self._backend.accumulate(st.accum, v)
                    st.count += num_merge
                    st.parked_pushes.append(entry)
                    if gate:
                        st.contributors.add(sender_s)
                    if st.count >= self.num_contributors:
                        completed = True
                        self._complete_key_locked(k, hfa_delta, k_acks,
                                                  k_reparks)
            if opened and self._flight is not None:
                # a fresh aggregation round opened for this key — the
                # stall forensic's "who was the round waiting on"
                self._flight.record(FlightEv.ROUND_OPEN, a=k,
                                    peer=msg.sender, note="global")
            with done_mu:
                acks.extend(k_acks)
                reparks.extend(k_reparks)
                if completed:
                    completed_keys.append(k)
                pending[0] -= 1
                last = pending[0] == 0
            if last:
                self._merge_finish(acks, reparks, completed_keys,
                                   dissem_ok)

        for k, v in slices:
            self._shards.submit(k, _ctx_bound(lambda k=k, v=v: merge_one(k, v)))

    def _complete_key_locked(self, k: int, hfa_delta: bool,
                             to_ack: List[tuple],
                             reparks: List[Message]) -> None:
        """One completed key's update (caller holds stripe(k) or the
        all-stripes barrier): optimizer (or additive HFA delta), parked
        push ack collection, parked pull serving.  Appends (request,
        error) pairs whose key sets emptied to ``to_ack`` and pulls
        still blocked on OTHER keys to ``reparks`` — the caller
        re-parks those via :meth:`_park_pull` OUTSIDE this stripe (a
        re-park takes the blocking key's stripe; taking it here would
        break the one-stripe-at-a-time lock order)."""
        st = self._keys[k]
        if k not in self.store:
            # a restarted server without a checkpoint cannot host
            # this key — fail the pushers loudly, don't hang them
            err = {"error": f"key {k} lost across server restart "
                            "(no checkpoint to resume from)"}
            st.accum = None
            st.count = 0
            st.contributors.clear()
            with self._ack_mu:
                for ent in st.parked_pushes:
                    ent[1].discard(k)
                    if not ent[1]:
                        to_ack.append((ent[0], err))
                # fence-deferred pushes never reached parked_pushes —
                # fail them the same way, don't hang their senders
                for _, _, ent, _ in st.deferred:
                    ent[1].discard(k)
                    if not ent[1]:
                        to_ack.append((ent[0], err))
            st.parked_pushes.clear()
            st.deferred.clear()
            return
        with self._tr.span("global.opt"):
            dev = self._dev_opt
            if dev is not None:
                # device-resident round close: the accumulator never
                # leaves the device — one jitted donated update over it
                # (grad+state donated; weights functionally replaced).
                # ZERO D2H here; the store entry becomes a DeviceWeight
                # that host consumers materialize on demand
                raw = self.store.raw(k)
                if hfa_delta:
                    new_w = dev.add_delta(raw, st.accum)
                else:
                    new_w = dev.step(
                        k, raw, st.accum, 1.0 / self.num_contributors)
            else:
                # the weighted mean at round close consumes a HOST
                # array (identity on numpy; device sync + one D2H
                # under jax without the device optimizer stage)
                accum = self._backend.materialize(st.accum)
                if hfa_delta:
                    # milestone deltas come pre-divided by
                    # num_global_workers; apply additively (ref:
                    # HandleHFAAccumulate :959-972)
                    new_w = self.store[k] + accum
                else:
                    # accum is donated: update_scaled may build the new
                    # weights in it, skipping the /num temporary and the
                    # result allocation (big-tensor hot path)
                    new_w = self.optimizer.update_scaled(
                        k, self.store[k], accum,
                        1.0 / self.num_contributors)
            with self._wv_mu:
                self.store[k] = new_w
                st.ver += 1
        st.accum = None
        st.count = 0
        st.contributors.clear()
        with self._ack_mu:
            for ent in st.parked_pushes:
                ent[1].discard(k)
                if not ent[1]:
                    to_ack.append((ent[0], None))
        st.parked_pushes.clear()
        reparks.extend(self._serve_parked_pulls_locked(k))
        if st.deferred:
            # replay pushes the same-sender fence parked for the round
            # that just opened.  An item whose sender is already in the
            # NEW round (two deferred rounds from one party) re-defers;
            # per-sender FIFO is preserved.  A cascade close recurses —
            # depth is bounded by the backlog / num_contributors
            backlog, st.deferred = st.deferred, []
            for item in backlog:
                d_sender, v, ent, donated = item
                if st.accum is not None and d_sender in st.contributors:
                    st.deferred.append(item)
                    continue
                if st.accum is None:
                    st.accum = self._backend.seed(v, donated, key=k)
                else:
                    st.accum = self._backend.accumulate(st.accum, v)
                st.count += 1
                st.parked_pushes.append(ent)
                st.contributors.add(d_sender)
                if st.count >= self.num_contributors:
                    # _merge_finish only counts the outer close
                    self.key_rounds += 1
                    self._complete_key_locked(k, False, to_ack, reparks)

    def _merge_finish(self, to_ack: List[tuple],
                      reparks: List[Message],
                      completed_keys: List[int], dissem_ok: bool):
        """Message-level finish of one sync push, with no stripes held:
        re-park multi-key pulls, mark checkpoint/replication progress
        and build the overlay dissemination under the all-stripes
        barrier (both snapshot cross-key state), then flush acks."""
        for m in reparks:
            self._park_pull(m)
        self.key_rounds += len(completed_keys)  # GIL-atomic int add
        if completed_keys and self._flight is not None:
            self._flight.record(FlightEv.ROUND_COMPLETE,
                                a=len(completed_keys), b=self.key_rounds,
                                note="global")
        dissem = None
        if completed_keys and (
                self._repl is not None or self.ts_inter is not None
                or (self.config.checkpoint_dir
                    and self.config.auto_ckpt_updates)):
            with self._mu:
                self._auto_ckpt_locked(len(completed_keys))
                if self._repl is not None:
                    self._repl.mark_locked(len(completed_keys))
                if self.ts_inter is not None and dissem_ok:
                    dissem = self._build_dissem_locked(sorted(
                        k for k in completed_keys if k in self.store))
        self._flush_completions(to_ack, dissem)

    def _complete_keys_locked(self, completed: List[int],
                              hfa_delta: bool, dissem_ok: bool):
        """Batch completion for the FOLD paths (party leave / crash
        fold / overwrite-INIT): caller holds the all-stripes barrier,
        so the per-key completions just re-enter their stripes and
        still-blocked pulls can re-park immediately.  Returns
        ``(to_ack, dissem)`` for :meth:`_flush_completions` outside the
        lock."""
        to_ack: List[tuple] = []
        reparks: List[Message] = []
        for k in completed:
            self._complete_key_locked(k, hfa_delta, to_ack, reparks)
        for m in reparks:
            self._park_pull(m)
        if completed:
            self.key_rounds += len(completed)
            if self._flight is not None:
                self._flight.record(FlightEv.ROUND_COMPLETE,
                                    a=len(completed), b=self.key_rounds,
                                    note="fold")
            self._auto_ckpt_locked(len(completed))
            if self._repl is not None:
                self._repl.mark_locked(len(completed))
        if self.ts_inter is not None and completed and dissem_ok:
            dissem = self._build_dissem_locked(sorted(
                k for k in completed if k in self.store))
        else:
            dissem = None
        return to_ack, dissem

    def _flush_completions(self, to_ack: List[tuple], dissem):
        for req, err in to_ack:
            if err is None:
                # a poisoned push completed its rounds with a zeroed
                # contribution; its ack is the typed reject, and the
                # piggyback pull (if any) gets the error, not values
                err = getattr(req, "_gx_poisoned", None)
            self._recent.mark_done(req, err)
            if err is None and req.pull:
                # P3 piggyback on the WAN tier: the push response carries
                # the updated values, eliminating the ack -> pull-request
                # chain per key (ref: server replies with values in the
                # push response, kvstore_dist_server.h:1149-1165,1255-1267)
                self._respond_pull(req)
            else:
                self.server.response(req, body=err)
        if dissem is not None:
            self.ts_inter.disseminate_async(*dissem, Cmd.TS_AUTOPULL)

    def _build_dissem_locked(self, ks: List[int]):
        """Assemble one overlay-relay payload for keys ``ks`` (caller
        holds self._mu).  Honors fp16 pull compression on the relay
        (bsc/mpq are rejected at config time — per-subscriber deltas
        don't fit a shared relay payload)."""
        if not ks:
            return None
        self._ts_iter += 1
        dt = (np.float16 if self.compression.get("type") == "fp16"
              else np.float32)
        return (
            np.array(ks, dtype=np.int64),
            np.concatenate([self.store[k].astype(dt) for k in ks]),
            np.array([len(self.store[k]) for k in ks], dtype=np.int64),
            f"{self.po.node}:{self._ts_iter}",
        )

    # ---- async tier (MixedSync, ref :1519-1698) -----------------------------
    def _push_async(self, msg: Message, kvs: KVPairs):
        state = self._recent.check(msg)
        if state == "pending":
            # the original is still being applied — drop silently (a bare
            # ack here would consume the puller's response slot and the
            # real values response would then be discarded as a duplicate)
            return
        if state == "done":
            # the ACK was lost — re-ack without re-applying the gradient
            # (with values again if the original was a piggybacked
            # push_pull)
            body = self._recent.done_body(msg)
            if body is None and msg.pull:
                self._respond_pull(msg)
            else:
                self.server.response(msg, body=body)
            return
        self._screen_push(msg, kvs)
        poisoned = getattr(msg, "_gx_poisoned", None)
        if poisoned is not None:
            # async tier: no round barrier to keep honest — reject
            # outright before any optimizer touch
            self._recent.mark_done(msg, poisoned)
            self.server.response(msg, body=poisoned)
            return
        dissem = None
        with self._mu:
            for k, v in kvs.slices():
                k = int(k)
                grad = v.astype(np.float32)  # copy: donated below
                if self._dev_opt is None and not isinstance(grad,
                                                            np.ndarray):
                    # device-decoded push meeting a HOST optimizer
                    # engine (DCASGD / opt stage off): one explicit D2H
                    grad = np.asarray(grad)
                if self._dev_opt is not None:
                    # async tier on the device stage: one H2D of the
                    # push, jitted update, weights stay device-resident
                    # (DCASGD never constructs a device optimizer — its
                    # per-sender backups are host bookkeeping)
                    new_w = self._dev_opt.step(
                        k, self.store.raw(k), grad, 1.0)
                elif isinstance(self.optimizer, DCASGD):
                    new_w = self.optimizer.update(
                        k, self.store[k], grad, sender=str(msg.sender))
                else:
                    new_w = self.optimizer.update_scaled(
                        k, self.store[k], grad, 1.0)
                with self._wv_mu:
                    self.store[k] = new_w
                    self._keys.setdefault(k, _GlobalKeyState()).ver += 1
            self.key_rounds += len(kvs.keys)
            if self._flight is not None:
                self._flight.record(FlightEv.ROUND_COMPLETE,
                                    a=len(kvs.keys), b=self.key_rounds,
                                    note="async")
            self._auto_ckpt_locked(len(kvs.keys))
            if self._repl is not None:
                self._repl.mark_locked(len(kvs.keys))
            if self.ts_inter is not None and msg.cmd == Cmd.DEFAULT:
                self._ts_async_dirty.update(int(k) for k in kvs.keys)
                self._ts_async_pushes += 1
                if (self._ts_async_pushes
                        >= self.config.inter_ts_async_every):
                    self._ts_async_pushes = 0
                    ks = sorted(self._ts_async_dirty)
                    self._ts_async_dirty.clear()
                    dissem = self._build_dissem_locked(ks)
        self._recent.mark_done(msg)
        if msg.pull:
            self._respond_pull(msg)  # piggybacked push_pull (P3)
        else:
            self.server.response(msg)
        if dissem is not None:
            self.ts_inter.disseminate_async(*dissem, Cmd.TS_AUTOPULL)

    def _push_catchup(self, msg: Message, kvs: KVPairs):
        """Merge a healed party's staleness-stamped catch-up delta
        (Cmd.CATCHUP) through the SAME optimizer path as a live async
        push — DC-ASGD's per-sender backup compensates the staleness
        exactly as it would for a slow party — WITHOUT advancing sync-
        round accounting or the timestamp overlay: the quarantined
        party was folded out of those rounds, and replaying it into
        them would stall survivors waiting on a contributor that
        already left.  Bypasses the adaptive policy-epoch fence by
        construction (``_reject_bad_push`` only fences Cmd.DEFAULT):
        the delta was encoded under the healing party's last-known
        policy, and a refusal here would discard the partition's entire
        surviving progress over a codec-parameter quibble."""
        state = self._recent.check(msg)
        if state == "pending":
            return
        if state == "done":
            self.server.response(msg, body=self._recent.done_body(msg))
            return
        self._screen_push(msg, kvs)
        if getattr(msg, "_gx_poisoned", None) is not None:
            # a NaN catch-up delta would poison every key it touches
            # through the optimizer; the healed party re-syncs dense
            # instead (same fallback as an invalidated delta)
            err = msg._gx_poisoned
            self._recent.mark_done(msg, err)
            self.server.response(msg, body=err)
            return
        meta = (msg.body or {}).get("catchup", {}) \
            if isinstance(msg.body, dict) else {}
        rounds = int(meta.get("rounds", 0))
        with self._mu:
            for k, v in kvs.slices():
                k = int(k)
                if k not in self.store:
                    continue  # key retired while the party was dark
                grad = v.astype(np.float32)
                if self._dev_opt is None and not isinstance(grad,
                                                            np.ndarray):
                    grad = np.asarray(grad)  # host optimizer engine
                if self._dev_opt is not None:
                    new_w = self._dev_opt.step(
                        k, self.store.raw(k), grad, 1.0)
                elif isinstance(self.optimizer, DCASGD):
                    new_w = self.optimizer.update(
                        k, self.store[k], grad, sender=str(msg.sender))
                else:
                    new_w = self.optimizer.update_scaled(
                        k, self.store[k], grad, 1.0)
                with self._wv_mu:
                    self.store[k] = new_w
                    self._keys.setdefault(k, _GlobalKeyState()).ver += 1
            self.catchup_merges += 1
            self._auto_ckpt_locked(len(kvs.keys))
            if self._repl is not None:
                self._repl.mark_locked(len(kvs.keys))
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.partition_catchup_merges").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.NETFAULT, a=len(kvs.keys),
                                b=rounds, peer=msg.sender,
                                note="netfault_catchup_merge")
        print(f"{self.po.node}: merged catch-up delta from "
              f"{msg.sender} ({len(kvs.keys)} keys, {rounds} degraded "
              f"rounds, {meta.get('age_s', 0)}s stale)", flush=True)
        self._recent.mark_done(msg)
        self.server.response(msg)

    # ---- pulls --------------------------------------------------------------
    def _pull(self, msg: Message, kvs: KVPairs):
        self._park_pull(msg)

    def _park_pull(self, m: Message) -> None:
        """Serve a pull, or park it under its first key that is MISSING
        NOW (one stripe at a time).  Re-parking under a missing key
        matters: leaving a pull under an already-present key would
        orphan it — later INITs only rescan their own key's list
        (advisor r1: zpull([a,b]) before INIT of both hung when a and b
        arrived in separate INITs)."""
        for k in m.keys:
            k = int(k)
            with self._mu.stripe(k):
                if k not in self.store:
                    self._keys.setdefault(
                        k, _GlobalKeyState()).parked_pulls.append(m)
                    return
        self._respond_pull(m)

    def _serve_parked_pulls_locked(self, key: int) -> List[Message]:
        """Serve ``key``'s parked pulls that became servable; returns
        the ones still blocked on OTHER keys.  Caller holds stripe(key)
        (or the barrier) and re-parks the returned pulls via
        :meth:`_park_pull` — re-parking takes the blocking key's
        stripe, which must not nest inside this one."""
        st = self._keys.get(key)
        if not st:
            return []
        pending, st.parked_pulls = st.parked_pulls, []
        blocked: List[Message] = []
        for m in pending:
            if all(int(k) in self.store for k in m.keys):
                self._respond_pull(m)
            else:
                blocked.append(m)
        return blocked

    def _respond_pull(self, req: Message):
        # HFA K2 pulls must come back dense: the subscriber's replica just
        # adopted its party mean, so sparse deltas against the tracked
        # view would desync it.  A warm-boot pull (body {"dense": True})
        # is dense for the same reason — the fresh replica has no view
        # for a delta (or an fp16 downgrade) to be safe against
        hfa_pull = req.cmd == Cmd.HFA_DELTA
        dense = hfa_pull or (isinstance(req.body, dict)
                             and bool(req.body.get("dense")))
        if not dense and (self.pull_comp is not None
                          or self.compression.get("type") == "fp16"):
            self._respond_pull_compressed(req)
            return
        ks, vs, ls, wvs = [], [], [], {}
        for k in req.keys:
            k = int(k)
            w, wvs[str(k)] = self._weight_wv(k)
            ks.append(k); vs.append(w); ls.append(len(w))
        self.server.response(req, KVPairs(
            np.array(ks, dtype=np.int64), _store_payload(vs),
            np.array(ls, dtype=np.int64)),
            body={"wv": wvs})

    def _weight_wv(self, k: int):
        """Coherent ``(weights, weight-version)`` snapshot for a
        pull-down response.  Writers pair the store write with the ver
        bump under ``_wv_mu``, so taking it here rules out stamping new
        weights with an old version (or vice versa) — the subscriber's
        roll-back guard (:meth:`LocalServer._on_pull_down`) relies on
        the stamp never under-reporting.  The term rides the high bits:
        a promoted standby restarts per-key counters at 0 but its
        bumped term keeps the stamps monotonic across the failover."""
        with self._wv_mu:
            st = self._keys.get(k)
            return self.store[k], ((self.term << 48)
                                   + (st.ver if st is not None else 0))

    def _respond_pull_compressed(self, req: Message):
        """Pull-direction compression (the second half of Bi-Sparse,
        ref: BSCPullCompress/DefaultStorageResponse :1171-1211).

        One wire format for all compressed pulls: byte-packed payload with
        per-key tags in the response body.  "bsc" keys carry a top-k
        weight-delta against this subscriber's tracked view; "fp16" keys
        (small tensors under MPQ, or everything under plain fp16 —
        ref: README.md:22 fp16 halves both directions) carry half-precision
        weights.
        """
        typ = self.compression.get("type")
        size_bound = (int(self.compression.get("size_bound", 200_000))
                      if typ == "mpq" else 0)
        # _pc_mu: the compressor's per-subscriber tracked views, payload
        # cache and rng are shared across keys — a leaf lock (taken
        # under a stripe or the barrier, never the reverse) keeps them
        # coherent now that pull serving runs outside the big lock
        with self._tr.span("codec.encode"), self._pc_mu:
            self._respond_pull_compressed_inner(req, typ, size_bound)

    def _respond_pull_compressed_inner(self, req: Message, typ,
                                       size_bound: int):
        sender = str(req.sender)
        echo = {}
        if isinstance(req.body, dict):
            echo = req.body.get("pv", {}) or {}
        ks, chunks, ls, tags, pvs, wvs = [], [], [], {}, {}, {}
        for k in req.keys:
            k = int(k)
            w, wvs[str(k)] = self._weight_wv(k)
            if typ == "fp16" or (size_bound and len(w) < size_bound):
                payload = w.astype(np.float16)
                tags[str(k)] = "fp16"
            else:
                # version handshake: mismatched echo (either side
                # restarted, or a lost response) → dense "f32" resync
                # instead of a delta against a desynced view
                payload, tag, ver = self.pull_comp.compress(
                    sender, k, w, echo_ver=int(echo.get(str(k), 0)))
                tags[str(k)] = tag
                pvs[str(k)] = ver
            b = np.ascontiguousarray(payload).view(np.uint8)
            ks.append(k); chunks.append(b); ls.append(len(b))
        self.server.response(
            req,
            KVPairs(np.array(ks, dtype=np.int64), np.concatenate(chunks),
                    np.array(ls, dtype=np.int64)),
            body={"compr": tags, "pv": pvs, "wv": wvs},
        )

    def _on_set_wan_policy(self, msg: Message, body: dict):
        """Ctrl.SET_WAN_POLICY from the controller (receiver side):
        adopt the decode parameters + pull compressor IMMEDIATELY (the
        controller contacts receivers before senders).  The rebuilt
        compressor carries ``trust_init=False`` and its tracked views
        are gone, so every subscriber's next compressed pull resyncs
        dense through the existing version handshake — the coherent
        invalidation the epoch protocol relies on.  Old-epoch pushes
        already merged into an open round stay merged (they were decoded
        under their own epoch's parameters when they arrived); only
        NOT-yet-decoded cross-epoch payloads are fenced."""
        if not self._adaptive:
            self.server.reply_cmd(msg, body={
                "error": "adaptive WAN is disabled on this server "
                         "(Config.adaptive_wan / --adaptive-wan)"})
            return
        from geomx_tpu.compression import (compression_allowed,
                                           make_push_codec)

        comp = dict(body.get("compression") or {})
        ok, why = compression_allowed(
            comp.get("type", "none"),
            inter_ts=self.ts_inter is not None, hfa=self.config.use_hfa)
        if not ok:
            self.server.reply_cmd(msg, body={"error": why})
            return
        try:
            make_push_codec(comp)  # validate before adopting
        except ValueError as e:
            self.server.reply_cmd(msg, body={"error": str(e)})
            return
        applied = False
        with self._mu:
            epoch = int(body.get("epoch", 0))
            if epoch > self._policy_epoch:
                self._policy_epoch = epoch
                # trust_init=False: subscribers hold trained weights,
                # not INIT values — their first pull under the new
                # policy must resync dense, never sparse-from-INIT
                self._apply_compression_locked(comp, trust_init=False)
                # stateful decoders die with the epoch that created them
                self._decoders.clear()
                applied = True
            cur = self._policy_epoch
        if applied:
            from geomx_tpu.utils.metrics import system_gauge

            system_gauge(f"{self.po.node}.wan_policy_epoch").set(cur)
            self._tr.instant("wanpolicy.apply", epoch=cur,
                             codec=comp.get("type"))
            print(f"{self.po.node}: WAN policy epoch {cur} adopted -> "
                  f"{comp.get('type')}", flush=True)
        self.server.reply_cmd(msg, body={"epoch": cur})

    def _apply_compression_locked(self, body: dict, trust_init: bool = True):
        """Install a compression config (caller holds self._mu).

        ``trust_init=False`` (checkpoint restore) builds the pull
        compressor without the sparse-from-INIT fast path: subscribers
        still hold whatever they last pulled, not the restored weights,
        so every pair's first post-restore pull must resync dense."""
        from geomx_tpu.compression import BroadcastCompressor

        self.compression = body
        if body.get("type") in ("bsc", "mpq"):
            pc = BroadcastCompressor(ratio=body.get("ratio", 0.01),
                                     trust_init=trust_init)
            for k, v in self.store.items():
                pc.ensure_base(k, v)
            # publish only after bases are seeded, and under the
            # compressor's own leaf lock — compressed pull serving
            # synchronizes on _pc_mu, not the barrier
            with self._pc_mu:
                self.pull_comp = pc
        else:
            with self._pc_mu:
                self.pull_comp = None

    def _auto_ckpt_locked(self, n_updates: int = 0, force: bool = False):
        """Periodic background checkpoint (caller holds self._mu).

        Snapshots under the lock, serializes on a daemon thread — a
        multi-MB savez must not stall every party's round.  ``force``
        writes immediately (used right after INIT so a crash before the
        first interval still restores the key set)."""
        if not self.config.checkpoint_dir or not self.config.auto_ckpt_updates:
            return
        self._since_ckpt += n_updates
        if not force and self._since_ckpt < self.config.auto_ckpt_updates:
            return
        self._since_ckpt = 0
        if self._ckpt_busy:
            # a write is in flight with an older snapshot — re-snapshot
            # when it finishes (dropping this request could persist a
            # checkpoint that is missing keys INITed during the write)
            self._ckpt_pending = True
            return
        self._spawn_ckpt_write_locked()

    def _spawn_ckpt_write_locked(self):
        self._ckpt_busy = True
        import os

        from geomx_tpu.kvstore import checkpoint as ckpt

        store_snap = {k: v.copy() for k, v in self.store.items()}
        opt_snap = self._export_opt_locked()
        meta = {"sync_mode": self.sync_mode,
                "compression": dict(self.compression)}
        path = os.path.join(self.config.checkpoint_dir,
                            f"global_server_{self.po.node.rank}.npz")

        def write():
            try:
                # N-generation retention (Config.ckpt_generations): the
                # previous checkpoint shifts to path.1 (… path.N-1)
                # BEFORE the new write lands, so a generation that rots
                # on disk still leaves a verified older one for
                # load_checkpoint's fallback scan
                ckpt.rotate_generations(path, self.config.ckpt_generations)
                ckpt.save_server_state(path, store_snap,
                                       {"optimizer": opt_snap}, meta)
            except Exception:  # any failure must not wedge _ckpt_busy —
                # that would silently disable all future auto-checkpoints
                import logging

                logging.getLogger(__name__).exception(
                    "auto-checkpoint to %s failed", path)
            finally:
                with self._mu:
                    self._ckpt_busy = False
                    if self._ckpt_pending:
                        self._ckpt_pending = False
                        self._spawn_ckpt_write_locked()

        threading.Thread(target=write, daemon=True,
                         name=f"auto-ckpt-{self.po.node}").start()

    def _activate_dev_opt_locked(self):
        """(Re)derive the device optimizer stage from the current host
        ``self.optimizer`` (caller holds ``_mu``): when the merge
        backend offers one for this optimizer's spec, import any
        existing per-key trajectory onto the device and hand the state
        ownership over (the host shell keeps hyper-parameters and the
        type tag; single ownership keeps export unambiguous).  Standbys
        defer — every replication snapshot would otherwise re-stage the
        whole state H2D; promotion activates instead."""
        self._dev_opt = None
        if self.is_standby:
            return
        from geomx_tpu.optim import spec_of

        spec = spec_of(self.optimizer)
        if spec is None:
            return  # custom subclass / unsupported: host path
        dev = self._backend.make_device_optimizer(spec)
        if dev is None:
            return
        dev.import_state(self.optimizer)
        self.optimizer.state = {}
        self._dev_opt = dev

    def _export_opt_locked(self) -> ServerOptimizer:
        """THE optimizer-stage snapshot hook (caller holds ``_mu``):
        every path that serializes this server's optimizer — periodic
        checkpoint, Ctrl.CHECKPOINT save, the replication stream, a
        HANDOFF drain — goes through here, so a device-resident
        trajectory is materialized into the equivalent host optimizer
        (numpy pickle format unchanged on the wire/slab) and survives
        failover, reassignment and warm boot on either engine."""
        if self._dev_opt is not None:
            return self._dev_opt.export_state()
        import copy

        return copy.deepcopy(self.optimizer)

    def _drop_opt_key_locked(self, k: int):
        """Discard one key's optimizer trajectory (overwrite-INIT
        restore abort), whichever engine holds it."""
        self.optimizer.state.pop(k, None)
        if self._dev_opt is not None:
            self._dev_opt.drop_key(k)

    def _install_state_locked(self, store: dict, opt: dict, meta: dict):
        """Adopt a full state snapshot (checkpoint restore OR a
        replication snapshot from the primary).  Caller holds ``_mu``."""
        self.store = WeightStore(
            {k: np.array(v) for k, v in store.items()})
        for k in self.store:
            self._keys.setdefault(k, _GlobalKeyState())
        self.optimizer = opt["optimizer"]
        # a restored trajectory re-enters the device stage (no-op on
        # the host path / on a standby, which defers to promotion)
        self._activate_dev_opt_locked()
        # a restored optimizer IS a configured optimizer: central-
        # worker deployments gate training on this flag, and a
        # restarted shard reporting False would wedge them
        self._optimizer_configured = bool(
            meta.get("optimizer_configured", True))
        # resume under the snapshotted config, not whatever this
        # fresh process happened to default to
        self.sync_mode = meta.get("sync_mode", self.sync_mode)
        # trust_init=False: subscribers hold whatever they last
        # pulled, not these restored weights — their first pull after
        # the restore must resync dense (version-echo mismatch)
        self._apply_compression_locked(
            meta.get("compression", self.compression),
            trust_init=False)
        # the primary's replay-dedup done-window rides the snapshot: a
        # client replaying an un-ACKed request the primary already
        # applied AND replicated must be re-acked, never re-applied
        # (the exactly-once half of failover replay)
        rd = meta.get("recent_done")
        if rd:
            self._recent.seed_done(rd)

    def _merge_state_locked(self, store: dict, opt: dict, meta: dict):
        """Adopt a drained shard's key range NEXT TO this server's own
        (key-range reassignment onto a live primary).  Unlike
        :meth:`_install_state_locked` nothing of this server's own shard
        is touched: the shipped keys and their optimizer state are added,
        the drained holder's replay-dedup window is seeded ADDITIVELY
        (so a client replay of a request the old holder already applied
        is re-acked, not re-applied — the same exactly-once contract as
        failover), and pulls parked on the new keys are served.  Caller
        holds ``_mu``."""
        shipped_opt = opt.get("optimizer")
        for k, v in store.items():
            k = int(k)
            self.store[k] = np.array(v)
            st = self._keys.setdefault(k, _GlobalKeyState())
            # any aggregation state this server somehow held for a
            # foreign key is stale by definition
            st.accum = None
            st.count = 0
            if shipped_opt is not None and k in getattr(
                    shipped_opt, "state", {}):
                # per-key optimizer state (momentum/Adam moments) moves
                # with the range; this server's own keys keep theirs
                if self._dev_opt is not None:
                    self._dev_opt.import_key(k, shipped_opt.state[k])
                else:
                    self.optimizer.state[k] = shipped_opt.state[k]
            if self.pull_comp is not None:
                self.pull_comp.ensure_base(k, self.store[k])
            for m in self._serve_parked_pulls_locked(k):
                self._park_pull(m)
        if not self._optimizer_configured and shipped_opt is not None \
                and meta.get("optimizer_configured"):
            # an unconfigured target adopts the drained shard's
            # optimizer wholesale — MultiGPS must never mix a configured
            # shard with a default-SGD one
            self.optimizer = shipped_opt
            self._optimizer_configured = True
            self._activate_dev_opt_locked()
        rd = meta.get("recent_done")
        if rd:
            self._recent.seed_done(rd)
        if self._repl is not None:
            # the adopted range replicates with THIS holder's standby
            # chain from now on — ship a fresh snapshot that includes it
            self._repl.mark_locked(force=True)

    # ---- live key-range reassignment (shard drain) --------------------------
    def _on_handoff(self, msg: Message) -> bool:
        """Control.HANDOFF from the global scheduler: drain this
        holder's key range onto ``body["target"]`` under a bumped term.
        The ship blocks on a WAN round trip, so it runs off the hook
        thread; the scheduler retries until a reply lands (idempotent —
        an already-drained holder re-acks)."""
        if msg.control is not Control.HANDOFF or not msg.request:
            return False
        body = msg.body if isinstance(msg.body, dict) else {}
        term = int(body.get("term", 0))
        target = body.get("target")
        with self._mu:
            if self._draining or self._fenced:
                # replayed (or raced) handoff: the drain already
                # happened — re-ack with the recorded outcome
                self.po.van.send(msg.reply_to(
                    control=Control.HANDOFF,
                    body={"ok": term <= self.term and self.drains > 0,
                          "keys": len(self.store),
                          "token": body.get("token")}))
                return True
            if term <= self.term or target is None:
                self.po.van.send(msg.reply_to(
                    control=Control.HANDOFF,
                    body={"ok": False, "term": self.term,
                          "error": f"stale handoff term {term} <= "
                                   f"{self.term}",
                          "token": body.get("token")}))
                return True
        threading.Thread(
            target=self._drain_thread,
            args=(msg, term, NodeId.parse(str(target))),
            daemon=True, name=f"handoff-{self.po.node}").start()
        return True

    def _drain_thread(self, msg: Message, term: int, target: NodeId):
        from geomx_tpu.kvstore import checkpoint as ckpt
        from geomx_tpu.kvstore.replication import HANDOFF_CUSTOMER_ID

        ok = False
        nkeys = 0
        try:
            # stop the regular replication stream FIRST and wait out any
            # in-flight ship: a pre-quiesce snapshot landing at a standby
            # target AFTER the handoff install would roll it back to a
            # state missing the final rounds
            if self._repl is not None:
                self._repl.stopped = True
                deadline = time.monotonic() + 10
                while self._repl._busy and time.monotonic() < deadline:
                    time.sleep(0.05)
            # program order: merges queued from already-arrived pushes
            # land before the snapshot; requests arriving after the
            # _draining flip below are dropped (clients replay them at
            # the new holder post-retarget)
            self._shards.drain()
            with self._mu:
                self._draining = True
                store_snap = {k: v.copy() for k, v in self.store.items()}
                opt_snap = self._export_opt_locked()
                meta = {
                    "sync_mode": self.sync_mode,
                    "compression": dict(self.compression),
                    "recent_done": self._recent.export_done(),
                    "optimizer_configured": self._optimizer_configured,
                }
                nkeys = len(store_snap)
            blob = np.frombuffer(
                ckpt.dumps_server_state(store_snap, {"optimizer": opt_snap},
                                        meta), dtype=np.uint8)
            if self._handoff_kw is None:
                self._handoff_kw = KVWorker(
                    APP_PS, HANDOFF_CUSTOMER_ID, self.po,
                    targets=[target], key_ranges=split_range(1),
                    domain=Domain.GLOBAL)
            else:
                self._handoff_kw.targets[0] = target
            kw = self._handoff_kw
            kw.zpush(
                KVPairs(np.array([0], dtype=np.int64), blob,
                        np.array([len(blob)], dtype=np.int64)),
                cmd=Cmd.REPLICATE, wait=True, donated=True,
                body={"term": term, "seq": self._repl_seq + 1,
                      "handoff": True})
            with kw._mu:
                errs, kw.errors[:] = list(kw.errors), []
            ok = not errs
            if ok:
                self.drains += 1  # single drain thread per lifetime
                from geomx_tpu.utils.metrics import system_counter

                system_counter(f"{self.po.node}.drains").inc()
                self._tr.instant("reassign.drained", term=term,
                                 target=str(target), keys=nkeys)
                if self._flight is not None:
                    self._flight.record(FlightEv.HANDOFF, a=term,
                                        c=nkeys, peer=target,
                                        note="drained")
                self._fence(f"key range drained to {target}", term)
            else:
                # aborted ship: the range is still ours — resume serving
                # (replication stream included) rather than wedging the
                # shard half-drained
                with self._mu:
                    self._draining = False
                    if self._repl is not None:
                        self._repl.stopped = False
                import logging

                logging.getLogger(__name__).error(
                    "%s: handoff to %s failed (%s); resuming as holder",
                    self.po.node, target, "; ".join(errs))
        except Exception:
            with self._mu:
                self._draining = False
                if self._repl is not None:
                    self._repl.stopped = False
            import logging

            logging.getLogger(__name__).exception(
                "%s: handoff to %s failed; resuming as holder",
                self.po.node, target)
        try:
            self.po.van.send(msg.reply_to(
                control=Control.HANDOFF,
                body={"ok": ok, "keys": nkeys,
                      "token": (msg.body or {}).get("token")}))
        except (KeyError, OSError):
            pass  # the scheduler re-asks; the idempotent re-ack answers

    # ---- hot-standby replication + promotion (kvstore/replication.py) ------
    def _on_replicate(self, msg: Message, kvs: Optional[KVPairs]):
        """Apply one streamed state snapshot from the shard's primary —
        the checkpoint slab format over the wire.  Term-fenced: once a
        newer primary holds the shard, a zombie's stale stream is
        rejected (counted) so it can never roll the store back."""
        state = self._recent.check(msg)
        if state == "pending":
            return
        if state == "done":
            self.server.response(msg, body=self._recent.done_body(msg))
            return
        body = msg.body if isinstance(msg.body, dict) else {}
        term, seq = int(body.get("term", 0)), int(body.get("seq", 0))
        handoff = bool(body.get("handoff"))
        err = None
        with self._mu:
            if term < self.term:
                self.fenced_rejects += 1
                from geomx_tpu.utils.metrics import system_counter

                system_counter(
                    f"{self.po.node}.replication_fenced_rejects").inc()
                if self._flight is not None:
                    self._flight.record(FlightEv.FENCE, a=term, b=self.term,
                                        peer=msg.sender,
                                        note="stale_repl_term")
                err = {"error": f"fenced: stale replication term {term} < "
                                f"{self.term}", "term": self.term}
            elif handoff and kvs is not None:
                # key-range reassignment: the draining holder's final
                # snapshot.  A live primary MERGES the shipped range
                # next to its own (it keeps serving its own shard
                # mid-adopt); a standby target full-installs — both
                # idempotent, so the scheduler's handoff retries are
                # safe.  Ordering vs. our own primary's replication
                # stream is by term: the drain bumped the shipped
                # range's term past anything the old stream carries.
                from geomx_tpu.kvstore import checkpoint as ckpt

                try:
                    store, opt, meta = ckpt.loads_server_state(
                        np.ascontiguousarray(kvs.vals).tobytes())
                except ckpt.CheckpointCorruption as e:
                    err = self._reject_corrupt_snapshot_locked(e, msg)
                else:
                    if self.is_standby:
                        self._install_state_locked(store, opt, meta)
                    else:
                        self._merge_state_locked(store, opt, meta)
                    self.merged_handoffs += 1
                    self._repl_seq = max(self._repl_seq, seq)
            elif seq > self._repl_seq and kvs is not None:
                from geomx_tpu.kvstore import checkpoint as ckpt
                from geomx_tpu.utils.metrics import system_gauge

                try:
                    store, opt, meta = ckpt.loads_server_state(
                        np.ascontiguousarray(kvs.vals).tobytes())
                except ckpt.CheckpointCorruption as e:
                    # the standby KEEPS its previous verified generation
                    # — a rotted stream frame must never replace good
                    # replica state; the primary's next mark re-ships
                    err = self._reject_corrupt_snapshot_locked(e, msg)
                else:
                    self._install_state_locked(store, opt, meta)
                    self._repl_seq = seq
                    system_gauge(
                        f"{self.po.node}.replication_seq").set(seq)
            # else: an out-of-order older snapshot — ack without applying
        self._recent.mark_done(msg, err)
        self.server.response(msg, body=err)

    def _reject_corrupt_snapshot_locked(self, e: Exception,
                                        msg: Message) -> dict:
        """A replication/handoff snapshot failed checkpoint verification
        (caller holds ``_mu``): count it, keep the state we already
        have, and answer with a typed error.  The body deliberately
        avoids the word "fenced" — the primary's Replicator reads
        fence-flavored replies as a deposition signal, and one rotted
        frame must not depose a healthy primary."""
        self.integrity_ckpt_rejects += 1
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.integrity_ckpt_rejects").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.CORRUPT, peer=msg.sender,
                                note="corrupt_snapshot")
        print(f"{self.po.node}: rejected corrupt replication snapshot "
              f"from {msg.sender} ({e}) — keeping previous generation",
              flush=True)
        return {"error": "corrupt replication snapshot rejected "
                         f"({e}); receiver keeps its previous state"}

    def _on_promote(self, msg: Message) -> bool:
        """Control.PROMOTE from the global scheduler: become the shard's
        primary under the given term.  Idempotent per term (the
        scheduler retries until acknowledged)."""
        if msg.control is not Control.PROMOTE or not msg.request:
            return False
        body = msg.body if isinstance(msg.body, dict) else {}
        term = int(body.get("term", 0))
        self._tr.instant("failover.promote", term=term)
        parked: List[tuple] = []
        with self._mu:
            if term > self.term:
                self.term = term
                self.is_standby = False
                self._fenced = False  # a promote supersedes any fence
                self.promotions += 1
                # the replicated trajectory enters the device stage NOW
                # (deferred while standby): the promoted holder resumes
                # the momentum/moments the primary was training with
                self._activate_dev_opt_locked()
                parked, self._parked_standby = self._parked_standby, []
                for k in list(self.store):
                    for m in self._serve_parked_pulls_locked(k):
                        self._park_pull(m)
                from geomx_tpu.utils.metrics import system_counter

                system_counter(f"{self.po.node}.promotions").inc()
                if self._flight is not None:
                    self._flight.record(FlightEv.PROMOTE, a=term,
                                        c=len(self.store),
                                        peer=self.po.node,
                                        note="promoted")
                print(f"{self.po.node}: promoted to primary "
                      f"(term={term}, keys={len(self.store)}, "
                      f"repl_seq={self._repl_seq})", flush=True)
        self.po.van.send(msg.reply_to(control=Control.PROMOTE, body={
            "ok": not self.is_standby, "term": self.term,
            "keys": len(self.store), "token": body.get("token")}))
        # re-dispatch traffic that raced ahead of the promotion
        for m, kv in parked:
            self._handle_inner(m, kv, self.server)
        return True

    def _on_new_primary(self, msg: Message) -> bool:
        """Control.NEW_PRIMARY broadcast: fence myself if I am the
        deposed ex-primary; adopt the promotion if I am the named new
        primary and the direct PROMOTE was lost."""
        if msg.control is not Control.NEW_PRIMARY or msg.request:
            return False
        b = msg.body if isinstance(msg.body, dict) else {}
        term = int(b.get("term", 0))
        if b.get("old") == str(self.po.node) and term > self.term:
            self._fence(f"deposed by {b.get('new')}", term)
        elif b.get("new") == str(self.po.node) and term > self.term:
            fake = Message(sender=msg.sender, recipient=self.po.node,
                           control=Control.PROMOTE, domain=Domain.GLOBAL,
                           request=True, body={"term": term})
            self._on_promote(fake)
        return True

    def _fence(self, reason: str, term: Optional[int] = None):
        """Flip into the deposed state: stop replicating, refuse data
        requests (split-brain guard for a zombie ex-primary)."""
        with self._mu:
            if term is not None:
                self.term = max(self.term, term)
            if self._fenced:
                return
            self._fenced = True
            self._fence_reason = reason
            if self._repl is not None:
                self._repl.stopped = True
        self._tr.instant("failover.fenced", term=self.term, reason=reason)
        from geomx_tpu.utils.metrics import system_counter

        system_counter(f"{self.po.node}.fenced").inc()
        if self._flight is not None:
            self._flight.record(FlightEv.FENCE, a=self.term,
                                peer=self.po.node, note="deposed")
        print(f"{self.po.node}: fenced — {reason} (term={self.term})",
              flush=True)

    def load_checkpoint(self, path: str):
        """Restore weights + optimizer + config from a checkpoint file and
        drain any pulls that parked while the state was missing.  Used by
        the Ctrl.CHECKPOINT command and launcher crash-recovery
        (GEOMX_CHECKPOINT_DIR)."""
        from geomx_tpu.kvstore import checkpoint as ckpt

        store = opt = meta = None
        last_err: Optional[Exception] = None
        for i, cand in enumerate(ckpt.restore_candidates(path) or [path]):
            try:
                store, opt, meta = ckpt.load_server_state(cand)
                break
            except (ckpt.CheckpointCorruption, OSError) as e:
                # newest generation rotted (or vanished): fall back to
                # the next one that verifies instead of dying on it
                last_err = e
                self.integrity_ckpt_rejects += 1
                from geomx_tpu.utils.metrics import system_counter

                system_counter(
                    f"{self.po.node}.integrity_ckpt_rejects").inc()
                if self._flight is not None:
                    self._flight.record(FlightEv.CORRUPT, a=i,
                                        note="ckpt_fallback")
                print(f"{self.po.node}: checkpoint {cand} failed "
                      f"verification ({e}); trying previous generation",
                      flush=True)
        if store is None:
            raise last_err  # no generation verified — caller surfaces it
        self._shards.drain()  # pre-restore merges must not land on the
        #                       restored state
        with self._mu:
            self._install_state_locked(store, opt, meta)
            for k in list(self.store):
                for m in self._serve_parked_pulls_locked(k):
                    self._park_pull(m)

    # ---- control ------------------------------------------------------------
    def _on_cmd(self, msg: Message):
        body = msg.body or {}
        if msg.cmd in (Ctrl.SET_OPTIMIZER, Ctrl.SET_COMPRESSION,
                       Ctrl.SET_SYNC_GLOBAL_MODE, Ctrl.CHECKPOINT):
            # program order vs. the merge lanes: an optimizer/codec/mode
            # swap (or a checkpoint snapshot) must not interleave with
            # merges queued from earlier-arrived pushes
            self._shards.drain()
        if msg.cmd == Ctrl.SET_OPTIMIZER:
            # ref: master worker pickles the optimizer, executes on the
            # global server (kvstore.py:452-499, kvstore_dist_server.h:357-364)
            with self._mu:
                self.optimizer = make_optimizer(body)
                self._optimizer_configured = True
                self._activate_dev_opt_locked()
        elif msg.cmd == Ctrl.SET_COMPRESSION:
            from geomx_tpu.compression import (compression_allowed,
                                               make_push_codec)

            try:
                make_push_codec(body)  # validate
            except ValueError as e:
                self.server.reply_cmd(msg, body={"error": str(e)})
                return
            # hfa=False for the same reason as the local-server gate:
            # static HFA+bsc is the dense-bypass case
            ok, why = compression_allowed(
                body.get("type", "none"),
                inter_ts=self.ts_inter is not None)
            if not ok:
                self.server.reply_cmd(msg, body={"error": why})
                return
            with self._mu:
                if body == self.compression:
                    # idempotent: every party's rank-0 sends this; a
                    # recreation mid-training would wipe other parties'
                    # tracked subscriber views
                    self.server.reply_cmd(msg)
                    return
                self._apply_compression_locked(body)
        elif msg.cmd == Ctrl.SET_WAN_POLICY:
            self._on_set_wan_policy(msg, body)
            return
        elif msg.cmd == Ctrl.SET_SYNC_GLOBAL_MODE:
            if self.ts_inter is not None and bool(body["sync"]) != self.sync_mode:
                # local servers key their round-completion path off the
                # STATIC config; a runtime flip only we can see would
                # desync the tiers (sync→async would deadlock every
                # party's round on a dissemination that never fires)
                self.server.reply_cmd(msg, body={
                    "error": "cannot switch the global sync mode at "
                             "runtime under inter-TS — set "
                             "sync_global_mode in the static config so "
                             "all roles agree"})
                return
            self.sync_mode = bool(body["sync"])
        elif msg.cmd == Ctrl.QUERY_STATS:
            self.server.reply_cmd(msg, body=self.stats())
            return
        elif msg.cmd == Ctrl.LIST_KEYS:
            # a replacement local server's warm boot — and every serve
            # replica's refresh (geomx_tpu/serve) — asks for the hosted
            # key set before pulling; ``key_rounds`` rides along so
            # replicas can stamp their copy with the round progress it
            # reflects (the version-lag observable)
            with self._mu:
                ks = sorted(int(k) for k in self.store)
                kr = self.key_rounds
            self.server.reply_cmd(msg, body={"keys": ks, "key_rounds": kr})
            return
        elif msg.cmd == Ctrl.PROFILER:
            _handle_profiler_cmd(self.po, msg, self.server)
            return
        elif msg.cmd == Ctrl.CHECKPOINT:
            from geomx_tpu.kvstore import checkpoint as ckpt

            try:
                if body["action"] == "save":
                    # snapshot under the lock, serialize/write outside it —
                    # a multi-GB savez must not stall every party's round
                    with self._mu:
                        store_snap = {k: v.copy() for k, v in self.store.items()}
                        opt_snap = self._export_opt_locked()
                        meta = {"sync_mode": self.sync_mode,
                                "compression": dict(self.compression)}
                    ckpt.rotate_generations(body["path"],
                                            self.config.ckpt_generations)
                    ckpt.save_server_state(
                        body["path"], store_snap,
                        {"optimizer": opt_snap}, meta)
                elif body["action"] == "load":
                    self.load_checkpoint(body["path"])
                self.server.reply_cmd(msg, body={"ok": True})
            except Exception as e:  # surface failures to the caller
                self.server.reply_cmd(msg, body={"error": repr(e)})
            return
        self.server.reply_cmd(msg)

    def stats(self) -> dict:
        """The QUERY_STATS body — also sampled on an interval by the
        telemetry plane's MetricsPump (geomx_tpu/obs)."""
        van = self.po.van
        with self._mu:
            store_b = sum(a.nbytes for a in self.store.values())
            accum_b = sum(st.accum.nbytes for st in self._keys.values()
                          if st.accum is not None)
        with self._pc_mu:
            pv_subs = (len(self.pull_comp.subscribers())
                       if self.pull_comp is not None else 0)
        return {
            "wan_send_bytes": van.wan_send_bytes,
            "wan_recv_bytes": van.wan_recv_bytes,
            "store_bytes": store_b,
            "accum_bytes": accum_b,
            # lets a central-worker deployment confirm configuration
            # landed before training starts (the reference sequences
            # this through the master worker finishing first)
            "optimizer": type(self.optimizer).__name__.lower(),
            "optimizer_configured": self._optimizer_configured,
            # device-resident optimizer stage: which DeviceOptimizer
            # closes rounds ("" = host optimizer), and how many keys'
            # trajectories live on device right now
            **(self._dev_opt.stats() if self._dev_opt is not None
               else {"opt_device": ""}),
            # forced dense resyncs of the BSC pull compressor: a
            # nonzero steady-state rate means the pull direction is
            # degrading to uncompressed (e.g. sustained overlapping
            # rounds of one key) — observability for finding that
            "pull_resyncs": (self.pull_comp.resyncs
                             if self.pull_comp is not None else 0),
            # tracked-view hygiene: distinct subscribers currently
            # pinning a pull-compressor view, and prune events (leaves /
            # folds / replica evictions) — a count that only grows as
            # subscribers churn means the leak is back
            "pull_view_subscribers": pv_subs,
            "subscriber_prunes": self.subscriber_prunes,
            # failover observability: term fencing + replication
            "term": self.term,
            "is_standby": self.is_standby,
            "promotions": self.promotions,
            "fenced_rejects": self.fenced_rejects,
            "replication_seq": self._repl_seq,
            "replication_acked_seq": (self._repl.acked_seq
                                      if self._repl is not None else 0),
            # crash-tolerant membership: reversible party folds
            "party_folds": self.party_folds,
            "party_unfolds": self.party_unfolds,
            "num_global_workers": self.num_contributors,
            # partition heals merged through the optimizer (Cmd.CATCHUP)
            "catchup_merges": self.catchup_merges,
            # data-integrity observability: gradient hygiene + verified
            # durable state (docs/deployment.md "Data integrity")
            "integrity_poison_rejects": self.integrity_poison_rejects,
            "integrity_ckpt_rejects": self.integrity_ckpt_rejects,
            "integrity_codec_rejects": self.integrity_codec_rejects,
            # adaptive WAN: receiver-side epoch + fence observables
            "policy_epoch": self._policy_epoch,
            "policy_fenced_pushes": self.policy_fenced_pushes,
            "rejected_compr_tags": self.rejected_compr_tags,
            # key-range reassignment (shard drain) observables
            "drains": self.drains,
            "merged_handoffs": self.merged_handoffs,
            "draining": self._draining,
            # round progress: completed (key, round) pairs — the health
            # engine's per-shard round-stall input
            "key_rounds": self.key_rounds,
            # restart discrimination (see LocalServer.stats)
            "uptime_s": self.po.uptime_s(),
            "boot": van.boot,
            # merge backend observability (see LocalServer._merge_stats)
            **self._merge_stats(),
        }

    def _merge_stats(self) -> dict:
        out = self._backend.stats()
        ms, h2d = out.get("merge_device_ms"), out.get("h2d_bytes")
        if ms is not None:
            from geomx_tpu.utils.metrics import system_gauge

            system_gauge(f"{self.po.node}.merge_device_ms").set(ms)
            system_gauge(f"{self.po.node}.h2d_bytes").set(h2d or 0)
            # device->host traffic + optimizer-stage time: the
            # steady-state zero-D2H contract is audited on these
            system_gauge(f"{self.po.node}.d2h_bytes").set(
                out.get("d2h_bytes") or 0)
            system_gauge(f"{self.po.node}.opt_device_ms").set(
                out.get("opt_device_ms") or 0)
            # codec stage (ISSUE 20): decode kernel time + wire-ready
            # compressed D2H — host_copy auditing rides the same stats
            system_gauge(f"{self.po.node}.codec_device_ms").set(
                out.get("codec_device_ms") or 0)
            system_gauge(f"{self.po.node}.codec_d2h_bytes").set(
                out.get("codec_d2h_bytes") or 0)
        return out

    def stop(self):
        if self._repl is not None:
            self._repl.stop()
        if self._handoff_kw is not None:
            self._handoff_kw.stop()
        if self.ts_inter is not None:
            self.ts_inter.stop()
        self._shards.stop()
        self._backend.stop()
        self.server.stop()
