#!/usr/bin/env bash
# Acceptance config: dgt (mirrors the reference scripts/cpu/run_dgt.sh)
exec "$(dirname "$0")/run_cluster.sh" --dgt 1
