"""Black-box flight recorder: always-on per-node event ring.

Geo-distributed failures are rare, cross-node, and unreproducible: by
the time an operator looks at a round-stall alert, the evidence is
gone.  PR 3's tracing only sees sampled rounds (``trace_sample_every``,
default off) and the PR 7 health engine says *that* something fired,
not *why*.  The flight recorder closes that gap the way production
systems do (cf. TensorFlow's always-on event logs, PAPERS.md): every
node keeps a **fixed-size ring of structured events** — preallocated
column arrays, no per-event allocation on the hot path — recording the
decision points the subsystems already log ad hoc:

- message send/recv heads (cmd/control, policy epoch, boot, bytes,
  peer) tapped in the Van;
- fence and dedup decisions (eviction fences, policy-epoch fences,
  stale-term replication rejects, van duplicate suppression);
- barrier enter/release/timeout (both the waiter and the scheduler);
- promotion / eviction / fold / handoff / warm-boot transitions;
- round open/complete per server (the stall forensic);
- periodically sampled **pressure** readings (StripedRLock wait,
  merge-lane queue depth, van send-queue depth, codec-pool backlog),
  mirrored into the system-metrics registry so the PR 7 pump ships
  them as gauges (``lock_wait_s`` / ``lane_depth`` /
  ``van_sendq_depth`` / ``codec_pool_busy``).

Rings dump to ``GEOMX_OBS_DIR`` (JSON, one file per node per incident)
on three triggers: process exit/signal (``install_process_hooks``), a
HealthEngine alert transition (the engine broadcasts
``Control.FLIGHT_DUMP`` so every node snapshots the same incident
window, and the alert record carries the dump paths), and operator
request (``python -m geomx_tpu.status --dump-flight`` →
``Ctrl.FLIGHT_DUMP`` at the scheduler → the same broadcast).  The
offline assembler (``python -m geomx_tpu.obs.postmortem <dir>``)
merges per-node dumps on the heartbeat clock-offset estimates into one
causal timeline and answers "why did round X stall".

Disabled path (``GEOMX_FLIGHT=0`` / ``Config.enable_flight=False``):
no recorder is constructed anywhere — every tap is one attribute-load
+ None check.
"""

from __future__ import annotations

import enum
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from geomx_tpu.utils.metrics import system_counter, system_gauge

# the pressure gauges every sampled reading mirrors into the registry
# (documented in docs/metrics.md; the status console's pressure column
# and the PR 7 pump read them back).  process_threads is registered on
# every node; the reactor_* pair only when the node's fabric rides the
# shared reactor (GEOMX_TRANSPORT=reactor / lightweight sims)
PRESSURE_GAUGES = ("lock_wait_s", "lane_depth", "van_sendq_depth",
                   "codec_pool_busy", "process_threads",
                   "reactor_loop_lag_ms", "reactor_fds")


class FlightEv(enum.IntEnum):
    """Structured event codes.  The int value is what sits in the ring;
    dumps carry the name."""

    SEND = 1             # a=cmd (>=0) or -control, b=policy_epoch,
    #                      c=nbytes, d=boot, peer=recipient
    RECV = 2             # mirror of SEND, peer=sender
    DEDUP = 3            # duplicate suppressed (van resender window)
    FENCE = 4            # a/b context ints, peer=the fenced party,
    #                      note=which fence (evicted_push/policy_epoch/
    #                      stale_repl_term/deposed/...)
    BARRIER_ENTER = 5    # a=group value; scheduler side: peer=entrant
    BARRIER_RELEASE = 6  # c=waiters released (scheduler side)
    BARRIER_TIMEOUT = 7
    PROMOTE = 8          # a=term, peer=the promoted node
    EVICT = 9            # peer=the evicted member
    FOLD = 10            # peer=the folded member/party server
    UNFOLD = 11
    HANDOFF = 12         # a=term, peer=the handoff target
    ROUND_OPEN = 13      # a=key (global) / wan round counter (local)
    ROUND_COMPLETE = 14  # a=keys completed, b=total key/wan rounds
    PRESSURE = 15        # a=value*1e6 (scaled int), note=gauge name
    WARM_BOOT = 16       # a=keys pulled
    DUMP = 17            # a ring dump was taken (note=incident)
    ALERT = 18           # health transition observed locally
    MERGE_BACKEND = 19   # server merge engine chosen at boot: a=lane
    #                      count, note=backend name (numpy/jax) — the
    #                      postmortem can tell a device-lane server
    #                      from a host-lane one without its config
    CHURN = 20           # churn-orchestrator injected event (chaos/
    #                      churn.py): peer=the targeted node,
    #                      note=churn_{notice,kill,join,server_kill,
    #                      server_restart,stall_round} — postmortems
    #                      attribute stalls to INJECTED vs organic
    #                      faults by joining these with the fold/evict
    #                      timeline
    NETFAULT = 21        # partition-tolerance transition (chaos/netfault
    #                      injection + kvstore quarantine machinery):
    #                      note=netfault_{cut,heal,quarantine,
    #                      unquarantine,degraded,catchup_merge,
    #                      catchup_fallback}, peer=the affected node/
    #                      party server; a=context int (keys merged,
    #                      party id, ...), b=rounds accumulated —
    #                      postmortems can separate INJECTED cuts from
    #                      organic silence and audit every quarantine
    #                      state-machine edge without logs
    CORRUPT = 22         # data-integrity plane verdict: note=
    #                      wire_nack_resend (sender retransmitting after
    #                      a receiver checksum NACK), poison_push (a
    #                      NaN/Inf/oversized push zeroed out of a merge),
    #                      poison_quarantine (sender crossed the strike
    #                      budget), corrupt_snapshot (standby rejected a
    #                      REPLICATE slab), ckpt_fallback (restore
    #                      skipped an unverifiable generation); peer=the
    #                      offending sender/file, a=strike count or
    #                      generation — the health engine's
    #                      data_corruption rule reads the same counters,
    #                      the flight tape gives the per-event trail


_EV_NAMES = {int(e): e.name for e in FlightEv}


def _sanitize(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-." else "_" for c in s)


def dump_path(out_dir: str, node: str, incident: Optional[str]) -> str:
    return os.path.join(
        out_dir, f"flight_{_sanitize(node)}_{_sanitize(incident or 'exit')}"
        ".json")


class FlightRecorder:
    """One per node (owned by its Postoffice).  ``record`` is the hot
    path: one short lock + column-array stores into preallocated slots
    — the guard test taps it with tracemalloc."""

    def __init__(self, node: str, config=None, postoffice=None,
                 cap: Optional[int] = None):
        self.node = str(node)
        self.po = postoffice
        n = int(cap if cap is not None
                else getattr(config, "flight_events", 4096) or 4096)
        self.cap = max(8, n)
        # column layout: one preallocated array per field; a slot is
        # overwritten in place on wraparound — record() allocates
        # nothing that outlives the call
        self._t = np.zeros(self.cap, np.float64)
        self._code = np.zeros(self.cap, np.int16)
        self._a = np.zeros(self.cap, np.int64)
        self._b = np.zeros(self.cap, np.int64)
        self._c = np.zeros(self.cap, np.int64)
        self._d = np.zeros(self.cap, np.int64)
        self._peer = np.empty(self.cap, object)  # NodeId/str refs as-is
        self._note = np.empty(self.cap, object)  # interned literals
        self._n = 0          # total ever recorded (monotonic)
        self._mu = threading.Lock()
        self.dumps = 0
        self._dumped_incidents: set = set()
        self._dump_mu = threading.Lock()
        # pressure sources: name -> (fn, gauge); sampled by the metrics
        # pump, the optional sampler thread, and every dump
        self._pressure: Dict[str, tuple] = {}
        self._last_pressure: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = None
        sample_s = float(getattr(config, "flight_sample_s", 0.0) or 0.0)
        if sample_s > 0:
            self._thread = threading.Thread(
                target=self._sample_loop, args=(sample_s,), daemon=True,
                name=f"flight-sampler-{self.node}")
            self._thread.start()

    # ---- hot path -----------------------------------------------------------
    def record(self, code: int, a: int = 0, b: int = 0, c: int = 0,
               d: int = 0, peer=None, note=None,
               t: Optional[float] = None) -> None:
        """Store one event into the ring.  Preallocated slots only: the
        wraparound overwrites the oldest event in place.  ``t`` is
        injectable for deterministic tests; production call sites leave
        it None (monotonic now)."""
        with self._mu:
            i = self._n % self.cap
            self._n += 1
            self._t[i] = time.monotonic() if t is None else t
            self._code[i] = code
            self._a[i] = a
            self._b[i] = b
            self._c[i] = c
            self._d[i] = d
            self._peer[i] = peer
            self._note[i] = note

    # ---- van taps (hot path; see transport/van.py) --------------------------
    def msg_send(self, msg, nbytes: int) -> None:
        """One SEND head: cmd (>=0) or -control, the policy epoch the
        payload was encoded under, size, sender incarnation, peer."""
        self.record(FlightEv.SEND,
                    a=(msg.cmd if msg.control.value == 0
                       else -msg.control.value),
                    b=msg.policy_epoch, c=nbytes, d=msg.boot,
                    peer=msg.recipient)

    def msg_recv(self, msg, nbytes: int) -> None:
        self.record(FlightEv.RECV,
                    a=(msg.cmd if msg.control.value == 0
                       else -msg.control.value),
                    b=msg.policy_epoch, c=nbytes, d=msg.boot,
                    peer=msg.sender)

    def msg_dedup(self, msg) -> None:
        """A reliable-channel duplicate was suppressed — a burst of
        these around an incident is a replay stampede the postmortem
        should see."""
        self.record(FlightEv.DEDUP, a=msg.msg_sig, d=msg.boot,
                    peer=msg.sender, note="resend_dedup")

    # ---- pressure -----------------------------------------------------------
    def add_pressure(self, name: str, fn: Callable[[], float]) -> None:
        """Register one pressure source; its sampled value is recorded
        as a PRESSURE event AND set on the ``<node>.<name>`` registry
        gauge (the PR 7 pump ships that slice)."""
        self._pressure[name] = (fn, system_gauge(f"{self.node}.{name}"))

    def sample_pressure(self) -> Dict[str, float]:
        """One sweep over the registered sources (pump cadence / the
        optional sampler thread / dump time).  A broken source must
        never take the pump down."""
        out = {}
        for name, (fn, gauge) in list(self._pressure.items()):
            try:
                v = float(fn())
            except Exception:
                continue
            if not math.isfinite(v):
                continue
            out[name] = v
            self._last_pressure[name] = v
            gauge.set(v)
            # scaled to int for the fixed column layout (µ-units keep
            # sub-ms lock waits visible)
            self.record(FlightEv.PRESSURE, a=int(v * 1e6), note=name)
        return out

    def _sample_loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                self.sample_pressure()
            except Exception:
                pass

    # ---- reading / dumping --------------------------------------------------
    def events(self) -> List[dict]:
        """Chronological decode of the ring (oldest surviving event
        first).  Off the hot path — allocates freely."""
        with self._mu:
            n = self._n
            if n <= self.cap:
                order = range(n)
            else:
                start = n % self.cap
                order = [(start + i) % self.cap for i in range(self.cap)]
            rows = [(self._t[i], int(self._code[i]), int(self._a[i]),
                     int(self._b[i]), int(self._c[i]), int(self._d[i]),
                     self._peer[i], self._note[i]) for i in order]
        out = []
        for t, code, a, b, c, d, peer, note in rows:
            out.append({
                "t": float(t),
                "ev": _EV_NAMES.get(code, str(code)),
                "a": a, "b": b, "c": c, "d": d,
                "peer": None if peer is None else str(peer),
                "note": None if note is None else str(note),
            })
        return out

    def snapshot(self, incident=None) -> dict:
        """The dump body (also what tests inspect in-memory)."""
        po = self.po
        body = {
            "node": self.node,
            "boot": int(po.van.boot) if po is not None else 0,
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "clock_offsets": (po.clock_offsets() if po is not None
                              else {}),
            "topology": ([str(n) for n in po.topology.all_nodes()]
                         if po is not None else []),
            "incident": incident,
            "pressure": dict(self._last_pressure),
            "n_recorded": self._n,
            "capacity": self.cap,
            "events": self.events(),
        }
        return body

    def dump(self, out_dir: str, incident: Optional[str] = None,
             meta: Optional[dict] = None) -> Optional[str]:
        """Write the ring to ``out_dir`` (one JSON file per node per
        incident).  Idempotent per incident id: a rebroadcast dump
        request is a no-op — exactly one dump per alert transition.
        Returns the path, or None (already dumped / no dir)."""
        if not out_dir:
            return None
        with self._dump_mu:
            if incident is not None:
                if incident in self._dumped_incidents:
                    return None
                self._dumped_incidents.add(incident)
        try:
            self.sample_pressure()  # final reading rides the dump
            body = self.snapshot(incident)
            if meta:
                body["meta"] = meta
            os.makedirs(out_dir, exist_ok=True)
            path = dump_path(out_dir, self.node, incident)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)  # a crash mid-write leaves no torn dump
        except (OSError, ValueError):
            return None  # best-effort: a full disk must not kill the node
        self.dumps += 1
        system_counter(f"{self.node}.flight_dumps").inc()
        self.record(FlightEv.DUMP, note="dump")
        return path

    # ---- wire trigger -------------------------------------------------------
    def on_control(self, msg) -> bool:
        """Postoffice control hook: ``Control.FLIGHT_DUMP`` broadcast
        (health engine alert transition, or operator request relayed by
        the scheduler) — snapshot the incident window."""
        from geomx_tpu.transport.message import Control

        if msg.control is not Control.FLIGHT_DUMP:
            return False
        b = msg.body if isinstance(msg.body, dict) else {}
        out_dir = str(b.get("dir") or os.environ.get("GEOMX_OBS_DIR", ""))
        self.record(FlightEv.ALERT, peer=msg.sender,
                    note=str(b.get("rule") or "flight_dump"))
        self.dump(out_dir, incident=b.get("incident"),
                  meta={k: b[k] for k in ("rule", "subject", "reason")
                        if k in b})
        return True

    def stop(self):
        self._stop.set()


def attach_server_pressure(recorder: Optional[FlightRecorder],
                           striped_lock, shard_executor) -> None:
    """Register the server-tier pressure sources on ``recorder`` (both
    kvstore tiers call this): merge-lock contention, merge-lane
    backlog, and the shared codec pool's queued work.  Each sampled
    value lands in the ring (PRESSURE event) AND on the registry gauge
    the PR 7 pump ships (``lock_wait_s`` / ``lane_depth`` /
    ``codec_pool_busy``; the van's ``van_sendq_depth`` is registered by
    the Postoffice)."""
    if recorder is None:
        return
    stripes = striped_lock._stripes

    def lock_wait() -> float:
        # probe each stripe ONE AT A TIME (never two — the documented
        # lock order): total time spent waiting to step through all of
        # them is the contention reading; an idle server measures ~0
        t0 = time.perf_counter()
        for s in stripes:
            s.acquire()
            s.release()
        return time.perf_counter() - t0

    from geomx_tpu.kvstore.common import codec_pool_depth

    recorder.add_pressure("lock_wait_s", lock_wait)
    recorder.add_pressure("lane_depth", shard_executor.depth)
    recorder.add_pressure("codec_pool_busy", codec_pool_depth)


def broadcast_flight_dump(postoffice, out_dir: str, incident: str,
                          **info) -> List[str]:
    """Ask EVERY plan node (this one included) to snapshot its ring for
    ``incident`` — the health engine's alert trigger and the operator's
    ``--dump-flight`` share this.  Fire-and-forget: a dead node simply
    leaves no dump (which is itself the postmortem's signal).  Returns
    the per-node paths the dumps will land at."""
    from geomx_tpu.transport.message import Control, Domain, Message

    topo = postoffice.topology
    body = {"incident": incident, "dir": out_dir}
    body.update({k: v for k, v in info.items() if v is not None})
    paths = []
    for n in topo.all_nodes():
        paths.append(dump_path(out_dir, str(n), incident))
        try:
            postoffice.van.send(Message(
                recipient=n, control=Control.FLIGHT_DUMP,
                domain=Domain.GLOBAL, request=False, body=dict(body)))
        except (KeyError, OSError):
            pass  # a dark node's missing dump is the finding
    return paths


def install_process_hooks(postoffice) -> None:
    """Real-deployment (one process per role) crash/exit trigger: dump
    this node's ring to ``GEOMX_OBS_DIR`` at interpreter exit and on
    SIGTERM/SIGINT (chained to any previous handler).  SIGKILL leaves
    no dump by definition — the postmortem assembler infers the victim
    from every OTHER node's ring."""
    import atexit
    import signal

    fl = getattr(postoffice, "flight", None)
    if fl is None:
        return

    def _dump(reason: str):
        out_dir = os.environ.get("GEOMX_OBS_DIR", "")
        if out_dir:
            fl.dump(out_dir, incident=reason)

    atexit.register(_dump, "exit")
    for sig in (signal.SIGTERM, signal.SIGINT):
        prev = signal.getsignal(sig)

        def handler(signum, frame, prev=prev):
            _dump(f"signal-{signum}")
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            signal.signal(sig, handler)
        except ValueError:
            pass  # not the main thread (library use) — atexit remains
