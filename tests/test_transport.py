"""Transport-layer tests: routing, serialization, loss/latency, priority, resend."""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.transport import Control, Domain, FaultPolicy, InProcFabric, Message, Van


def _mk(msg_vals=None, **kw):
    m = Message(**kw)
    if msg_vals is not None:
        m.vals = np.asarray(msg_vals, dtype=np.float32)
    return m


def test_roundtrip_serialization():
    m = Message(
        sender=NodeId(Role.WORKER, 1, 0),
        recipient=NodeId(Role.SERVER, 0, 0),
        control=Control.EMPTY,
        domain=Domain.GLOBAL,
        app_id=3, customer_id=2, timestamp=42, request=True, push=True,
        cmd=7, priority=-5, body={"k": [1, 2]},
        keys=np.array([3, 9], dtype=np.int64),
        vals=np.arange(6, dtype=np.float32),
        lens=np.array([2, 4], dtype=np.int64),
        first_key=3, seq=1, seq_begin=0, seq_end=4, channel=2,
        total_bytes=24, val_bytes=8, compr="fp16",
    )
    m2 = Message.from_bytes(m.to_bytes())
    assert m2.sender == m.sender and m2.recipient == m.recipient
    assert m2.control is Control.EMPTY and m2.domain is Domain.GLOBAL
    assert m2.timestamp == 42 and m2.request and m2.push and not m2.pull
    assert m2.body == {"k": [1, 2]} and m2.compr == "fp16"
    np.testing.assert_array_equal(m2.keys, m.keys)
    np.testing.assert_array_equal(m2.vals, m.vals)
    np.testing.assert_array_equal(m2.lens, m.lens)
    assert (m2.seq, m2.seq_end, m2.channel) == (1, 4, 2)


def test_basic_send_recv():
    fab = InProcFabric()
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    got = []
    ev = threading.Event()
    van_a = Van(a, fab)
    van_b = Van(b, fab)
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m), ev.set()))
    van_a.send(_mk([1, 2, 3], recipient=b))
    assert ev.wait(2)
    assert got[0].sender == a
    np.testing.assert_array_equal(got[0].vals, [1, 2, 3])
    assert van_a.send_bytes > 0 and van_b.recv_bytes > 0
    assert van_a.wan_send_bytes == 0  # LOCAL domain
    van_a.stop(); van_b.stop()


def test_wan_byte_accounting():
    fab = InProcFabric()
    a, b = NodeId(Role.SERVER, 0, 0), NodeId(Role.GLOBAL_SERVER, 0)
    van_a, van_b = Van(a, fab), Van(b, fab)
    van_a.start(lambda m: None)
    ev = threading.Event()
    van_b.start(lambda m: ev.set())
    van_a.send(_mk(np.zeros(100), recipient=b, domain=Domain.GLOBAL))
    assert ev.wait(2)
    assert van_a.wan_send_bytes >= 400
    van_a.stop(); van_b.stop()


def test_drop_injection():
    fab = InProcFabric(FaultPolicy(drop_rate=1.0, seed=1))
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    van_a, van_b = Van(a, fab), Van(b, fab)
    got = []
    van_a.start(lambda m: None)
    van_b.start(got.append)
    for _ in range(10):
        van_a.send(_mk([1.0], recipient=b))
    time.sleep(0.1)
    assert got == [] and fab.dropped == 10
    van_a.stop(); van_b.stop()


def test_latency_injection_preserves_order_per_delay():
    fab = InProcFabric(FaultPolicy(latency_s=0.05))
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    van_a, van_b = Van(a, fab), Van(b, fab)
    got = []
    done = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m.timestamp), len(got) == 3 and done.set()))
    t0 = time.monotonic()
    for i in range(3):
        van_a.send(_mk([0.0], recipient=b, timestamp=i))
    assert done.wait(2)
    assert time.monotonic() - t0 >= 0.05
    assert got == [0, 1, 2]
    van_a.stop(); van_b.stop()
    fab.shutdown()


def test_priority_queue_orders_sends():
    fab = InProcFabric()
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    van_a = Van(a, fab, use_priority_queue=True)
    van_b = Van(b, fab)
    got = []
    done = threading.Event()
    van_b.start(lambda m: (got.append(m.priority), len(got) == 20 and done.set()))
    # enqueue before starting the drain thread so ordering is deterministic
    for i in range(20):
        van_a._pq.put((-i if i % 2 else i, next(van_a._pq_tie),
                       _mk([0.0], recipient=b, sender=a, priority=(i if i % 2 else -i))))
    van_a.start(lambda m: None)
    assert done.wait(2)
    assert got == sorted(got, reverse=True)
    van_a.stop(); van_b.stop()


def test_resend_recovers_dropped_messages():
    cfg = Config(resend_timeout_ms=30)
    fab = InProcFabric(FaultPolicy(drop_rate=0.5, seed=3))
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    van_a = Van(a, fab, config=cfg)
    van_b = Van(b, fab, config=cfg)
    got = []
    van_a.start(lambda m: None)
    van_b.start(lambda m: got.append(m.timestamp))
    for i in range(20):
        van_a.send(_mk([float(i)], recipient=b, timestamp=i))
    deadline = time.monotonic() + 5
    while len(set(got)) < 20 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sorted(set(got)) == list(range(20))  # all delivered exactly once logically
    assert len(got) == len(set(got))  # duplicate suppression held
    van_a.stop(); van_b.stop()


def test_topology_enumeration():
    t = Topology(num_parties=2, workers_per_party=2, num_global_servers=2)
    assert t.num_workers_total == 4
    assert t.num_global_workers == 2
    assert len(t.all_nodes()) == 2 * (1 + 1 + 2) + 1 + 2
    nid = NodeId(Role.WORKER, 1, 0)
    assert NodeId.parse(str(nid)) == nid
    gs = NodeId(Role.GLOBAL_SERVER, 1)
    assert NodeId.parse(str(gs)) == gs


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("GEOMX_NUM_PARTIES", "2")
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")
    monkeypatch.setenv("MXNET_KVSTORE_USE_HFA", "1")
    monkeypatch.setenv("MXNET_KVSTORE_HFA_K2", "4")
    monkeypatch.setenv("ENABLE_P3", "1")
    monkeypatch.setenv("PS_DROP_MSG", "10")
    cfg = Config.from_env()
    assert cfg.topology.num_parties == 2
    assert cfg.topology.workers_per_party == 3
    assert cfg.use_hfa and cfg.hfa_k2 == 4
    assert cfg.enable_p3
    assert abs(cfg.drop_rate - 0.1) < 1e-9


def test_van_dedup_keyed_on_incarnation():
    """A restarted sender Van (fresh sig counter, new boot nonce) must not
    have its first reliable messages suppressed as its predecessor's
    duplicates (same (sender, sig), different incarnation)."""
    fab = InProcFabric()
    a, b = NodeId(Role.WORKER, 0, 0), NodeId(Role.SERVER, 0, 0)
    cfg = Config(resend_timeout_ms=200)
    got = []
    van_b = Van(b, fab, cfg)
    van_b.start(lambda m: got.append(float(m.vals[0])))
    van_a1 = Van(a, fab, cfg)
    van_a1.start(lambda m: None)
    van_a1.send(_mk([1.0], recipient=b))
    _wait(lambda: len(got) == 1)
    van_a1.stop()
    # replacement: same node id, sig counter restarts at 1
    van_a2 = Van(a, fab, cfg)
    van_a2.start(lambda m: None)
    assert van_a2.boot != van_a1.boot
    van_a2.send(_mk([2.0], recipient=b))
    _wait(lambda: len(got) == 2)
    assert got == [1.0, 2.0]
    van_a2.stop(); van_b.stop()


def _wait(pred, timeout=5.0):
    import time as _t
    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        if pred():
            return
        _t.sleep(0.01)
    assert pred()


def test_deterministic_mode_trains_and_reproduces():
    """NaiveEngine-analog serial mode (ref: src/engine/naive_engine.cc):
    one dispatcher thread, inline customers — two identical runs produce
    the IDENTICAL wire schedule (message order), and training still
    converges with exact FSA semantics."""
    import numpy as np

    from geomx_tpu.core.config import Config as _Config, Topology as _Topo
    from geomx_tpu.kvstore import Simulation
    from geomx_tpu.transport import van as vanmod

    def run_once():
        order = []
        cfg = _Config(topology=_Topo(num_parties=2, workers_per_party=2),
                      deterministic=True)
        sim = Simulation(cfg)
        assert sim.fabric.serial
        orig = vanmod.InProcFabric.deliver

        def spy(self, msg, _orig=orig):
            if msg.control is Control.EMPTY and self is sim.fabric:
                order.append((str(msg.sender), str(msg.recipient),
                              msg.timestamp, bool(msg.push),
                              bool(msg.pull), msg.cmd))
            return _orig(self, msg)

        vanmod.InProcFabric.deliver = spy
        try:
            ws = sim.all_workers()
            for w in ws:
                w.init(0, np.zeros(32, np.float32))
            ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
            for _ in range(2):
                for w in ws:
                    w.push(0, np.ones(32, np.float32))
                outs = [w.pull_sync(0) for w in ws]
            for out in outs:
                np.testing.assert_allclose(out, -0.4, rtol=1e-6)
            return order
        finally:
            vanmod.InProcFabric.deliver = orig
            sim.shutdown()

    first = run_once()
    second = run_once()
    assert len(first) > 10
    assert first == second


def test_roundtrip_serialization_fuzz():
    """Property fuzz of the wire format: random field combinations and
    payload dtypes must survive to_bytes/from_bytes bit-exactly, and
    TRUNCATED frames must raise cleanly (a WAN peer dying mid-frame
    must never hang or silently mis-decode the receiver)."""
    import numpy as np

    rng = np.random.default_rng(7)
    dtypes = [np.float32, np.float16, np.uint8, np.int64]
    for trial in range(60):
        nk = int(rng.integers(0, 5))
        dt = dtypes[int(rng.integers(0, len(dtypes)))]
        vals = (rng.standard_normal(int(rng.integers(0, 200)))
                .astype(dt, copy=False)
                if dt != np.uint8 else
                rng.integers(0, 255, int(rng.integers(0, 200))
                             ).astype(np.uint8))
        m = Message(
            sender=NodeId(Role.WORKER, int(rng.integers(0, 4)),
                          int(rng.integers(0, 3))),
            recipient=NodeId(Role.SERVER, 0, int(rng.integers(0, 3))),
            domain=(Domain.GLOBAL if rng.integers(0, 2) else Domain.LOCAL),
            app_id=int(rng.integers(0, 8)),
            customer_id=int(rng.integers(0, 8)),
            timestamp=int(rng.integers(-1, 1000)),
            request=bool(rng.integers(0, 2)),
            push=bool(rng.integers(0, 2)),
            cmd=int(rng.integers(0, 200)),
            priority=int(rng.integers(-20, 20)),
            body=({"n": int(rng.integers(0, 9)), "s": "x" * 5}
                  if rng.integers(0, 2) else None),
            keys=rng.integers(0, 1 << 40, nk).astype(np.int64),
            vals=vals,
            lens=rng.integers(0, 100, nk).astype(np.int64),
            seq=int(rng.integers(0, 100)),
            seq_end=int(rng.integers(0, 100)),
            channel=int(rng.integers(0, 4)),
            compr=["", "fp16", "bsc", "2bit"][int(rng.integers(0, 4))],
        )
        raw = m.to_bytes()
        m2 = Message.from_bytes(raw)
        assert m2.sender == m.sender and m2.recipient == m.recipient
        assert m2.timestamp == m.timestamp and m2.cmd == m.cmd
        assert m2.priority == m.priority and m2.body == m.body
        assert m2.compr == m.compr and m2.channel == m.channel
        # every randomized field must round-trip, or the fuzz silently
        # stops covering it
        assert m2.request == m.request and m2.push == m.push
        assert m2.domain is m.domain
        assert m2.app_id == m.app_id and m2.customer_id == m.customer_id
        assert m2.seq == m.seq and m2.seq_end == m.seq_end
        np.testing.assert_array_equal(m2.keys, m.keys)
        np.testing.assert_array_equal(np.asarray(m2.vals),
                                      np.asarray(m.vals))
        np.testing.assert_array_equal(m2.lens, m.lens)
        # truncation at an arbitrary point must raise, not hang/garble
        if len(raw) > 4:
            cut = int(rng.integers(1, len(raw)))
            try:
                Message.from_bytes(raw[:cut])
            except Exception:
                pass  # any clean exception is acceptable
            else:
                # decoding a prefix "successfully" is only legal if the
                # cut landed past everything the format needs
                assert cut >= len(raw) - 1, cut
