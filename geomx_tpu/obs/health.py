"""SLO health engine: rule evaluation over the collected series.

Pull-based like the adaptive-WAN controller: each :meth:`tick` sweeps
the ``MetricsCollector`` rings (and, when tracing is on, the trace
collector's critical-path report) through a fixed rule set and emits
structured alert records on STATE TRANSITIONS only — one record when a
rule starts firing for a subject, one when it recovers.  Every record
lands four independent ways:

- appended to ``HealthEngine.alerts`` (and the JSONL alert log when
  ``Config.obs_alert_log`` names one);
- registry counters (``<gsched>.health_alerts`` / ``health_recoveries``
  + a per-rule counter);
- a ``health.alert`` trace instant, so alerts interleave with the PR 3
  merged timeline exactly like failover/eviction control events;
- one stdout line per transition (``health ALERT ...`` /
  ``health RECOVERED ...``) the chaos scripts assert on.

Rules (thresholds are ``Config.obs_*`` knobs):

- **round_stall** — a global shard completed no key-round within
  ``max(obs_stall_min_s, obs_stall_factor x rolling-median gap)``;
  progress is tracked per (node, boot) so a promoted standby's first
  completed round is the recovery signal.
- **replication_lag** — a shard's hot-standby lag gauge exceeds
  ``obs_repl_lag_s``.
- **shard_imbalance** — the critical-path report's slowest shard is
  busy more than ``obs_imbalance_factor`` x the mean of its peers.
- **goodput_collapse** — a party's WAN byte rate fell below
  ``obs_goodput_frac`` x its rolling peak while its rounds are still
  progressing (a throttled-not-idle link).
- **rtt_outlier** — a node's heartbeat RTT exceeds ``obs_rtt_s`` or
  8x the fleet median.
- **fence_spike** — fenced/evicted/rejected event counters for one
  node grew by more than ``obs_fence_spike`` within the ring window.
- **replica_staleness** — a serve replica's reported local-copy age
  exceeds the configured read bound (``Config.serve_staleness_s``):
  its refresh loop is falling behind, so reads are parking instead of
  being answered (the serving tier's SLO; geomx_tpu/serve).
- **churn_storm** — membership transitions (graceful leaves, kills,
  joins — injected by the churn orchestrator or organic) exceed
  ``obs_churn_storm`` within the window, or the orchestrator's
  survivor gauge reaches its min-survivor floor (the next departure
  stalls training; docs/deployment.md "Elasticity & preemption").
- **serve_overload** — a serve replica's admission-control shed rate
  (explicit RETRY_AFTER refusals, geomx_tpu/serve) is sustained above
  ``obs_shed_rate`` per second over the collector window: the tier is
  degrading by design, but it needs capacity (docs/serving.md
  "Serving plane").
- **replica_flap** — the replica autoscaler counted direction
  reversals inside its cooldown (``autoscale_flaps``) past
  ``obs_replica_flap`` within the window: the scaling signals are
  oscillating faster than the hysteresis can follow — widen the
  deadband or lengthen the cooldown.
- **net_partition** — some monitor's ``quarantined_nodes`` gauge is
  nonzero: a node/party is heartbeat-dead but an indirect probe still
  hears it, so it was folded out REVERSIBLY instead of evicted
  (docs/deployment.md "Partition tolerance").  Training is running
  degraded; the alert recovers when the partition heals (or escalates
  into eviction/fold events, which page through fence_spike /
  churn_storm instead).
"""

from __future__ import annotations

import collections
import json
import math
import statistics
import threading
import time
from typing import Dict, List, Optional, Tuple

from geomx_tpu.trace.collector import _shard_of
from geomx_tpu.utils.metrics import system_counter

# counters summed by the fence_spike rule (stats keys and/or registry
# suffixes — whatever the node ships)
_FENCE_KEYS = ("eviction_fenced_pushes", "fenced_rejects",
               "policy_fenced_pushes", "rejected_compr_tags",
               "evicted_workers", "worker_evictions")

RULES = ("round_stall", "replication_lag", "shard_imbalance",
         "goodput_collapse", "rtt_outlier", "fence_spike",
         "replica_staleness", "churn_storm", "serve_overload",
         "replica_flap", "net_partition", "data_corruption")

# counters summed per node by the data_corruption rule: every reject
# the integrity plane produces (wire checksum mismatches, poisoned
# gradient pushes, corrupt checkpoint/replication snapshots) plus the
# quarantines they escalated into — a repeat offender shows up as a
# sustained per-node rate here long before training loss moves
_INTEGRITY_KEYS = ("integrity_wire_rejects", "integrity_wire_nacks",
                   "integrity_poison_rejects", "integrity_ckpt_rejects",
                   "integrity_codec_rejects", "poison_quarantines")

# membership-transition counters summed by the churn_storm rule: the
# churn orchestrator's injected-event family (registered on the global
# scheduler by chaos/churn.py) plus the organic server-side counters,
# so a storm pages whether it was scripted or real
_CHURN_KEYS = ("churn_notices", "churn_graceful_leaves",
               "churn_ungraceful_kills", "churn_joins",
               "churn_replica_kills",
               "left_workers", "evicted_workers", "joined_workers")


def _json_safe(obj):
    """NaN-fenced copy (invalid-JSON floats become None)."""
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


class HealthEngine:
    """One per deployment, beside the MetricsCollector on the global
    scheduler.  ``Config.obs_interval_s <= 0`` runs no sweep thread —
    tests drive :meth:`tick` deterministically."""

    def __init__(self, collector, config=None, trace_collector=None):
        from geomx_tpu.trace.recorder import get_tracer

        self.collector = collector
        self.config = config or collector.config
        self.trace_collector = trace_collector
        self.node = collector.node
        cfg = self.config
        self.stall_factor = float(getattr(cfg, "obs_stall_factor", 4.0))
        self.stall_min_s = float(getattr(cfg, "obs_stall_min_s", 2.0))
        self.repl_lag_s = float(getattr(cfg, "obs_repl_lag_s", 60.0))
        self.rtt_s = float(getattr(cfg, "obs_rtt_s", 1.0))
        self.goodput_frac = float(getattr(cfg, "obs_goodput_frac", 0.1))
        self.fence_spike = int(getattr(cfg, "obs_fence_spike", 8))
        self.imbalance_factor = float(
            getattr(cfg, "obs_imbalance_factor", 4.0))
        self.shed_rate = float(getattr(cfg, "obs_shed_rate", 2.0))
        self.replica_flap = int(getattr(cfg, "obs_replica_flap", 2))
        self.alert_log = str(getattr(cfg, "obs_alert_log", "") or "")
        self._mu = threading.Lock()
        self.active: Dict[Tuple[str, str], dict] = {}
        self.alerts: List[dict] = []      # transition history, bounded
        self._cap = 4096
        # round_stall bookkeeping: per shard subject, the last seen
        # (boot, value) per reporting node + progress times + gaps
        self._stall: Dict[str, dict] = {}
        self._peak_rate: Dict[str, float] = {}
        self._tr = get_tracer(self.node)
        self._alert_counter = system_counter(f"{self.node}.health_alerts")
        self._recovery_counter = system_counter(
            f"{self.node}.health_recoveries")
        self._rule_counters = {r: system_counter(
            f"{self.node}.health_{r}_alerts") for r in RULES}
        self._stop = threading.Event()
        self._thread = None
        # flight-recorder incident trigger: each FIRING transition
        # broadcasts Control.FLIGHT_DUMP so EVERY node snapshots the
        # same incident window (obs/flight.py); the counter keys the
        # incident ids so two transitions never collide on one file.
        # Per-(rule, subject) cooldown: the FIRST firing captures the
        # evidence; a flapping rule re-firing inside the window must
        # not flood the dump dir with near-identical snapshots
        self._flight_incidents = 0
        self._flight_last: Dict[Tuple[str, str], float] = {}
        self._flight_cooldown = float(
            getattr(cfg, "obs_flight_cooldown_s", 60.0))
        if getattr(cfg, "obs_interval_s", 0) > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"health-engine-{self.node}")
            self._thread.start()

    def _run(self):
        while not self._stop.wait(self.config.obs_interval_s):
            try:
                self.tick()
            except Exception:  # a sweep error must not kill the loop
                import logging

                logging.getLogger(__name__).exception(
                    "%s: health sweep failed", self.node)

    # ---- evaluation ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation sweep; returns the NEW transition records
        (alerts + recoveries) it produced.  ``now`` is injectable for
        deterministic tests."""
        now = time.monotonic() if now is None else now
        records = []
        for rule in (self._rule_round_stall, self._rule_replication_lag,
                     self._rule_shard_imbalance, self._rule_goodput_collapse,
                     self._rule_rtt_outlier, self._rule_fence_spike,
                     self._rule_replica_staleness, self._rule_churn_storm,
                     self._rule_serve_overload, self._rule_replica_flap,
                     self._rule_net_partition,
                     self._rule_data_corruption):
            try:
                records.extend(rule(now))
            except Exception:  # one broken rule must not mute the rest
                import logging

                logging.getLogger(__name__).exception(
                    "%s: health rule %s failed", self.node, rule.__name__)
        return records

    def active_alerts(self) -> List[dict]:
        with self._mu:
            return [dict(a) for a in self.active.values()]

    # ---- state machine ------------------------------------------------------
    def _set_state(self, rule: str, subject: str, firing: bool, now: float,
                   severity: str = "warn", message: str = "",
                   **data) -> Optional[dict]:
        key = (rule, subject)
        with self._mu:
            cur = self.active.get(key)
            if firing and cur is None:
                rec = {"rule": rule, "subject": subject, "state": "firing",
                       "severity": severity, "t": time.time(),
                       "t_mono": now, "message": message,
                       "data": _json_safe(data)}
                self.active[key] = rec
            elif not firing and cur is not None:
                del self.active[key]
                rec = {"rule": rule, "subject": subject,
                       "state": "recovered", "severity": cur["severity"],
                       "t": time.time(), "t_mono": now,
                       "firing_for_s": round(now - cur["t_mono"], 3),
                       "message": message or "condition cleared",
                       "data": _json_safe(data)}
            else:
                return None
        self._emit(rec)
        return rec

    def _emit(self, rec: dict) -> None:
        firing = rec["state"] == "firing"
        if firing:
            # snapshot the incident window BEFORE anything else: every
            # node's flight ring dumps under one incident id, and the
            # alert record carries the dump paths (obs/flight.py)
            flight = self._request_flight_dump(rec)
            if flight is not None:
                rec.setdefault("data", {})["flight"] = flight
        with self._mu:
            self.alerts.append(rec)
            del self.alerts[:-self._cap]
        if firing:
            self._alert_counter.inc()
            self._rule_counters[rec["rule"]].inc()
        else:
            self._recovery_counter.inc()
        # alerts land on the merged trace timeline like failover events
        self._tr.instant("health.alert", rule=rec["rule"],
                         subject=rec["subject"], state=rec["state"],
                         severity=rec["severity"])
        print(f"{self.node}: health "
              f"{'ALERT' if firing else 'RECOVERED'} {rec['rule']} "
              f"{rec['subject']} — {rec['message']}", flush=True)
        if self.alert_log:
            try:
                with open(self.alert_log, "a") as f:
                    f.write(json.dumps(rec, allow_nan=False) + "\n")
            except (OSError, ValueError):
                pass  # the log is best-effort; registry/stdout remain

    def _request_flight_dump(self, rec: dict) -> Optional[dict]:
        """Broadcast ``Control.FLIGHT_DUMP`` for one firing transition:
        exactly one incident id per transition, so every node dumps
        exactly once per alert (the per-node recorders dedup
        rebroadcasts by the id).  Returns the info dict the alert
        record carries (None when the recorder plane or GEOMX_OBS_DIR
        is off)."""
        import os

        po = self.collector.po
        if getattr(po, "flight", None) is None:
            return None
        out_dir = os.environ.get("GEOMX_OBS_DIR", "")
        if not out_dir:
            return None
        from geomx_tpu.obs.flight import broadcast_flight_dump

        key = (rec["rule"], rec["subject"])
        now = rec["t_mono"]
        with self._mu:
            last = self._flight_last.get(key)
            if (last is not None and self._flight_cooldown > 0
                    and now - last < self._flight_cooldown):
                return None  # flapping: the first firing has the window
            self._flight_last[key] = now
            self._flight_incidents += 1
            n = self._flight_incidents
        subject = "".join(c if c.isalnum() else "_"
                          for c in str(rec["subject"]))
        incident = f"{rec['rule']}-{subject}-{n}"
        try:
            paths = broadcast_flight_dump(po, out_dir, incident,
                                          rule=rec["rule"],
                                          subject=rec["subject"])
        except Exception:  # the dump trigger must never mute the alert
            return None
        return {"incident": incident, "dir": out_dir, "paths": paths}

    # ---- rules --------------------------------------------------------------
    def _rule_round_stall(self, now: float) -> List[dict]:
        out = []
        topo = self.collector.po.topology
        nodes = self.collector.nodes()
        for k in range(topo.num_global_servers):
            subject = f"shard:{k}"
            st = self._stall.setdefault(subject, {
                "v": {}, "t_prog": None,
                "gaps": collections.deque(maxlen=32)})
            progressed = False
            for node in nodes:
                if _shard_of(node) != k:
                    continue
                sample = self.collector.latest(node)
                if sample is None:
                    continue
                v = self.collector._get(sample, node, "key_rounds")
                if not isinstance(v, (int, float)):
                    continue
                boot = sample.get("boot", 0)
                prev = st["v"].get(node)
                st["v"][node] = (boot, v)
                # progress only counts within one boot: a restarted
                # holder's zeroed counter re-baselines instead of
                # masking (or faking) progress
                if prev is not None and prev[0] == boot and v > prev[1]:
                    progressed = True
            if progressed:
                if st["t_prog"] is not None:
                    st["gaps"].append(now - st["t_prog"])
                st["t_prog"] = now
            if st["t_prog"] is None:
                continue  # this shard never completed a round yet
            med = statistics.median(st["gaps"]) if st["gaps"] else 0.0
            limit = max(self.stall_min_s, self.stall_factor * med)
            stalled = now - st["t_prog"]
            rec = self._set_state(
                "round_stall", subject, stalled > limit, now,
                severity="critical",
                message=(f"no key-round completed in {stalled:.2f}s "
                         f"(limit {limit:.2f}s)" if stalled > limit
                         else f"round completed after {stalled:.2f}s"),
                stalled_for_s=round(stalled, 3), limit_s=round(limit, 3))
            if rec:
                out.append(rec)
        return out

    def _rule_replication_lag(self, now: float) -> List[dict]:
        out = []
        for node in self.collector.nodes():
            v = self.collector.value(node, "replication_lag_s")
            if not isinstance(v, (int, float)):
                continue
            rec = self._set_state(
                "replication_lag", node, v > self.repl_lag_s, now,
                message=f"standby lag {v:.1f}s (ceiling "
                        f"{self.repl_lag_s:.0f}s)",
                lag_s=round(float(v), 3), ceiling_s=self.repl_lag_s)
            if rec:
                out.append(rec)
        return out

    def _rule_shard_imbalance(self, now: float) -> List[dict]:
        if self.trace_collector is None:
            return []
        try:
            rounds = self.trace_collector.critical_path().get("rounds") or ()
        except Exception:
            return []
        if not rounds:
            return []
        by_shard = rounds[-1].get("by_shard") or {}
        if len(by_shard) < 2:
            return []
        slowest = max(by_shard, key=by_shard.get)
        others = [v for s, v in by_shard.items() if s != slowest]
        mean = sum(others) / len(others)
        firing = mean > 0 and by_shard[slowest] > self.imbalance_factor * mean
        out = []
        for s in by_shard:
            rec = self._set_state(
                "shard_imbalance", f"shard:{s}",
                firing and s == slowest, now,
                message=f"shard busy {by_shard[s] / 1e3:.1f}ms vs peer "
                        f"mean {mean / 1e3:.1f}ms",
                busy_us=by_shard[s], peer_mean_us=mean)
            if rec:
                out.append(rec)
        return out

    def _rule_goodput_collapse(self, now: float) -> List[dict]:
        out = []
        for node in self.collector.nodes():
            if not node.startswith("server:"):
                continue  # WAN senders only (the local servers)
            rate = self.collector.rate(node, "wan_send_bytes")
            if rate is None:
                continue
            peak = self._peak_rate.get(node, 0.0)
            self._peak_rate[node] = max(peak, rate)
            rounds_rate = self.collector.rate(node, "wan_push_rounds")
            firing = (peak > 0 and rate < self.goodput_frac * peak
                      and bool(rounds_rate) and rounds_rate > 0)
            rec = self._set_state(
                "goodput_collapse", node, firing, now,
                message=f"WAN goodput {rate / 1e6:.2f} MB/s vs peak "
                        f"{max(peak, rate) / 1e6:.2f} MB/s",
                goodput_bps=rate, peak_bps=max(peak, rate))
            if rec:
                out.append(rec)
        return out

    def _rule_rtt_outlier(self, now: float) -> List[dict]:
        rtts = {}
        for node in self.collector.nodes():
            v = self.collector.value(node, "heartbeat_rtt_s")
            if isinstance(v, (int, float)) and math.isfinite(v):
                rtts[node] = float(v)
        med = statistics.median(rtts.values()) if len(rtts) >= 3 else None
        out = []
        for node, v in rtts.items():
            firing = v > self.rtt_s or (
                med is not None and v > 8 * max(med, 1e-3))
            rec = self._set_state(
                "rtt_outlier", node, firing, now,
                message=f"heartbeat RTT {v * 1e3:.1f}ms "
                        + (f"(fleet median {med * 1e3:.1f}ms)"
                           if med is not None else
                           f"(ceiling {self.rtt_s:.2f}s)"),
                rtt_s=v, median_s=med)
            if rec:
                out.append(rec)
        return out

    def _rule_fence_spike(self, now: float) -> List[dict]:
        out = []
        for node in self.collector.nodes():
            total = 0.0
            seen = False
            for key in _FENCE_KEYS:
                pts = self.collector.series(node, key)
                if len(pts) >= 2:
                    seen = True
                    total += pts[-1][1] - pts[0][1]
            if not seen:
                continue
            rec = self._set_state(
                "fence_spike", node, total > self.fence_spike, now,
                message=f"{total:.0f} fenced/evicted events in the "
                        f"window (threshold {self.fence_spike})",
                events=total, threshold=self.fence_spike)
            if rec:
                out.append(rec)
        return out

    def _rule_data_corruption(self, now: float) -> List[dict]:
        """Sustained integrity rejects from one node mean its data path
        is rotting — a flaky NIC corrupting frames, a worker emitting
        NaN gradients, a disk eating checkpoint generations.  Any
        single reject is survivable by design (checksum → NACK resend,
        poison → zeroed + typed error, corrupt snapshot → previous
        generation); this rule pages when the RATE says the fault is
        chronic, naming the offender the quarantine machinery is
        already throttling."""
        bound = int(getattr(self.config, "obs_corruption_events", 8))
        out = []
        for node in self.collector.nodes():
            total = 0.0
            quarantines = 0.0
            seen = False
            for key in _INTEGRITY_KEYS:
                pts = self.collector.series(node, key)
                if len(pts) >= 2:
                    seen = True
                    delta = pts[-1][1] - pts[0][1]
                    total += delta
                    if key == "poison_quarantines":
                        quarantines += delta
            if not seen:
                continue
            rec = self._set_state(
                "data_corruption", node, total > bound, now,
                severity="critical" if quarantines else "warn",
                message=f"{total:.0f} integrity rejects in the window "
                        f"(threshold {bound}"
                        + (f", {quarantines:.0f} quarantines)"
                           if quarantines else ")"),
                events=total, quarantines=quarantines, threshold=bound)
            if rec:
                out.append(rec)
        return out

    def _rule_churn_storm(self, now: float) -> List[dict]:
        """Elastic membership under churn is NORMAL (docs/deployment.md
        "Elasticity & preemption") — but a membership-transition RATE
        past ``obs_churn_storm`` per collector window means the fleet
        is thrashing (preemption wave, flapping autoscaler), and a
        survivor count at the churn plan's min-survivor floor means the
        next departure stalls training.  Two subjects: ``cluster``
        (event rate) and ``survivor_floor`` (the orchestrator's
        ``churn_survivors`` / ``churn_min_survivors`` gauges)."""
        bound = int(getattr(self.config, "obs_churn_storm", 16))
        out = []
        total = 0.0
        seen = False
        for node in self.collector.nodes():
            for key in _CHURN_KEYS:
                pts = self.collector.series(node, key)
                if len(pts) >= 2:
                    seen = True
                    total += pts[-1][1] - pts[0][1]
        if seen:
            rec = self._set_state(
                "churn_storm", "cluster", total > bound, now,
                message=f"{total:.0f} membership transitions in the "
                        f"window (threshold {bound})",
                events=total, threshold=bound)
            if rec:
                out.append(rec)
        # min-survivor floor: gauges shipped by the churn orchestrator
        # (absent outside orchestrated runs — nothing to judge then)
        survivors = floor = None
        for node in self.collector.nodes():
            s = self.collector.value(node, "churn_survivors")
            f = self.collector.value(node, "churn_min_survivors")
            if isinstance(s, (int, float)) and isinstance(f, (int, float)):
                survivors, floor = float(s), float(f)
                break
        if survivors is not None and floor is not None and floor > 0:
            firing = survivors <= floor + 1
            rec = self._set_state(
                "churn_storm", "survivor_floor", firing, now,
                severity="critical",
                message=(f"{survivors:.0f} survivors at the churn "
                         f"plan's floor ({floor:.0f}) — the next "
                         "departure stalls training" if firing else
                         f"{survivors:.0f} survivors, clear of the "
                         f"floor ({floor:.0f})"),
                survivors=survivors, floor=floor)
            if rec:
                out.append(rec)
        return out

    def _rule_serve_overload(self, now: float) -> List[dict]:
        """A sustained admission-control shed rate is the serving
        plane's capacity alarm: the replica is protecting its latency
        by refusing reads (the intended degradation), but the refusals
        are landing on real clients — add capacity or raise the
        budget (docs/serving.md)."""
        out = []
        for node in self.collector.nodes():
            if not node.startswith("replica:"):
                continue
            rate = self.collector.rate(node, "serve_sheds")
            if rate is None:
                continue
            rec = self._set_state(
                "serve_overload", node, rate > self.shed_rate, now,
                message=(f"shedding {rate:.1f} reads/s with RETRY_AFTER "
                         f"(threshold {self.shed_rate:.1f}/s)"
                         if rate > self.shed_rate else
                         f"shed rate {rate:.1f}/s, back under the "
                         f"threshold ({self.shed_rate:.1f}/s)"),
                shed_rate=round(float(rate), 3),
                threshold=self.shed_rate)
            if rec:
                out.append(rec)
        return out

    def _rule_replica_flap(self, now: float) -> List[dict]:
        """Autoscaler direction reversals inside cooldown
        (``autoscale_flaps``, shipped by the global scheduler's own
        pump): the scaling signals oscillate faster than the
        hysteresis can follow — the actuated sequence stays stable
        (cooldown blocks the reversal), but the operator should widen
        the deadband or lengthen the cooldown."""
        total = 0.0
        seen = False
        for node in self.collector.nodes():
            pts = self.collector.series(node, "autoscale_flaps")
            if len(pts) >= 2:
                seen = True
                total += pts[-1][1] - pts[0][1]
        if not seen:
            return []
        rec = self._set_state(
            "replica_flap", "autoscaler",
            total >= self.replica_flap, now,
            message=f"{total:.0f} suppressed direction reversals in "
                    f"the window (threshold {self.replica_flap})",
            reversals=total, threshold=self.replica_flap)
        return [rec] if rec else []

    def _rule_net_partition(self, now: float) -> List[dict]:
        """A nonzero ``quarantined_nodes`` gauge (shipped by the party
        schedulers' worker monitors and the global scheduler's recovery
        monitor) means the quarantine-not-evict machinery is holding a
        suspect in limbo: heartbeats expired but an indirect probe
        still hears it.  Degraded but self-healing — the alert clears
        on heal (unquarantine) or when the escalation paths (eviction /
        party fold) take over."""
        total = 0.0
        seen = False
        for node in self.collector.nodes():
            v = self.collector.value(node, "quarantined_nodes")
            if isinstance(v, (int, float)) and math.isfinite(v):
                seen = True
                total += float(v)
        if not seen:
            return []
        rec = self._set_state(
            "net_partition", "cluster", total > 0, now,
            message=(f"{total:.0f} node(s)/part(ies) quarantined — "
                     "heartbeat-dead but probe-alive; training runs "
                     "degraded until the partition heals" if total > 0
                     else "all quarantines lifted"),
            quarantined=total)
        return [rec] if rec else []

    def _rule_replica_staleness(self, now: float) -> List[dict]:
        out = []
        bound = float(getattr(self.config, "serve_staleness_s", 5.0))
        for node in self.collector.nodes():
            if not node.startswith("replica:"):
                continue
            v = self.collector.value(node, "staleness_s")
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                continue  # never refreshed yet: nothing to judge
            rec = self._set_state(
                "replica_staleness", node, v > bound, now,
                message=f"local model copy {v:.2f}s old (read bound "
                        f"{bound:.2f}s — reads are parking)"
                if v > bound else
                f"local copy {v:.2f}s old, back under the bound",
                staleness_s=round(float(v), 3), bound_s=bound)
            if rec:
                out.append(rec)
        return out

    def stop(self):
        self._stop.set()
