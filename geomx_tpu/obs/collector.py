"""Scheduler-side metrics collector: ring-buffered per-node series.

Runs on the global scheduler next to (and sharing a telemetry endpoint
with) PR 3's trace collector.  Each ``Ctrl.METRICS_REPORT`` frame is
appended to the sender's bounded ring; derived reads are pull-based:

- :meth:`rate` — boot-fenced delta rates over the ring (a warm-booted
  node's counter reset truncates the ring instead of producing a
  negative rate that looks like a collapse);
- :meth:`latest_stats` — freshest QUERY_STATS-style sample per server,
  which the adaptive-WAN controller consumes instead of issuing its own
  QUERY_STATS sweeps when the pump cadence already covers it;
- :meth:`trace_counter_events` — perfetto counter-track ("ph": "C")
  events that merge into the trace collector's clock-corrected timeline
  (registered as an ``extra_event_sources`` hook, so ``dump_trace``
  interleaves round spans with the metric curves behind them);
- :meth:`prometheus_text` — Prometheus-style text exposition of the
  freshest sample per node (never-set gauges are NaN-fenced out).
"""

from __future__ import annotations

import collections
import math
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from geomx_tpu.utils.metrics import system_counter

# the default counter tracks merged into the trace timeline: the round
# pipeline's load-bearing series (bytes moved, rounds completed, policy
# epoch) plus the failure-detector inputs
DEFAULT_TRACKS = ("wan_send_bytes", "wan_push_rounds", "key_rounds",
                  "replication_lag_s", "heartbeat_rtt_s", "policy_epoch")


class MetricsCollector:
    """One per deployment, on the global scheduler's postoffice."""

    def __init__(self, postoffice, config=None, trace_collector=None,
                 tracks: Tuple[str, ...] = DEFAULT_TRACKS):
        from geomx_tpu.kvstore.common import Ctrl
        from geomx_tpu.obs.endpoint import get_endpoint

        self.po = postoffice
        self.node = str(postoffice.node)
        self.config = config or postoffice.config
        self.window = max(8, int(getattr(self.config, "obs_window", 256)))
        self.tracks = tuple(tracks)
        self.trace_collector = trace_collector
        self._mu = threading.Lock()
        self._rings: Dict[str, collections.deque] = {}
        self._boots: Dict[str, int] = {}
        self._offsets: Dict[str, Dict[str, float]] = {}
        self.node_restarts: Dict[str, int] = {}
        self.reports_received = 0
        self._reports_counter = system_counter(f"{self.node}.obs_reports")
        self._restart_counter = system_counter(
            f"{self.node}.obs_node_restarts")
        self._endpoint = get_endpoint(postoffice).acquire()
        self._endpoint.route(Ctrl.METRICS_REPORT, self._on_report)
        if trace_collector is not None:
            trace_collector.extra_event_sources.append(
                self.trace_counter_events)

    def _on_report(self, msg):
        body = msg.body if isinstance(msg.body, dict) else {}
        self.ingest(body)

    def ingest(self, body: dict) -> None:
        node = str(body.get("node", "?"))
        t_recv = time.monotonic()
        with self._mu:
            ring = self._rings.setdefault(
                node, collections.deque(maxlen=self.window))
            boot = int(body.get("boot", 0) or 0)
            prev = self._boots.get(node)
            if boot and prev is not None and prev != boot:
                # warm-booted replacement at the same identity: its
                # zeroed counters are a new life, not a rate collapse —
                # fence the ring so no delta spans the restart
                ring.clear()
                self.node_restarts[node] = self.node_restarts.get(node, 0) + 1
                self._restart_counter.inc()
            if boot:
                self._boots[node] = boot
            ring.append({
                "t": float(body.get("t_mono", t_recv)),
                "t_recv": t_recv,
                "boot": boot,
                "seq": int(body.get("seq", 0) or 0),
                "uptime_s": float(body.get("uptime_s", 0.0) or 0.0),
                "metrics": dict(body.get("metrics") or {}),
                "stats": dict(body.get("stats") or {}),
            })
            offs = body.get("offsets")
            if offs:
                self._offsets[node] = {str(k): float(v)
                                       for k, v in offs.items()}
            self.reports_received += 1
        self._reports_counter.inc()

    # ---- series access ------------------------------------------------------
    def nodes(self) -> List[str]:
        with self._mu:
            return sorted(self._rings)

    def latest(self, node: str) -> Optional[dict]:
        with self._mu:
            ring = self._rings.get(str(node))
            return dict(ring[-1]) if ring else None

    def latest_stats(self, node: str,
                     max_age_s: Optional[float] = None) -> Optional[dict]:
        """Freshest stats dict for ``node`` (None when absent or staler
        than ``max_age_s`` by local receive time) — the controller's
        QUERY_STATS substitute."""
        with self._mu:
            ring = self._rings.get(str(node))
            if not ring:
                return None
            s = ring[-1]
            if (max_age_s is not None
                    and time.monotonic() - s["t_recv"] > max_age_s):
                return None
            return dict(s["stats"])

    @staticmethod
    def _get(sample: dict, node: str, key: str):
        """Value of ``key`` in one sample: stats first, then the
        registry (bare suffix or full dotted name)."""
        v = sample["stats"].get(key)
        if v is not None:
            return v
        m = sample["metrics"]
        return m.get(f"{node}.{key}", m.get(key))

    def value(self, node: str, key: str):
        s = self.latest(node)
        return None if s is None else self._get(s, str(node), key)

    def series(self, node: str, key: str) -> List[Tuple[float, float]]:
        """(t_mono, value) pairs over the ring (sender clock)."""
        node = str(node)
        with self._mu:
            ring = list(self._rings.get(node) or ())
        out = []
        for s in ring:
            v = self._get(s, node, key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out.append((s["t"], float(v)))
        return out

    def rate(self, node: str, key: str,
             lookback_s: Optional[float] = None) -> Optional[float]:
        """Δvalue/Δt over the ring (or its trailing ``lookback_s``);
        None with < 2 samples.  Boot fencing happens at ingest, so a
        restart can never produce a negative counter rate here."""
        pts = self.series(node, key)
        if lookback_s is not None and pts:
            t1 = pts[-1][0]
            pts = [p for p in pts if t1 - p[0] <= lookback_s]
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def sample_age_s(self, node: str,
                     now: Optional[float] = None) -> Optional[float]:
        """Local seconds since ``node``'s last report (collection
        freshness — a dead node's series goes stale before any counter
        says so)."""
        with self._mu:
            ring = self._rings.get(str(node))
            if not ring:
                return None
            t = ring[-1]["t_recv"]
        return (now if now is not None else time.monotonic()) - t

    # ---- perfetto counter tracks --------------------------------------------
    def trace_counter_events(self) -> List[dict]:
        """Counter-track events for the trace collector's merged
        timeline: one "C"-phase event per (sample, tracked key), on the
        sender's monotonic clock — the collector rebases them with the
        same per-node offsets as the spans."""
        with self._mu:
            rings = {n: list(r) for n, r in self._rings.items()}
        out = []
        for node, ring in rings.items():
            for s in ring:
                t_us = s["t"] * 1e6
                for key in self.tracks:
                    v = self._get(s, node, key)
                    if not (isinstance(v, (int, float))
                            and not isinstance(v, bool)
                            and math.isfinite(v)):
                        continue
                    out.append({
                        "name": f"metric.{key}", "cat": "metrics",
                        "ph": "C", "ts": t_us, "dur": 0.0,
                        "pid": node, "tid": "metrics",
                        "args": {key: float(v), "t_mono_us": t_us,
                                 "trace_id": 0, "span": 0, "parent": 0},
                    })
        return out

    # ---- text exposition ----------------------------------------------------
    @staticmethod
    def _prom_name(name: str) -> str:
        return "geomx_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)

    def prometheus_text(self) -> str:
        """Prometheus-style text exposition of the freshest sample per
        node.  Registry names become ``geomx_<suffix>{node="..."}``;
        non-finite values (never-set gauges) and non-numeric stats are
        fenced out — the dump is always parseable."""
        with self._mu:
            latest = {n: r[-1] for n, r in self._rings.items() if r}
        lines = ["# GeoMX system metrics (freshest sample per node)"]
        for node in sorted(latest):
            s = latest[node]
            rows = {}
            for name, v in s["metrics"].items():
                family = name.split(".", 1)[1] if name.startswith(
                    f"{node}.") else name
                rows[self._prom_name(family)] = v
            for name, v in s["stats"].items():
                rows[self._prom_name(name)] = v
            for fam in sorted(rows):
                v = rows[fam]
                if isinstance(v, bool):
                    v = int(v)
                if not isinstance(v, (int, float)) or not math.isfinite(v):
                    continue  # strings / NaN never reach the exposition
                lines.append(f'{fam}{{node="{node}"}} {v:g}')
        return "\n".join(lines) + "\n"

    def stop(self):
        if self.trace_collector is not None:
            try:
                self.trace_collector.extra_event_sources.remove(
                    self.trace_counter_events)
            except ValueError:
                pass
        self._endpoint.release()
