from geomx_tpu.data.synthetic import synthetic_classification, ShardedIterator  # noqa: F401
