"""Per-finding suppression baseline (``analysis-baseline.toml``).

A baseline entry acknowledges ONE finding (or a small ``fnmatch``
family) as defensible and says WHY — the ``reason`` field is mandatory
and must be a real justification (placeholder reasons like ``TODO`` are
rejected at load time, so a skeleton emitted by ``--baseline`` cannot
be committed unfilled).  Format::

    [[suppress]]
    checker = "reactor-blocking"
    key = "geomx_tpu/kvstore/server.py::GlobalServerLogic._x::send_cmd"
    reason = "runs on a dedicated drain thread spawned by the handler"

The container image pins Python 3.10 (no ``tomllib``), so this module
carries a tiny parser for exactly the subset the file uses: comments,
``[[suppress]]`` array-of-tables headers, and ``key = "string"`` pairs
with standard backslash escapes.  Anything else is a hard error — the
baseline is a reviewed artifact, not a config language.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import pathlib
from typing import Iterable, List, Optional

from geomx_tpu.analysis.core import Finding

DEFAULT_BASELINE = "analysis-baseline.toml"

_PLACEHOLDER_REASONS = ("", "todo", "tbd", "fixme", "xxx")


class BaselineError(ValueError):
    pass


@dataclasses.dataclass
class Suppression:
    checker: str
    key: str          # exact finding key, or an fnmatch pattern
    reason: str
    line: int = 0
    used: int = 0     # findings matched this run

    def matches(self, f: Finding) -> bool:
        if self.checker != f.checker:
            return False
        if self.key == f.key:
            return True
        return ("*" in self.key or "?" in self.key) \
            and fnmatch.fnmatchcase(f.key, self.key)


def _unquote(raw: str, line_no: int) -> str:
    raw = raw.strip()
    if not raw.startswith('"'):
        raise BaselineError(
            f"baseline line {line_no}: value must be a double-quoted "
            f"string, got {raw!r}")
    out: List[str] = []
    i = 1
    closed = False
    while i < len(raw):
        c = raw[i]
        if c == '"':
            closed = True
            i += 1
            break
        if c == "\\":
            i += 1
            if i >= len(raw):
                raise BaselineError(
                    f"baseline line {line_no}: dangling escape")
            esc = raw[i]
            out.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc)
                       or _bad_escape(esc, line_no))
        else:
            out.append(c)
        i += 1
    rest = raw[i:].strip()
    if not closed or (rest and not rest.startswith("#")):
        raise BaselineError(
            f"baseline line {line_no}: malformed string value {raw!r}")
    return "".join(out)


def _bad_escape(esc: str, line_no: int) -> str:
    raise BaselineError(
        f"baseline line {line_no}: unsupported escape \\{esc}")


def parse(text: str) -> List[Suppression]:
    entries: List[Suppression] = []
    current: Optional[dict] = None
    current_line = 0

    def flush():
        nonlocal current
        if current is None:
            return
        missing = [k for k in ("checker", "key", "reason")
                   if k not in current]
        if missing:
            raise BaselineError(
                f"baseline entry at line {current_line} is missing "
                f"{missing}")
        reason = current["reason"].strip()
        if reason.lower().rstrip(":. ") in _PLACEHOLDER_REASONS \
                or len(reason) < 10:
            raise BaselineError(
                f"baseline entry at line {current_line} "
                f"({current['key']}): 'reason' must be a real "
                f"justification, got {reason!r}")
        entries.append(Suppression(current["checker"], current["key"],
                                   reason, current_line))
        current = None

    for no, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppress]]":
            flush()
            current = {}
            current_line = no
            continue
        if "=" in line and current is not None:
            k, _, v = line.partition("=")
            k = k.strip()
            if k not in ("checker", "key", "reason"):
                raise BaselineError(
                    f"baseline line {no}: unknown field {k!r}")
            if k in current:
                raise BaselineError(
                    f"baseline line {no}: duplicate field {k!r}")
            current[k] = _unquote(v, no)
            continue
        raise BaselineError(f"baseline line {no}: cannot parse {raw!r}")
    flush()
    return entries


class Baseline:
    def __init__(self, entries: Iterable[Suppression] = ()):
        self.entries = list(entries)

    @classmethod
    def load(cls, path: pathlib.Path) -> "Baseline":
        path = pathlib.Path(path)
        if not path.exists():
            return cls()
        return cls(parse(path.read_text()))

    def filter(self, findings: List[Finding]
               ) -> tuple[List[Finding], List[Finding]]:
        """Split into (unsuppressed, suppressed)."""
        fresh: List[Finding] = []
        eaten: List[Finding] = []
        for f in findings:
            hit = next((s for s in self.entries if s.matches(f)), None)
            if hit is None:
                fresh.append(f)
            else:
                hit.used += 1
                eaten.append(f)
        return fresh, eaten

    def unused(self) -> List[Suppression]:
        """Entries that matched nothing this run — stale suppressions
        that should be deleted (reported as a warning, not a failure:
        a checker run restricted by --check legitimately skips some)."""
        return [s for s in self.entries if s.used == 0]


def skeleton(findings: List[Finding]) -> str:
    """Render unsuppressed findings as baseline entries for a human to
    justify.  The emitted reason fails validation on purpose."""
    blocks = []
    for f in findings:
        blocks.append(
            "[[suppress]]\n"
            f'checker = "{f.checker}"\n'
            f'key = "{f.key}"\n'
            f'reason = "TODO"  # justify or fix — TODO is rejected\n')
    return "\n".join(blocks)
