"""Dynamic worker join (VERDICT r3 item 7; ref: ADD_NODE runtime id
assignment + node-table broadcast, ps-lite van.cc:41-112).

The build's topology is a static plan (documented divergence), so the
party SERVER owns rank assignment and the aggregation count: a new
worker registers mid-training and is folded into each key's count at
that key's next fresh aggregation round — never mid-round.
"""

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation


def _round(workers, tid, grads):
    for w, g in zip(workers, grads):
        w.push(tid, g)
    outs = [w.pull_sync(tid) for w in workers]
    for w in workers:
        w.wait_all()
    return outs


def test_worker_joins_midtraining_and_count_shifts():
    """Start 2 workers, train, add a third: the server's round count
    shifts to 3 at the next round boundary and training continues with
    all three contributions aggregated."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)

        # round 1: two workers; server applies -lr * sum = -2
        outs = _round(ws, 0, [g, g])
        np.testing.assert_allclose(outs[0], -2.0 * np.ones(4))

        # join a third worker mid-training
        w3 = sim.add_worker(0)
        assert w3.num_workers == 3
        srv = sim.local_servers[0]
        assert srv.joined_workers == 1
        # the joiner initializes its replica (no-op server-side) and
        # pulls current weights before contributing
        w3.init(0, np.zeros(4, np.float32))
        np.testing.assert_allclose(w3.pull_sync(0), -2.0 * np.ones(4))

        # round 2: THREE workers must now complete the round — if the
        # server still counted to 2, the third push would leak into a
        # phantom next round and desync every later pull
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -5.0 * np.ones(4))

        # round 3: still 3
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -8.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_join_mid_round_extends_open_round():
    """A join landing while a round is mid-aggregation EXTENDS that
    round's target: the joiner's first pushes land in whatever round is
    open, and completing it early at the old count would leak a static
    worker's push into the next round (advisor r4).  So the open round
    waits for all three — no contribution is lost or carried over."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)

        # first worker pushes: round is now mid-aggregation (1 of 2)
        ws[0].push(0, g)
        w3 = sim.add_worker(0)  # join lands mid-round -> target 3
        ws[1].push(0, g)        # 2 of 3: round still open
        w3.init(0, np.zeros(4, np.float32))
        w3.push(0, g)           # 3 of 3: completes with everyone
        np.testing.assert_allclose(ws[0].pull_sync(0), -3.0 * np.ones(4))
        for w in ws + [w3]:
            w.wait_all()

        # membership broadcast reached the static workers too: their
        # 1/num_workers gradient pre-scale must track the new size
        assert ws[0].num_workers == 3 and ws[1].num_workers == 3

        # next round counts all three as well
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -6.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_leave_restores_count_and_releases_stalled_round():
    """Graceful leave: the target drops at the boundary, and a round the
    leaver never reached completes without it instead of stalling."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        w3 = sim.add_worker(0)
        w3.init(0, np.zeros(4, np.float32))

        outs = _round(ws + [w3], 0, [g, g, g])  # 3-way round: -3
        np.testing.assert_allclose(outs[0], -3.0 * np.ones(4))

        # the two static workers push the NEXT round (2 of 3) — it
        # stalls until the third contributor's fate resolves
        ws[0].push(0, g)
        ws[1].push(0, g)
        res = w3.leave_party()
        assert res["num_workers"] == 2
        assert sim.local_servers[0].left_workers == 1
        # the leave released the stalled round at count 2
        np.testing.assert_allclose(ws[0].pull_sync(0), -5.0 * np.ones(4))
        for w in ws:
            w.wait_all()

        # subsequent rounds count 2 again
        outs = _round(ws, 0, [g, g])
        np.testing.assert_allclose(outs[0], -7.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_static_plan_worker_can_leave():
    """The membership registry is seeded with the static plan, so a PLAN
    worker's leave lowers the target too (advisor r4: it used to be
    silently treated as a replayed leave, stalling every later round)."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        _round(ws, 0, [g, g])
        res = ws[1].leave_party()
        assert res["num_workers"] == 1
        # worker 0 trains on alone — rounds complete at count 1
        ws[0].push(0, g)
        np.testing.assert_allclose(ws[0].pull_sync(0), -3.0 * np.ones(4))
        ws[0].wait_all()
    finally:
        sim.shutdown()


def test_join_under_wan_compression():
    """Join interplay with the WAN codec path: a joiner folds into a
    party whose push-ups ride BSC — the pull-direction compressor's
    per-subscriber tracked views and the join are independent, so
    training must continue and the WAN must stay compressed."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        compression="bsc"))
    try:
        ws = sim.all_workers()
        rng = np.random.default_rng(0)
        for w in ws:
            w.init(0, np.zeros(4096, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        ws[0].set_gradient_compression({"type": "bsc", "ratio": 0.05})
        g = rng.standard_normal(4096).astype(np.float32)
        _round(ws, 0, [g, g])
        base = sim.wan_bytes()["wan_send_bytes"]

        w3 = sim.add_worker(0)
        w3.init(0, np.zeros(4096, np.float32))
        outs = _round(ws + [w3], 0, [g, g, g])
        # all three replicas agree post-join
        np.testing.assert_allclose(outs[0], outs[2], rtol=1e-5, atol=1e-6)
        # and the WAN hop stayed sparse (well under the dense 2x16KB
        # push+pull a vanilla round would cost)
        sent = sim.wan_bytes()["wan_send_bytes"] - base
        assert sent < 0.5 * (2 * 4096 * 4), sent
    finally:
        sim.shutdown()


def test_join_survives_drop_injection():
    """ADD_NODE is a control message outside the resender; the client
    RPC retries (and the server handler is idempotent by node id), so a
    join must succeed across a lossy fabric and must not double-count
    when a reply — not the request — was the drop."""
    from geomx_tpu.transport.van import FaultPolicy

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        resend_timeout_ms=100),  # recovers dropped DATA traffic; the
        #                          ADD_NODE rpc has its own retry
        fault=FaultPolicy(drop_rate=0.3, seed=7))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        w3 = sim.add_worker(0)  # retries under 30% drop
        assert w3.num_workers == 3
        srv = sim.local_servers[0]
        # idempotency: however many requests got through, ONE member
        assert srv._workers_target == 3, srv._workers_target
        assert srv.joined_workers >= 1
    finally:
        sim.shutdown()


def test_join_rejected_under_intra_ts():
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        enable_intra_ts=True))
    try:
        with pytest.raises(RuntimeError, match="unsupported"):
            sim.add_worker(0)
    finally:
        sim.shutdown()


@pytest.mark.slow
def test_worker_joins_over_real_tcp():
    """Process-level join (the reference's ADD_NODE is inherently
    multi-process, van.cc:41-112): a full TCP topology trains while an
    out-of-plan worker process registers via --join --advertise, trains
    a couple of rounds, and leaves gracefully; everyone exits 0 and the
    server's exit stats show the join+leave."""
    import os
    import re
    import subprocess
    import sys
    import time

    from tests.test_tcp import free_base_port

    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    topo = Topology(num_parties=1, workers_per_party=2)
    base = free_base_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu")

    def spawn(role, extra):
        return subprocess.Popen(
            [sys.executable, "-m", "geomx_tpu.launch", "--role", role,
             "--parties", "1", "--workers", "2",
             "--base-port", str(base)] + extra,
            cwd=cwd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    procs = {str(n): spawn(str(n), ["--steps", "8"])
             for n in topo.all_nodes()}
    # the joiner: out-of-plan rank 2, binds past the plan's ports.
    # Launched immediately — it registers while the static workers are
    # still in jax compile, and runs fewer steps than they do so its
    # rounds are a prefix of theirs (leave covers the rest)
    join_role = "worker:2@p0"
    procs[join_role] = spawn(join_role, [
        "--steps", "2", "--join",
        "--advertise", f"127.0.0.1:{base + 40}"])
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        for r, p in procs.items():
            assert p.returncode == 0, \
                f"{r} rc={p.returncode}: {outputs[r][-1000:]}"
        assert "joined as rank 2" in outputs[join_role], outputs[join_role]
        assert "left cleanly" in outputs[join_role], outputs[join_role]
        srv_out = outputs["server:0@p0"]
        m = re.search(r"joined=(\d+) left=(\d+)", srv_out)
        assert m and m.group(1) == "1" and m.group(2) == "1", srv_out
        for w in ("worker:0@p0", "worker:1@p0"):
            assert "steps=8" in outputs[w], outputs[w][-500:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


def test_joined_worker_trains_a_model():
    """End-to-end: CNN training continues across a join and the loss
    keeps improving with three contributors."""
    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import flatten_params, run_worker

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        x, y = synthetic_classification(n=256, shape=(8, 8, 1), seed=0)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})

        import threading

        hist = {}

        def train(kv, widx, nw, steps):
            it = ShardedIterator(x, y, 16, widx, nw)
            hist[widx] = run_worker(kv, params, grad_fn, it, steps,
                                    barrier_init=False)

        ths = [threading.Thread(target=train, args=(w, i, 2, 3))
               for i, w in enumerate(ws)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        w3 = sim.add_worker(0)
        ths = [threading.Thread(target=train, args=(w, i, 3, 3))
               for i, w in enumerate(ws + [w3])]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(hist[2]) == 3  # the joiner trained full rounds
        losses = [h[0] for h in hist[0]]
        assert np.isfinite(losses).all()
    finally:
        sim.shutdown()
